"""Serve configuration dataclasses.

Reference parity: python/ray/serve/config.py (AutoscalingConfig,
HTTPOptions) and python/ray/serve/schema.py (deployment options). Kept
pydantic-free: plain dataclasses with validation in __post_init__.
"""
from __future__ import annotations

from dataclasses import dataclass, field, asdict
from typing import Any, Dict, Optional


@dataclass
class AutoscalingConfig:
    """Queue-depth-driven autoscaling policy (reference:
    serve/config.py::AutoscalingConfig + autoscaling_policy.py)."""
    min_replicas: int = 1
    initial_replicas: Optional[int] = None
    max_replicas: int = 1
    target_ongoing_requests: float = 2.0
    metrics_interval_s: float = 0.5
    look_back_period_s: float = 5.0
    upscaling_factor: float = 1.0
    downscaling_factor: float = 1.0
    upscale_delay_s: float = 0.5
    downscale_delay_s: float = 2.0
    # step clamp fed into core/autoscaler.py's policy: at most
    # max(1, int(upscaling_speed * current)) new replicas per decision
    upscaling_speed: float = 1.0
    # SLO terms (serve/autoscaler.py): each may only RAISE the desired
    # count computed from the load formula above. None disables a term.
    target_queue_depth: Optional[float] = None   # engine queue / replica
    ttft_slo_ms: Optional[float] = None
    tpot_slo_ms: Optional[float] = None
    kv_util_target: Optional[float] = 0.9        # KV pages in use / pool

    def __post_init__(self):
        if self.min_replicas < 0:
            raise ValueError("min_replicas must be >= 0")
        if self.max_replicas < max(self.min_replicas, 1):
            raise ValueError("max_replicas must be >= min_replicas and >= 1")
        if self.target_ongoing_requests <= 0:
            raise ValueError("target_ongoing_requests must be > 0")

    def desired_replicas(self, total_ongoing: float, current: int) -> int:
        """The reference formula: replicas scaled by load/target ratio."""
        if current == 0:
            return max(self.min_replicas, 1) if total_ongoing > 0 else \
                self.min_replicas
        per_replica = total_ongoing / current
        ratio = per_replica / self.target_ongoing_requests
        if ratio > 1.0:
            desired = current * (1 + (ratio - 1) * self.upscaling_factor)
            import math
            desired = math.ceil(desired)
        elif ratio < 1.0:
            desired = current * (1 - (1 - ratio) * self.downscaling_factor)
            import math
            desired = math.floor(desired) if desired >= self.min_replicas \
                else self.min_replicas
        else:
            desired = current
        return int(min(max(desired, self.min_replicas), self.max_replicas))


@dataclass
class DeploymentConfig:
    """Resolved per-deployment options (reference: serve/schema.py
    DeploymentSchema + serve/api.py::deployment kwargs)."""
    num_replicas: int = 1
    max_ongoing_requests: int = 5
    max_queued_requests: int = -1  # -1 == unbounded
    user_config: Optional[Dict[str, Any]] = None
    autoscaling_config: Optional[AutoscalingConfig] = None
    graceful_shutdown_timeout_s: float = 5.0
    health_check_period_s: float = 1.0
    health_check_timeout_s: float = 5.0
    health_check_failure_threshold: int = 3
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)
    # when set (PACK/SPREAD/STRICT_PACK/STRICT_SPREAD), autoscale-ups
    # reserve a placement group with one bundle per new replica before
    # starting them (multi-host capable placement)
    placement_group_strategy: Optional[str] = None

    def __post_init__(self):
        if isinstance(self.autoscaling_config, dict):
            self.autoscaling_config = AutoscalingConfig(
                **self.autoscaling_config)
        if self.num_replicas is not None and self.num_replicas < 1:
            raise ValueError("num_replicas must be >= 1")
        if self.placement_group_strategy is not None:
            from ..util.placement_group import VALID_STRATEGIES
            if self.placement_group_strategy not in VALID_STRATEGIES:
                raise ValueError(
                    f"placement_group_strategy must be one of "
                    f"{VALID_STRATEGIES}")

    def to_dict(self) -> Dict[str, Any]:
        return asdict(self)


def default_request_timeout_s() -> float:
    """Per-request budget when the client supplies no deadline (HTTP
    X-Serve-Timeout-S header / gRPC deadline). Shared by both ingress
    proxies; replaces the old hardcoded 60s unary timeout."""
    from ..util import knobs
    return knobs.get_float("RAY_TPU_SERVE_REQUEST_TIMEOUT_S")


@dataclass
class HTTPOptions:
    """Reference: serve/config.py::HTTPOptions (host/port/root_path)."""
    host: str = "127.0.0.1"
    port: int = 8000
    root_path: str = ""


@dataclass
class ReplicaInfo:
    """Controller-side record of one running replica."""
    replica_id: str
    deployment_name: str
    app_name: str
    version: str
    actor_handle: Any = None
    state: str = "STARTING"  # STARTING | RUNNING | STOPPING | DEAD
    start_ref: Any = None    # ObjectRef of the readiness probe
    # active health probing (controller reconcile loop)
    health_ref: Any = None       # outstanding health_check ObjectRef
    last_probe_ts: float = 0.0   # when the last probe was dispatched
    health_failures: int = 0     # consecutive probe failures
    # graceful drain (rolling update / scale-down / shutdown)
    draining_since: float = 0.0  # 0 = not draining
    drain_ref: Any = None        # outstanding ongoing-count ObjectRef
    # live autoscale metrics (controller reconcile loop; non-blocking)
    metrics_ref: Any = None      # outstanding get_autoscale_metrics ref
    metrics_dispatch_ts: float = 0.0
    last_metrics: Optional[Dict[str, Any]] = None
    # placement-group reservation this replica was started into
    pg_id: Optional[str] = None
