"""Replica actor: hosts one copy of a deployment's callable.

Reference parity: python/ray/serve/_private/replica.py (request handling,
ongoing-request accounting, health checks, reconfigure, streaming) —
re-shaped for the ray_tpu runtime: one actor per replica, async
`handle_request` running on the worker's persistent asyncio loop, and a
poll-based streaming protocol (`stream_next`) instead of gRPC streams.
"""
from __future__ import annotations

import asyncio
import inspect
import itertools
import queue as queue_mod
import threading
import time
from typing import Any, Dict, Optional

from ..exceptions import DeadlineExceededError, ReplicaDrainingError

_STREAM_END = "__ray_tpu_stream_end__"


class _StreamCancelled(BaseException):
    """Internal: consumer abandoned the stream; stop the drain task.
    BaseException so a handler's own `except Exception` can't eat it."""


class Replica:
    """The actor class the controller instantiates per replica.

    Wraps either a user class (instantiated with init args) or a plain
    function. All requests land on `handle_request`; generators/async
    generators are exposed through `stream_start`/`stream_next` so HTTP
    proxies and handles can pull token-by-token.
    """

    def __init__(self, deployment_name: str, replica_id: str,
                 callable_bytes: bytes, init_args, init_kwargs,
                 user_config: Optional[Dict[str, Any]] = None,
                 max_ongoing_requests: int = 5):
        from .. import core  # noqa: F401  (ensures runtime symbols loaded)
        from ..core import serialization
        self._deployment_name = deployment_name
        self._replica_id = replica_id
        self._max_ongoing = max_ongoing_requests
        self._ongoing = 0
        self._total_served = 0
        self._lock = threading.Lock()
        self._draining = False
        # chaos-injection state (serve/chaos.py): deterministic fault
        # modes for the fault-tolerance tests; all default off
        self._chaos_delay_s = 0.0
        self._chaos_health_mode = ""   # "" | "fail" | "hang" | "wedged"
        self._streams: Dict[str, queue_mod.Queue] = {}
        self._stream_counter = itertools.count()
        # stream ids whose consumer hung up: _drain stops pumping (and
        # the parked _put unblocks) instead of leaking the queue and a
        # permanently-elevated _ongoing count
        self._cancelled_streams: set = set()
        # stream ids whose drain task is still pumping: stream_cancel
        # only flags these — flagging a FINISHED drain would leave the
        # id in _cancelled_streams forever (its finally-discard already
        # ran), an unbounded leak under abandon-after-completion traffic
        self._live_drains: set = set()

        target = serialization.loads_call(callable_bytes)
        if inspect.isclass(target):
            self._callable = target(*init_args, **init_kwargs)
            self._is_function = False
        else:
            self._callable = target
            self._is_function = True
        if user_config is not None:
            self.reconfigure(user_config)

    # ---- lifecycle --------------------------------------------------------
    def ready(self) -> str:
        """Readiness probe: returns once __init__ (and any model load in
        the user ctor) has completed."""
        return self._replica_id

    def health_check(self) -> bool:
        if self._chaos_health_mode == "hang":
            time.sleep(3600)           # probe times out controller-side
        if self._chaos_health_mode == "fail":
            raise RuntimeError("chaos: health check failing")
        if self._chaos_health_mode == "wedged":
            from ..exceptions import EngineWedgedError
            raise EngineWedgedError("chaos: wedged")
        user_check = getattr(self._callable, "check_health", None)
        if user_check is not None:
            user_check()
        return True

    def reconfigure(self, user_config: Dict[str, Any]) -> None:
        fn = getattr(self._callable, "reconfigure", None)
        if fn is not None:
            fn(user_config)

    def prepare_for_shutdown(self) -> int:
        """Graceful drain: stop admitting new requests (they raise the
        retriable ReplicaDrainingError and fail over) and report the
        in-flight count so the controller can wait for it to hit zero.
        Counts BOTH running handlers (_ongoing) and streams whose
        consumer is still pulling buffered chunks (_streams keeps the
        id until the consumer reads the end marker or cancels) —
        _ongoing alone drops when the PRODUCER finishes, which would
        let the controller kill us mid-consumer-read. Idempotent; the
        controller re-calls it as its drain poll."""
        with self._lock:
            self._draining = True
            return self._ongoing + len(self._streams)

    def chaos(self, mode: str, seconds: float = 0.0) -> bool:
        """Deterministic fault injection (serve/chaos.py; tests only).
        Modes: "delay" (every request sleeps `seconds` first),
        "health_fail" / "health_hang" / "health_wedged" (health probe
        fails / blocks / raises EngineWedgedError), "wedge" (stall the
        hosted LLM engine's loop for `seconds` — real watchdog path),
        "die" (hard-exit the replica process), "reset" (clear all)."""
        if mode == "delay":
            self._chaos_delay_s = float(seconds)
        elif mode in ("health_fail", "health_hang", "health_wedged"):
            self._chaos_health_mode = mode.split("_", 1)[1]
        elif mode == "wedge":
            engine = getattr(self._callable, "engine", None)
            if engine is None:
                raise ValueError("replica hosts no LLM engine to wedge")
            engine._chaos_stall(float(seconds))
        elif mode == "die":
            import os
            os._exit(1)
        elif mode == "reset":
            self._chaos_delay_s = 0.0
            self._chaos_health_mode = ""
        else:
            raise ValueError(f"unknown chaos mode {mode!r}")
        return True

    def _admit(self, kwargs) -> Optional[float]:
        """Shared admission gate for unary + stream paths: reject while
        draining (retriable — the handle fails over), shed requests
        whose propagated deadline already expired, and apply the chaos
        delay. Returns the request's absolute deadline (or None)."""
        deadline_ts = kwargs.pop("__serve_deadline_ts", None)
        if self._draining:
            raise ReplicaDrainingError(
                f"replica {self._replica_id} is draining")
        if deadline_ts is not None and time.time() >= deadline_ts:
            self._shed("deadline_expired")
            raise DeadlineExceededError(
                f"deadline expired {time.time() - deadline_ts:.3f}s "
                f"before admission on {self._replica_id}")
        if self._chaos_delay_s > 0:
            time.sleep(self._chaos_delay_s)
        return deadline_ts

    def _shed(self, reason: str) -> None:
        from ..util import events as events_mod
        events_mod.emit_safe("serve.request.shed",
                             counter="ray_tpu_serve_requests_shed_total",
                             counter_tags={"reason": reason},
                             replica_id=self._replica_id,
                             deployment=self._deployment_name,
                             reason=reason)

    def shutdown_user_callable(self) -> None:
        fn = getattr(self._callable, "__del__", None)
        del fn  # user __del__ runs when the process exits; nothing to do

    # ---- metrics ----------------------------------------------------------
    def get_metrics(self) -> Dict[str, Any]:
        with self._lock:
            return {"replica_id": self._replica_id,
                    "ongoing": self._ongoing,
                    "total": self._total_served,
                    "max_ongoing": self._max_ongoing}

    def get_queue_len(self) -> int:
        with self._lock:
            return self._ongoing

    def get_autoscale_metrics(self) -> Dict[str, Any]:
        """Live load sample for the controller's autoscaler/scale-down
        victim selection: in-flight handlers + undrained streams, plus
        whatever the hosted callable exposes via an `autoscale_metrics`
        hook (LLMServer reports engine queue depth, TTFT/TPOT, and
        KV-page utilization through it)."""
        with self._lock:
            out: Dict[str, Any] = {"replica_id": self._replica_id,
                                   "ongoing": self._ongoing,
                                   "streams": len(self._streams),
                                   "total": self._total_served,
                                   "ts": time.time()}
        hook = getattr(self._callable, "autoscale_metrics", None)
        if callable(hook):
            try:
                engine = hook()
                if isinstance(engine, dict):
                    out["engine"] = engine
            except Exception:  # noqa: BLE001  telemetry must not fail
                pass
        return out

    # ---- request path -----------------------------------------------------
    def _resolve_method(self, method_name: str):
        if self._is_function:
            if method_name not in ("__call__", None):
                raise AttributeError(
                    f"function deployment has no method {method_name!r}")
            return self._callable
        return getattr(self._callable, method_name or "__call__")

    async def handle_request(self, method_name: str, args, kwargs) -> Any:
        """Unary request. Runs user coroutines on the worker loop; sync
        handlers run in the default executor so they don't block the loop
        (and so max_ongoing_requests > 1 gives real concurrency)."""
        deadline_ts = self._admit(kwargs)
        with self._lock:
            self._ongoing += 1
        try:
            mux_id = kwargs.pop("__serve_multiplexed_model_id", "")
            from .context import _set_request_deadline
            from .multiplex import _set_multiplexed_model_id
            method = self._resolve_method(method_name)
            if inspect.iscoroutinefunction(method):
                if mux_id:
                    _set_multiplexed_model_id(mux_id)
                _set_request_deadline(deadline_ts)
                result = await method(*args, **kwargs)
            else:
                def _call_sync():
                    # contextvar set inside the executor thread: plain
                    # run_in_executor does not propagate context.
                    if mux_id:
                        _set_multiplexed_model_id(mux_id)
                    _set_request_deadline(deadline_ts)
                    return method(*args, **kwargs)
                loop = asyncio.get_running_loop()
                result = await loop.run_in_executor(None, _call_sync)
                if inspect.iscoroutine(result):
                    result = await result
            if inspect.isgenerator(result) or inspect.isasyncgen(result):
                raise TypeError(
                    "handler returned a generator; call it via the "
                    "streaming path (handle.options(stream=True))")
            return result
        finally:
            with self._lock:
                self._ongoing -= 1
                self._total_served += 1

    # ---- streaming path ---------------------------------------------------
    async def stream_start(self, method_name: str, args, kwargs) -> str:
        """Start a streaming call; returns a stream id to poll with
        stream_next(). The generator is drained on a background task and
        chunks buffered, so slow consumers don't stall the handler."""
        deadline_ts = self._admit(kwargs)
        stream_id = f"{self._replica_id}-s{next(self._stream_counter)}"
        q: queue_mod.Queue = queue_mod.Queue(maxsize=1024)
        self._streams[stream_id] = q
        with self._lock:
            self._ongoing += 1
        mux_id = kwargs.pop("__serve_multiplexed_model_id", "")
        from .context import _set_request_deadline
        from .multiplex import _set_multiplexed_model_id
        if mux_id:
            _set_multiplexed_model_id(mux_id)
        _set_request_deadline(deadline_ts)
        method = self._resolve_method(method_name)

        async def _put(item):
            # never block the event loop: the queue is bounded, so park
            # in short async sleeps when a slow consumer falls behind.
            while True:
                if stream_id in self._cancelled_streams:
                    raise _StreamCancelled()
                try:
                    q.put_nowait(item)
                    return
                except queue_mod.Full:
                    await asyncio.sleep(0.01)

        def _next_with_ctx(it):
            # executor threads don't inherit the loop's contextvars; a
            # sync generator reading get_multiplexed_model_id() needs the
            # var set in the thread actually running its frames.
            if mux_id:
                _set_multiplexed_model_id(mux_id)
            _set_request_deadline(deadline_ts)
            return next(it, _STREAM_END)

        async def _drain():
            try:
                result = method(*args, **kwargs)
                if inspect.iscoroutine(result):
                    result = await result
                if inspect.isasyncgen(result):
                    async for chunk in result:
                        await _put(("chunk", chunk))
                elif inspect.isgenerator(result):
                    loop = asyncio.get_running_loop()
                    it = iter(result)
                    while True:
                        chunk = await loop.run_in_executor(
                            None, _next_with_ctx, it)
                        if chunk == _STREAM_END:
                            break
                        await _put(("chunk", chunk))
                else:  # unary result streamed as a single chunk
                    await _put(("chunk", result))
                await _put(("end", None))
            except _StreamCancelled:
                pass               # consumer gone: just stop pumping
            except BaseException as e:  # noqa: BLE001
                try:
                    await _put(("error", e))
                except _StreamCancelled:
                    pass
            finally:
                with self._lock:
                    # same lock as stream_cancel's check-then-add: the
                    # cancel path runs on a threadpool thread while this
                    # finally runs on the asyncio loop thread — unlocked
                    # interleaving could add the id AFTER this discard,
                    # leaking it forever
                    self._live_drains.discard(stream_id)
                    self._cancelled_streams.discard(stream_id)
                    self._ongoing -= 1
                    self._total_served += 1

        self._live_drains.add(stream_id)
        asyncio.ensure_future(_drain())
        return stream_id

    def stream_cancel(self, stream_id: str) -> bool:
        """Consumer abandoned the stream (client hung up): stop the
        drain task and drop the buffer. Idempotent; unknown/finished
        ids are a no-op."""
        if stream_id in self._streams:
            with self._lock:
                if stream_id in self._live_drains:
                    # only a still-running drain needs the flag (its
                    # finally-discard cleans it up); a finished drain
                    # would never remove it — leak
                    self._cancelled_streams.add(stream_id)
            self._streams.pop(stream_id, None)
            return True
        return False

    def stream_next(self, stream_id: str, batch: int = 64,
                    timeout_s: float = 30.0):
        """Pull up to `batch` buffered chunks. Returns (chunks, done).
        Raises the handler's exception if the stream errored."""
        q = self._streams.get(stream_id)
        if q is None:
            return [], True
        chunks = []
        done = False
        from ..util import waits as waits_mod  # noqa: PLC0415
        wtok = waits_mod.park("serve-stream", stream_id,
                              pending=q.qsize())
        try:
            try:
                kind, payload = q.get(timeout=timeout_s)
            finally:
                waits_mod.unpark(wtok)
            while True:
                if kind == "chunk":
                    chunks.append(payload)
                elif kind == "end":
                    done = True
                    break
                elif kind == "error":
                    self._streams.pop(stream_id, None)
                    raise payload
                if len(chunks) >= batch:
                    break
                try:
                    kind, payload = q.get_nowait()
                except queue_mod.Empty:
                    break
        except queue_mod.Empty:
            pass
        if done:
            self._streams.pop(stream_id, None)
        return chunks, done
