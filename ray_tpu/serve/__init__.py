"""ray_tpu.serve — scalable model serving (reference: python/ray/serve).

Control plane: a controller actor reconciles replica actors per
deployment (health checks, autoscaling, rolling updates). Data plane:
client-side power-of-two-choices routing straight to replica actors, a
stdlib HTTP ingress, @batch coalescing (keeps the MXU fed), @multiplexed
model caches, and a JAX continuous-batching LLM engine (serve.llm).
"""
from __future__ import annotations

import importlib

from ..exceptions import (DeadlineExceededError, EngineWedgedError,
                          NoCapacityError, ReplicaDrainingError,
                          StreamInterruptedError)
from .api import (run, start, status, delete, shutdown, get_app_handle,
                  get_deployment_handle, register_prefix)
from .asgi import ingress
from .batching import batch
from .config import AutoscalingConfig, DeploymentConfig, HTTPOptions
from .context import get_request_deadline, remaining_budget
from .deployment import Application, Deployment, deployment_decorator
from .handle import (BackPressureError, DeploymentHandle,
                     DeploymentResponse, DeploymentResponseGenerator)
from .multiplex import get_multiplexed_model_id, multiplexed

deployment = deployment_decorator


def __getattr__(name):
    if name in ("llm", "chaos", "router", "autoscaler"):
        mod = importlib.import_module("." + name, __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'ray_tpu.serve' has no attribute {name!r}")


__all__ = [
    "run", "start", "status", "delete", "shutdown", "get_app_handle",
    "get_deployment_handle", "ingress", "batch", "AutoscalingConfig",
    "DeploymentConfig", "HTTPOptions", "Application", "Deployment",
    "deployment", "DeploymentHandle", "DeploymentResponse",
    "DeploymentResponseGenerator", "BackPressureError",
    "NoCapacityError", "DeadlineExceededError", "EngineWedgedError",
    "ReplicaDrainingError", "StreamInterruptedError",
    "get_request_deadline", "remaining_budget",
    "get_multiplexed_model_id", "multiplexed", "llm", "chaos",
    "register_prefix", "router", "autoscaler",
]
