"""Deployment & Application graph objects.

Reference parity: python/ray/serve/deployment.py (Deployment, .bind,
.options) + serve/dag.py (the bound-application graph). `.bind()` captures
init args — which may themselves be bound sub-deployments; `serve.run`
walks the graph, deploys every node, and wires DeploymentHandles in place
of the bound children (reference: _private/build_app.py).
"""
from __future__ import annotations

import hashlib
from typing import Any, Dict, Optional, Tuple

from ..core import serialization
from .config import AutoscalingConfig, DeploymentConfig


class Application:
    """A deployment bound with its init args (possibly nested apps)."""

    def __init__(self, deployment: "Deployment", args: Tuple, kwargs: Dict):
        self._deployment = deployment
        self._args = args
        self._kwargs = kwargs

    @property
    def deployment(self) -> "Deployment":
        return self._deployment


class Deployment:
    def __init__(self, target, name: str,
                 config: Optional[DeploymentConfig] = None,
                 version: Optional[str] = None,
                 route_prefix: Optional[str] = "/"):
        self._target = target
        self._name = name
        self._config = config or DeploymentConfig()
        self._version = version
        self._route_prefix = route_prefix
        self._target_bytes: Optional[bytes] = None

    @property
    def name(self) -> str:
        return self._name

    @property
    def config(self) -> DeploymentConfig:
        return self._config

    @property
    def route_prefix(self) -> Optional[str]:
        return self._route_prefix

    @property
    def is_asgi(self) -> bool:
        """True when the callable was wrapped by serve.ingress(app) —
        the HTTP proxy then ships raw requests instead of JSON bodies."""
        from .asgi import ASGI_ATTR  # noqa: PLC0415
        return bool(getattr(self._target, ASGI_ATTR, False))

    def options(self, *, name: Optional[str] = None,
                num_replicas: Optional[Any] = None,
                max_ongoing_requests: Optional[int] = None,
                max_queued_requests: Optional[int] = None,
                user_config: Optional[dict] = None,
                autoscaling_config: Optional[Any] = None,
                version: Optional[str] = None,
                route_prefix: Optional[str] = "__unset__",
                health_check_period_s: Optional[float] = None,
                health_check_timeout_s: Optional[float] = None,
                health_check_failure_threshold: Optional[int] = None,
                graceful_shutdown_timeout_s: Optional[float] = None,
                ray_actor_options: Optional[dict] = None,
                placement_group_strategy: Optional[str] = "__unset__",
                ) -> "Deployment":
        cfg = DeploymentConfig(**self._config.to_dict())
        if num_replicas == "auto":
            if autoscaling_config is None:
                autoscaling_config = AutoscalingConfig(
                    min_replicas=1, max_replicas=100,
                    target_ongoing_requests=2.0)
            num_replicas = None
        if num_replicas is not None:
            cfg.num_replicas = num_replicas
        if max_ongoing_requests is not None:
            cfg.max_ongoing_requests = max_ongoing_requests
        if max_queued_requests is not None:
            cfg.max_queued_requests = max_queued_requests
        if user_config is not None:
            cfg.user_config = user_config
        if autoscaling_config is not None:
            cfg.autoscaling_config = (
                autoscaling_config if isinstance(
                    autoscaling_config, AutoscalingConfig)
                else AutoscalingConfig(**autoscaling_config))
        if health_check_period_s is not None:
            cfg.health_check_period_s = health_check_period_s
        if health_check_timeout_s is not None:
            cfg.health_check_timeout_s = health_check_timeout_s
        if health_check_failure_threshold is not None:
            cfg.health_check_failure_threshold = \
                health_check_failure_threshold
        if graceful_shutdown_timeout_s is not None:
            cfg.graceful_shutdown_timeout_s = graceful_shutdown_timeout_s
        if ray_actor_options is not None:
            cfg.ray_actor_options = ray_actor_options
        if placement_group_strategy != "__unset__":
            cfg.placement_group_strategy = placement_group_strategy
        return Deployment(
            self._target, name or self._name, cfg,
            version if version is not None else self._version,
            self._route_prefix if route_prefix == "__unset__"
            else route_prefix)

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)

    # ---- controller payload ----------------------------------------------
    def callable_bytes(self) -> bytes:
        if self._target_bytes is None:
            self._target_bytes = serialization.dumps_call(self._target)
        return self._target_bytes

    def version_hash(self) -> str:
        """Code+config identity; a change triggers rolling replacement
        (reference: serve/_private/version.py::DeploymentVersion)."""
        h = hashlib.sha1()
        h.update(self.callable_bytes())
        h.update(repr(sorted((self._config.user_config or {}).items()))
                 .encode())
        if self._version:
            h.update(self._version.encode())
        return h.hexdigest()[:16]


def deployment_decorator(target=None, *, name: Optional[str] = None,
                         num_replicas=None, max_ongoing_requests=None,
                         max_queued_requests=None, user_config=None,
                         autoscaling_config=None, version=None,
                         route_prefix="/", health_check_period_s=None,
                         health_check_timeout_s=None,
                         health_check_failure_threshold=None,
                         graceful_shutdown_timeout_s=None,
                         ray_actor_options=None,
                         placement_group_strategy="__unset__", **_compat):
    """@serve.deployment — wraps a class or function into a Deployment."""

    def wrap(t):
        d = Deployment(t, name or t.__name__, route_prefix=route_prefix)
        return d.options(
            num_replicas=num_replicas,
            max_ongoing_requests=max_ongoing_requests,
            max_queued_requests=max_queued_requests,
            user_config=user_config, autoscaling_config=autoscaling_config,
            version=version,
            health_check_period_s=health_check_period_s,
            health_check_timeout_s=health_check_timeout_s,
            health_check_failure_threshold=health_check_failure_threshold,
            graceful_shutdown_timeout_s=graceful_shutdown_timeout_s,
            ray_actor_options=ray_actor_options,
            placement_group_strategy=placement_group_strategy)

    if target is not None:  # bare @serve.deployment
        return wrap(target)
    return wrap
