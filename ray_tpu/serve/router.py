"""Scale-out request router: load-aware replica selection with
session/prefix affinity.

Reference counterpart: python/ray/serve/_private/replica_scheduler/
pow_2_scheduler.py (least-loaded power-of-two-choices) plus the
consistent-hash-with-bounded-load scheme from "Consistent Hashing with
Bounded Loads" (Mirrokni et al.) that fronting LLM routers use to keep
shared-prompt traffic on a warm KV prefix cache.

Two cooperating policies, both stateless across processes:

* **Least-loaded p2c** — the default for keyless traffic: sample two
  replicas that still have request slots and take the one with fewer
  in-flight requests. Used by every `DeploymentHandle` (proxies
  included).
* **Affinity** — requests carrying an affinity key (an explicit
  `__serve_affinity_key` kwarg, a `session_id`/`user` field in a dict
  body, or a prompt that starts with a controller-registered prefix)
  are sticky-routed. The preferred replica is the key's previous
  binding, else its consistent-hash ring owner — the SAME deterministic
  ring the controller uses to pick which replica to pre-warm with a
  registered prefix, so the first request of a prefix-keyed session
  already lands on a warm KV cache. A preferred replica that is
  suspect, draining, or above the bounded-load cap is skipped (the key
  re-binds elsewhere — a cold prefill, never an error), which preserves
  the PR-5 failover guarantees.

The ring is derived from the RUNNING replica-id set only — every
handle, every proxy, and the controller compute identical ownership
without coordination.
"""
from __future__ import annotations

import bisect
import collections
import hashlib
import math
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..util import knobs

# virtual points per replica on the hash ring: enough to spread keys
# evenly across small replica sets without making ring builds costly
_VNODES = 64
# bounded-load factor c: a preferred replica is skipped when its load
# exceeds c * (average load + 1). c=2 tolerates bursty sessions while
# still shedding a pathological hot key onto the rest of the fleet.
_BOUND_FACTOR = knobs.get_float("RAY_TPU_SERVE_AFFINITY_BOUND")
# bindings kept per handle (LRU); beyond this the oldest sessions
# silently fall back to ring ownership (which is where they were bound
# anyway unless they were diverted)
_SESSION_CAP = knobs.get_int("RAY_TPU_SERVE_AFFINITY_SESSIONS")


def _hash64(s: str) -> int:
    """Stable cross-process 64-bit hash (builtin hash() is salted)."""
    return int.from_bytes(
        hashlib.md5(s.encode("utf-8", "surrogatepass")).digest()[:8],
        "big")


# ring points are a pure function of the replica-id set — cache them
# so the routing hot path pays one md5 + a binary search per request
# instead of rebuilding and sorting replicas x _VNODES points
_RING_CACHE_CAP = 32
_ring_cache: "collections.OrderedDict[tuple, List[Tuple[int, str]]]" = \
    collections.OrderedDict()
_ring_cache_lock = threading.Lock()


def _ring_points(replica_ids: Sequence[str],
                 vnodes: int) -> List[Tuple[int, str]]:
    cache_key = (tuple(sorted(set(replica_ids))), vnodes)
    with _ring_cache_lock:
        points = _ring_cache.get(cache_key)
        if points is not None:
            _ring_cache.move_to_end(cache_key)
            return points
    points = sorted(
        (_hash64(f"{rid}#{v}"), rid)
        for rid in cache_key[0] for v in range(vnodes))
    with _ring_cache_lock:
        _ring_cache[cache_key] = points
        while len(_ring_cache) > _RING_CACHE_CAP:
            _ring_cache.popitem(last=False)
    return points


def ring_order(key: str, replica_ids: Sequence[str],
               vnodes: int = _VNODES) -> List[str]:
    """Replica ids in consistent-hash preference order for `key`.

    Deterministic in (key, replica-id set): handles, proxies, and the
    controller all agree on the owner (the first entry) without talking
    to each other. Adding/removing one replica remaps only the keys it
    owned — established sessions elsewhere keep their replica.
    """
    if not replica_ids:
        return []
    points = _ring_points(replica_ids, vnodes)
    n_distinct = len(set(replica_ids))
    idx = bisect.bisect_left(points, (_hash64(key), ""))
    order: List[str] = []
    seen = set()
    for i in range(len(points)):
        rid = points[(idx + i) % len(points)][1]
        if rid not in seen:
            seen.add(rid)
            order.append(rid)
            if len(order) == n_distinct:
                break
    return order


def ring_owner(key: str, replica_ids: Sequence[str]) -> Optional[str]:
    """The replica that owns `key` on the ring (None when empty)."""
    order = ring_order(key, replica_ids)
    return order[0] if order else None


def extract_affinity_key(args: tuple,
                         registered_prefixes: Sequence[dict]
                         ) -> Optional[str]:
    """Affinity key from a request body (first positional arg when it
    is a dict): an explicit session id, else the key of the longest
    controller-registered prompt prefix the prompt starts with."""
    if not args or not isinstance(args[0], dict):
        return None
    body = args[0]
    sid = body.get("session_id") or body.get("user")
    if sid:
        return str(sid)
    prompt = body.get("prompt")
    if prompt is None or not registered_prefixes:
        return None
    best_key, best_len = None, -1
    for row in registered_prefixes:
        pfx = row.get("prefix")
        try:
            if isinstance(prompt, str) and isinstance(pfx, str):
                ok = prompt.startswith(pfx)
                n = len(pfx)
            elif not isinstance(prompt, str) and not isinstance(pfx, str):
                p = list(pfx)
                n = len(p)
                ok = len(prompt) > n and list(prompt[:n]) == p
            else:
                continue   # mixed str/token forms cannot match
        except TypeError:
            continue
        if ok and n > best_len:
            best_key, best_len = row.get("key"), n
    return best_key


def prefix_key(prefix) -> str:
    """Canonical key for a registered prefix payload (shared by the
    controller registry and callers that precompute keys)."""
    if isinstance(prefix, str):
        raw = prefix.encode()
    else:
        raw = repr([int(t) for t in prefix]).encode()
    return "pfx-" + hashlib.sha1(raw).hexdigest()[:12]


class AffinityRouter:
    """Sticky routing state for one (app, deployment) handle.

    `pick` returns the replica a keyed request should go to, or None
    when every affinity-preferred replica is over the bounded-load cap
    (the caller falls back to least-loaded p2c). Bindings live in a
    bounded LRU; hit/miss telemetry is emitted here so every routing
    surface (handles, both proxies) counts identically. Caller holds
    the router-state lock.
    """

    _NOTE_CAP = 64

    def __init__(self, deployment: str = "", app: str = "default"):
        self.deployment = deployment
        self.app = app
        self.bindings: "collections.OrderedDict[str, str]" = \
            collections.OrderedDict()
        self.hits = 0
        self.misses = 0
        # binding transitions awaiting delivery to the controller's
        # router table: (key, replica_id, outcome). Appended under the
        # caller's lock, drained by DeploymentHandle AFTER it releases
        # the lock — notification is a driver/controller round trip
        # and must never run on the locked routing path.
        self.pending_notes: List[tuple] = []

    # ---- policy -----------------------------------------------------------
    def _bound(self, loads: Dict[str, int], max_ongoing: int) -> int:
        """Bounded-load cap: c * (mean load + 1), never above the
        per-replica max_ongoing_requests slot count."""
        if not loads:
            return max_ongoing
        mean = sum(loads.values()) / len(loads)
        cap = max(1, math.ceil(_BOUND_FACTOR * (mean + 1.0)))
        return min(cap, max_ongoing) if max_ongoing > 0 else cap

    def pick(self, key: str, candidates: List[tuple],
             load: Callable[[str], int], max_ongoing: int
             ) -> Optional[tuple]:
        """Choose a candidate for an affinity-keyed request.

        Preference order: the key's current binding, then consistent-
        hash ring order. The first preference under the bounded-load
        cap wins; staying on the bound replica is a *hit*, landing
        anywhere else re-binds the key (*miss* — its KV prefix must be
        re-warmed there). Returns None when nothing is under the cap.
        """
        by_id = {c[0]: c for c in candidates}
        ids = list(by_id)
        loads = {rid: load(rid) for rid in ids}
        cap = self._bound(loads, max_ongoing)
        bound = self.bindings.get(key)
        prefs: List[str] = []
        if bound in by_id:
            prefs.append(bound)
        prefs.extend(r for r in ring_order(key, ids) if r not in prefs)
        for rid in prefs:
            if loads[rid] >= cap:
                continue
            # staying on the binding is a hit; a fresh key landing on
            # its ring owner is too (that's where a registered prefix
            # was pre-warmed by the controller)
            hit = rid == (bound if bound is not None else prefs[0])
            self._record(key, rid, hit=hit)
            return by_id[rid]
        return None

    # ---- bookkeeping / telemetry ------------------------------------------
    def _record(self, key: str, rid: str, hit: bool) -> None:
        from ..util import events as events_mod
        prev = self.bindings.get(key)
        rebind = prev != rid
        self.bindings[key] = rid
        self.bindings.move_to_end(key)
        while len(self.bindings) > _SESSION_CAP:
            self.bindings.popitem(last=False)
        if hit:
            self.hits += 1
        else:
            self.misses += 1
        outcome = "affinity_hit" if hit else "affinity_miss"
        # counters every request; events only at binding transitions
        # (first hit of a fresh binding / every divert-rebind) so the
        # event plane sees routing *changes*, not per-request noise
        events_mod.emit_safe(
            ("serve.router.affinity_hit" if hit and rebind else
             "serve.router.affinity_miss" if not hit else None),
            f"key {key[:64]!r} -> {rid}"
            + (f" (was {prev})" if rebind and prev else ""),
            counter="ray_tpu_serve_router_requests_total",
            counter_tags={"deployment": self.deployment,
                          "outcome": outcome},
            deployment=self.deployment, app=self.app,
            affinity_key=str(key)[:128], replica_id=rid,
            previous=prev if rebind else None)
        try:
            from ..util import metrics_catalog as mcat
            mcat.get("ray_tpu_serve_router_sessions").set(
                float(len(self.bindings)),
                tags={"deployment": self.deployment})
        except Exception:  # noqa: BLE001  telemetry never fails routing
            pass
        if rebind and len(self.pending_notes) < self._NOTE_CAP:
            self.pending_notes.append((key, rid, outcome))

    def take_notes(self) -> List[tuple]:
        """Drain queued binding transitions (caller holds the lock)."""
        notes, self.pending_notes = self.pending_notes, []
        return notes

    def forget(self, replica_id: str) -> None:
        """Drop every binding to a replica that just failed — the next
        request per key re-binds (and re-warms) elsewhere."""
        for k in [k for k, v in self.bindings.items() if v == replica_id]:
            del self.bindings[k]

    def snapshot(self) -> Dict:
        return {"deployment": self.deployment, "app": self.app,
                "bindings": dict(self.bindings),
                "hits": self.hits, "misses": self.misses,
                "ts": time.time()}


def pick_least_loaded(candidates: List[tuple],
                      load: Callable[[str], int],
                      max_ongoing: int) -> Optional[tuple]:
    """Power-of-two-choices over in-flight counts, restricted to
    replicas that still have request slots. Returns None when every
    replica is saturated (caller backs off and re-polls).

    Replaces the old "sample 2 of everything, then check the winner's
    cap" scan: that version could sample two saturated replicas while a
    free one sat idle, burning a backoff round per miss (replica
    hot-spotting under skewed load).
    """
    import random
    if len(candidates) == 1:           # hot path: single replica
        c = candidates[0]
        return c if max_ongoing <= 0 or load(c[0]) < max_ongoing \
            else None
    open_c = [c for c in candidates
              if max_ongoing <= 0 or load(c[0]) < max_ongoing]
    if not open_c:
        return None
    if len(open_c) == 1:
        return open_c[0]
    a, b = random.sample(open_c, 2)
    return a if load(a[0]) <= load(b[0]) else b


__all__ = ["AffinityRouter", "ring_order", "ring_owner",
           "extract_affinity_key", "prefix_key", "pick_least_loaded"]
