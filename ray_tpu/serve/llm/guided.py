"""Guided (constrained) decoding: per-step vocab masks from an FSM.

Reference parity: the vLLM-class serving path the fork targets
(BASELINE.json north star) supports guided/structured output — outlines-
style regex + choice constraints compiled to a token-level automaton.
TPU-first design: the automaton lives on the HOST and emits a static
(V,)-bool allowed mask per step; the engine applies it inside the
already-jitted sampling (`logits = where(mask, logits, -inf)`) so shapes
stay static and the decode step compiles once per (S, V).

Two constraint forms:

- ``choices``: the output must be exactly one of N token-id sequences
  (token-level trie; build from strings with `tokenize=`).
- ``regex``: the output's detokenized text must match the pattern.
  Internal engine: literals, ``.``, classes ``[a-z0-9]`` / ``[^...]``,
  groups, ``|``, ``* + ? {m} {m,} {m,n}`` — compiled to a Thompson NFA,
  subset-constructed to a DFA lazily.  Per DFA state the token-level
  transition over the whole vocab is computed ONCE as a vectorized
  numpy walk over the padded token-character matrix, then cached —
  per-step cost after warmup is a dict lookup + O(V) mask fetch.

EOS handling: the EOS token is allowed exactly when the automaton is in
an accepting state; any other token outside the language is masked out,
so a greedy or sampled decode can never leave the constraint.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["GuidedSpec", "TokenFSM", "compile_guided",
           "json_schema_to_regex"]


# ---------------------------------------------------------------- regex

class _NFA:
    """Thompson construction over byte/char codes 0..255 (we match on
    Python str chars via ord()<256; wider codepoints are matched by
    explicit literals only)."""

    def __init__(self):
        self.eps: List[List[int]] = []      # state -> eps targets
        self.edges: List[List[Tuple[np.ndarray, int]]] = []
        self.accept: int = -1

    def new_state(self) -> int:
        self.eps.append([])
        self.edges.append([])
        return len(self.eps) - 1


def _charclass(expr: str, i: int) -> Tuple[np.ndarray, int]:
    """Parse a [...] class starting at expr[i] == '['; returns (mask256,
    next index)."""
    mask = np.zeros(256, dtype=bool)
    i += 1
    negate = i < len(expr) and expr[i] == "^"
    if negate:
        i += 1
    first = True
    while i < len(expr) and (expr[i] != "]" or first):
        first = False
        if expr[i] == "\\" and i + 1 < len(expr):
            nc = expr[i + 1]
            if nc in "dwsDWS":
                mask |= _escape_set(nc)
                i += 2
                continue
            # single-char escapes (\n, \t, ...) may still anchor a range
            lo = ord(_ESCAPE_CHARS.get(nc, nc))
            i += 2
        else:
            lo = ord(expr[i])
            i += 1
        if i + 1 < len(expr) and expr[i] == "-" and expr[i + 1] != "]":
            hi = ord(expr[i + 1])
            mask[lo:hi + 1] = True
            i += 2
        elif lo < 256:
            mask[lo] = True
    if i >= len(expr) or expr[i] != "]":
        raise ValueError(f"unterminated character class in {expr!r}")
    if negate:
        mask = ~mask
    return mask, i + 1


_ESCAPE_CHARS = {"n": "\n", "t": "\t", "r": "\r", "f": "\f", "v": "\v",
                 "0": "\0", "a": "\a", "b": "\b"}


def _escape_set(c: str) -> np.ndarray:
    m = np.zeros(256, dtype=bool)
    if c == "d":
        m[ord("0"):ord("9") + 1] = True
    elif c == "w":
        m[ord("a"):ord("z") + 1] = True
        m[ord("A"):ord("Z") + 1] = True
        m[ord("0"):ord("9") + 1] = True
        m[ord("_")] = True
    elif c == "s":
        for ch in " \t\n\r\f\v":
            m[ord(ch)] = True
    elif c in "DWS":
        m = ~_escape_set(c.lower())
    elif c in _ESCAPE_CHARS:
        m[ord(_ESCAPE_CHARS[c])] = True
    else:
        if ord(c) < 256:
            m[ord(c)] = True
    return m


class _RegexParser:
    """Recursive-descent regex -> NFA fragment (start, end)."""

    def __init__(self, expr: str, nfa: _NFA):
        self.expr = expr
        self.i = 0
        self.nfa = nfa

    def parse(self) -> Tuple[int, int]:
        frag = self._alternation()
        if self.i != len(self.expr):
            raise ValueError(
                f"unexpected {self.expr[self.i]!r} at {self.i} "
                f"in regex {self.expr!r}")
        return frag

    def _alternation(self) -> Tuple[int, int]:
        frags = [self._concat()]
        while self.i < len(self.expr) and self.expr[self.i] == "|":
            self.i += 1
            frags.append(self._concat())
        if len(frags) == 1:
            return frags[0]
        s, e = self.nfa.new_state(), self.nfa.new_state()
        for fs, fe in frags:
            self.nfa.eps[s].append(fs)
            self.nfa.eps[fe].append(e)
        return s, e

    def _concat(self) -> Tuple[int, int]:
        frags = []
        while self.i < len(self.expr) and self.expr[self.i] not in "|)":
            frags.append(self._repeat())
        if not frags:
            s = self.nfa.new_state()
            return s, s
        for (  _s1, e1), (s2, _e2) in zip(frags, frags[1:]):
            self.nfa.eps[e1].append(s2)
        return frags[0][0], frags[-1][1]

    def _repeat(self) -> Tuple[int, int]:
        frag = self._atom()
        first = True
        while self.i < len(self.expr) and self.expr[self.i] in "*+?{":
            c = self.expr[self.i]
            if c == "?" and not first:
                # lazy-quantifier marker (X+?, X{m,n}?): laziness picks
                # a different match, not a different LANGUAGE — for a
                # fullmatch automaton it is a no-op, NOT (X+)?
                self.i += 1
                continue
            first = False
            if c == "{":
                j = self.expr.index("}", self.i)
                body = self.expr[self.i + 1:j]
                if "," in body:
                    lo_s, hi_s = body.split(",", 1)
                    lo = int(lo_s or 0)
                    hi = int(hi_s) if hi_s else None
                else:
                    lo = hi = int(body)
                self.i = j + 1
                frag = self._repeat_range(frag, lo, hi)
            else:
                self.i += 1
                s, e = self.nfa.new_state(), self.nfa.new_state()
                fs, fe = frag
                self.nfa.eps[s].append(fs)
                self.nfa.eps[fe].append(e)
                if c in "*?":
                    self.nfa.eps[s].append(e)
                if c in "*+":
                    self.nfa.eps[fe].append(fs)
                frag = (s, e)
        return frag

    def _repeat_range(self, frag, lo: int, hi: Optional[int]):
        # expand {m,n} by cloning the sub-expression; clones share no
        # states so the NFA stays a DAG of fragments
        src_s, src_e = frag
        clones = []
        total = hi if hi is not None else max(lo, 1)
        for _ in range(total):
            clones.append(self._clone(src_s, src_e))
        s, e = self.nfa.new_state(), self.nfa.new_state()
        cur = s
        for idx, (cs, ce) in enumerate(clones):
            self.nfa.eps[cur].append(cs)
            # `cur` has completed exactly `idx` repetitions: exiting is
            # legal only once idx >= lo (idx+1 would accept m-1 reps)
            if idx >= lo:
                self.nfa.eps[cur].append(e)
            cur = ce
        self.nfa.eps[cur].append(e)
        if hi is None:  # {m,}: loop the final clone
            fs, fe = clones[-1]
            self.nfa.eps[fe].append(fs)
        return (s, e)

    def _clone(self, s: int, e: int) -> Tuple[int, int]:
        """Deep-copy the fragment reachable from s (up to e)."""
        mapping: Dict[int, int] = {}
        stack = [s]
        while stack:
            st = stack.pop()
            if st in mapping:
                continue
            mapping[st] = self.nfa.new_state()
            for t in self.nfa.eps[st]:
                if t not in mapping:
                    stack.append(t)
            for _m, t in self.nfa.edges[st]:
                if t not in mapping:
                    stack.append(t)
        for st, new in list(mapping.items()):
            self.nfa.eps[new] = [mapping[t] for t in self.nfa.eps[st]]
            self.nfa.edges[new] = [(m, mapping[t])
                                   for m, t in self.nfa.edges[st]]
        return mapping[s], mapping[e]

    def _atom(self) -> Tuple[int, int]:
        expr = self.expr
        c = expr[self.i]
        if c in "^$":
            # the automaton always fullmatches, so anchors are no-ops
            # (outlines/vLLM-style patterns commonly include them)
            self.i += 1
            st = self.nfa.new_state()
            return st, st
        if c == "(":
            self.i += 1
            frag = self._alternation()
            if self.i >= len(expr) or expr[self.i] != ")":
                raise ValueError(f"unbalanced ( in regex {expr!r}")
            self.i += 1
            return frag
        if c == "[":
            mask, self.i = _charclass(expr, self.i)
            return self._edge(mask)
        if c == ".":
            self.i += 1
            mask = np.ones(256, dtype=bool)
            return self._edge(mask)
        if c == "\\" and self.i + 1 < len(expr):
            self.i += 2
            return self._edge(_escape_set(expr[self.i - 1]))
        self.i += 1
        mask = np.zeros(256, dtype=bool)
        if ord(c) < 256:
            mask[ord(c)] = True
        return self._edge(mask)

    def _edge(self, mask: np.ndarray) -> Tuple[int, int]:
        s, e = self.nfa.new_state(), self.nfa.new_state()
        self.nfa.edges[s].append((mask, e))
        return s, e


class _DFA:
    """Full subset construction (iterative worklist) with a dense char
    transition row per state (256-wide; -1 = dead)."""

    def __init__(self, nfa: _NFA, start: int, accept: int):
        self.nfa = nfa
        self.accept_nfa = accept
        self.states: Dict[frozenset, int] = {}
        self.trans: List[np.ndarray] = []
        self.accepting: List[bool] = []
        self.start = self._intern(self._closure({start}))
        work = [self.start]
        closures = {self.start: next(c for c, i in self.states.items()
                                     if i == self.start)}
        while work:
            sid = work.pop()
            closure = closures[sid]
            row = self.trans[sid]
            char_targets: List[Tuple[np.ndarray, int]] = []
            for s in closure:
                for mask, t in self.nfa.edges[s]:
                    char_targets.append((mask, t))
            if not char_targets:
                continue
            all_mask = np.zeros((len(char_targets), 256), dtype=bool)
            for k, (mask, _t) in enumerate(char_targets):
                all_mask[k] = mask
            # group chars by their target-set signature
            by_key: Dict[frozenset, List[int]] = {}
            for c in np.flatnonzero(all_mask.any(axis=0)):
                tgt = frozenset(t for k, (_m, t) in enumerate(char_targets)
                                if all_mask[k, c])
                by_key.setdefault(tgt, []).append(int(c))
            for tgt_key, chars in by_key.items():
                closure2 = self._closure(set(tgt_key))
                known = self.states.get(closure2)
                nid = self._intern(closure2)
                if known is None:
                    closures[nid] = closure2
                    work.append(nid)
                row[chars] = nid

    def _closure(self, states: set) -> frozenset:
        stack = list(states)
        seen = set(states)
        while stack:
            s = stack.pop()
            for t in self.nfa.eps[s]:
                if t not in seen:
                    seen.add(t)
                    stack.append(t)
        return frozenset(seen)

    def _intern(self, closure: frozenset) -> int:
        sid = self.states.get(closure)
        if sid is not None:
            return sid
        sid = len(self.trans)
        self.states[closure] = sid
        self.trans.append(np.full(256, -1, dtype=np.int64))
        self.accepting.append(self.accept_nfa in closure)
        return sid


# ------------------------------------------------------------- token FSM

class GuidedSpec:
    """User-facing constraint: exactly one of `choices` (strings or
    token-id sequences), `regex` over the detokenized output, or
    `json_schema` (compiled to a regex via json_schema_to_regex — the
    output is canonical compact JSON matching the schema subset)."""

    def __init__(self, choices: Optional[Sequence] = None,
                 regex: Optional[str] = None,
                 json_schema: Optional[dict] = None):
        provided = sum(x is not None
                       for x in (choices, regex, json_schema))
        if provided != 1:
            raise ValueError("GuidedSpec needs exactly one of choices=, "
                             "regex=, or json_schema=")
        if json_schema is not None:
            regex = json_schema_to_regex(json_schema)
        self.choices = list(choices) if choices is not None else None
        self.regex = regex
        self.json_schema = json_schema

    def __repr__(self):
        if self.choices is not None:
            return f"GuidedSpec(choices={self.choices!r})"
        if self.json_schema is not None:
            return f"GuidedSpec(json_schema={self.json_schema!r})"
        return f"GuidedSpec(regex={self.regex!r})"


class TokenFSM:
    """Token-level automaton over a fixed vocab.

    API used by the engine (all host-side, O(V) per step after warmup):
      - ``start`` : initial state id
      - ``allowed(state)`` -> (V,) bool mask (incl. eos when accepting)
      - ``advance(state, token)`` -> next state id (-1 = dead)
      - ``is_accepting(state)``
      - ``is_complete(state)``: accepting AND no live continuation
    """

    def __init__(self, vocab_size: int, eos_id: int):
        self.vocab_size = vocab_size
        self.eos_id = eos_id
        self.start = 0

    # -- choice/trie construction

    @classmethod
    def from_choices(cls, seqs: Sequence[Sequence[int]], vocab_size: int,
                     eos_id: int) -> "TokenFSM":
        fsm = cls(vocab_size, eos_id)
        fsm._mode = "trie"
        # trie node: dict token -> node id; node 0 = root
        fsm._children: List[Dict[int, int]] = [{}]
        fsm._accept: List[bool] = [False]
        for seq in seqs:
            seq = [int(t) for t in seq]
            if not seq:
                fsm._accept[0] = True
                continue
            node = 0
            for tok in seq:
                nxt = fsm._children[node].get(tok)
                if nxt is None:
                    nxt = len(fsm._children)
                    fsm._children.append({})
                    fsm._accept.append(False)
                    fsm._children[node][tok] = nxt
                node = nxt
            fsm._accept[node] = True
        fsm._mask_cache: Dict[int, np.ndarray] = {}
        return fsm

    @classmethod
    def from_regex(cls, pattern: str, token_strings: Sequence[str],
                   eos_id: int) -> "TokenFSM":
        """token_strings[i] = the text token id i appends (the engine
        passes tokenizer.convert_ids_to_tokens-style strings; specials/
        unused ids may be None to exclude them)."""
        fsm = cls(len(token_strings), eos_id)
        fsm._mode = "regex"
        nfa = _NFA()
        parser = _RegexParser(pattern, nfa)
        s, e = parser.parse()
        nfa.accept = e
        fsm._dfa = _DFA(nfa, s, e)
        # padded char-code matrix (V, Lmax); -1 pads; unusable tokens
        # (None/empty/non-latin1) get length 0 and are always masked out
        lens = np.zeros(len(token_strings), dtype=np.int64)
        codes_list = []
        for ts in token_strings:
            if ts is None or ts == "" or any(ord(ch) > 255 for ch in ts):
                codes_list.append([])
            else:
                codes_list.append([ord(ch) for ch in ts])
                lens[len(codes_list) - 1] = len(ts)
        lmax = max((len(c) for c in codes_list), default=1) or 1
        mat = np.zeros((len(token_strings), lmax), dtype=np.int64)
        for v, codes in enumerate(codes_list):
            mat[v, :len(codes)] = codes
        fsm._tok_codes = mat
        fsm._tok_lens = lens
        # per-DFA-state caches: (allowed mask incl eos, end-state per tok)
        fsm._state_cache: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        return fsm

    # -- shared API

    def allowed(self, state: int) -> np.ndarray:
        if state < 0:
            return np.zeros(self.vocab_size, dtype=bool)
        if self._mode == "trie":
            mask = self._mask_cache.get(state)
            if mask is None:
                mask = np.zeros(self.vocab_size, dtype=bool)
                for tok in self._children[state]:
                    if tok < self.vocab_size:
                        mask[tok] = True
                if self._accept[state] and self.eos_id < self.vocab_size:
                    mask[self.eos_id] = True
                self._mask_cache[state] = mask
            return mask
        mask, _ends = self._regex_state(state)
        return mask

    def advance(self, state: int, token: int) -> int:
        if state < 0:
            return -1
        if token == self.eos_id:
            return state if self.is_accepting(state) else -1
        if self._mode == "trie":
            return self._children[state].get(int(token), -1)
        _mask, ends = self._regex_state(state)
        return int(ends[token]) if 0 <= token < self.vocab_size else -1

    def is_accepting(self, state: int) -> bool:
        if state < 0:
            return False
        if self._mode == "trie":
            return self._accept[state]
        return self._dfa.accepting[state]

    def is_complete(self, state: int) -> bool:
        """Accepting with no way to continue — the engine force-stops."""
        if state < 0:
            return False
        mask = self.allowed(state)
        if self.eos_id < self.vocab_size:
            cont = mask.copy()
            cont[self.eos_id] = False
        else:
            cont = mask
        return self.is_accepting(state) and not cont.any()

    # -- regex internals

    def _regex_state(self, state: int) -> Tuple[np.ndarray, np.ndarray]:
        cached = self._state_cache.get(state)
        if cached is not None:
            return cached
        # vectorized walk of EVERY vocab token's chars through the DFA,
        # once per (visited) DFA state, then cached
        V, L = self._tok_codes.shape
        table = self._table()
        cur = np.full(V, state, dtype=np.int64)
        for col in range(L):
            live = (self._tok_lens > col) & (cur >= 0)
            if not live.any():
                break
            nxt = table[cur[live], self._tok_codes[live, col]]
            cur[live] = nxt
        ends = np.where(self._tok_lens > 0, cur, -1)
        mask = ends >= 0
        if self.eos_id < V:
            mask = mask.copy()
            mask[self.eos_id] = self._dfa.accepting[state]
            ends[self.eos_id] = state if self._dfa.accepting[state] else -1
        result = (mask, ends)
        self._state_cache[state] = result
        return result

    def _table(self) -> np.ndarray:
        """Dense (n_states, 256) DFA transition table, built once."""
        tbl = getattr(self, "_table_cache", None)
        if tbl is None or len(tbl) != len(self._dfa.trans):
            tbl = np.stack(self._dfa.trans) if self._dfa.trans else \
                np.full((1, 256), -1, dtype=np.int64)
            self._table_cache = tbl
        return tbl


def compile_guided(spec: GuidedSpec, *, vocab_size: int, eos_id: int,
                   tokenize: Optional[Callable[[str], List[int]]] = None,
                   token_strings: Optional[Sequence[str]] = None
                   ) -> TokenFSM:
    """Build the TokenFSM for a spec.

    choices: items may be token-id sequences already, or strings (then
    `tokenize` is required).  regex: requires `token_strings`."""
    if spec.choices is not None:
        seqs = []
        for ch in spec.choices:
            if isinstance(ch, str):
                if tokenize is None:
                    raise ValueError(
                        "string choices need tokenize= to map them to "
                        "token ids")
                seqs.append(tokenize(ch))
            else:
                seqs.append(list(ch))
        return TokenFSM.from_choices(seqs, vocab_size, eos_id)
    if token_strings is None:
        raise ValueError("regex constraints need token_strings= "
                         "(text appended by each token id)")
    return TokenFSM.from_regex(spec.regex, token_strings, eos_id)


# ---------------------------------------------------------- JSON schema

_REGEX_META = set(".[]{}()*+?|^$\\")


def _rx_literal(text: str) -> str:
    """Escape `text` for the guided regex engine (fullmatch subset)."""
    out = []
    for ch in text:
        if ch in _REGEX_META:
            out.append("\\" + ch)
        else:
            out.append(ch)
    return "".join(out)


# canonical compact JSON value regexes (guided output is canonical:
# no whitespace, fixed key order — the standard shape for guided_json)
_RX_STRING = r'"[^"\\]*"'          # simple strings: no escapes/quotes
_RX_INTEGER = r"-?(0|[1-9][0-9]{0,15})"
_RX_NUMBER = _RX_INTEGER + r"(\.[0-9]{1,8})?"
_RX_BOOL = r"(true|false)"
_RX_NULL = r"null"


def json_schema_to_regex(schema: dict, *, _depth: int = 0) -> str:
    """Compile a practical JSON-schema subset to the guided regex
    language (reference: the guided_json mode of the vLLM/outlines-style
    serving API — schema-constrained decoding).

    Supported: type object (properties in declaration order; non-required
    trailing properties become optional), string (+ enum, maxLength via
    simple strings), integer, number, boolean, null, array (items,
    minItems/maxItems up to 8), enum of strings/numbers, const.
    The output language is CANONICAL compact JSON: no whitespace, keys
    in schema order — every string in the language parses with
    json.loads and validates against the schema subset."""
    if not isinstance(schema, dict):
        raise ValueError(
            f"json schema must be an object, got {type(schema).__name__}")
    if _depth > 16:
        raise ValueError("json schema nesting too deep (>16)")
    if "const" in schema:
        import json as _json
        return _rx_literal(_json.dumps(schema["const"],
                                       separators=(",", ":")))
    if "enum" in schema:
        import json as _json
        if not schema["enum"]:
            raise ValueError("enum must be non-empty (unsatisfiable)")
        opts = [_rx_literal(_json.dumps(v, separators=(",", ":")))
                for v in schema["enum"]]
        return "(" + "|".join(opts) + ")"
    t = schema.get("type")
    if t == "string":
        lo = schema.get("minLength")
        hi = schema.get("maxLength")
        if lo is None and hi is None:
            return _RX_STRING
        lo = int(lo or 0)
        hi = int(hi if hi is not None else max(lo, 64))
        if lo > hi:
            raise ValueError("minLength > maxLength")
        return '"' + r'[^"\\]' + "{%d,%d}" % (lo, hi) + '"'

    if t == "integer":
        return _RX_INTEGER
    if t == "number":
        return _RX_NUMBER
    if t == "boolean":
        return _RX_BOOL
    if t == "null":
        return _RX_NULL
    if t == "array":
        item = json_schema_to_regex(schema.get("items", {"type": "null"}),
                                    _depth=_depth + 1)
        lo = int(schema.get("minItems", 0))
        hi = int(schema.get("maxItems", 8))
        if hi > 8 or lo > hi:
            raise ValueError("array bounds: need minItems <= maxItems "
                             "<= 8 for guided arrays")
        item_g = f"({item})"
        if hi == 0:
            return r"\[\]"
        more = f"(,{item_g}){{{max(lo - 1, 0)},{hi - 1}}}" \
            if hi > 1 else ""
        body = f"{item_g}{more}"
        if lo == 0:
            return r"\[" + f"({body})?" + r"\]"
        return r"\[" + body + r"\]"
    if t == "object" or "properties" in schema:
        props = schema.get("properties", {})
        # JSON-Schema semantics: a missing `required` key means NO
        # property is required (the old default of all-of-them silently
        # inverted that and forced optional fields into every output)
        required = set(schema.get("required", ()))
        parts = []
        import json as _json
        for key, sub in props.items():
            val = json_schema_to_regex(sub, _depth=_depth + 1)
            # keys are JSON-encoded like const/enum values, so quotes,
            # control chars, and non-latin1 keys stay valid JSON (or
            # fail loudly in the regex engine, never silently)
            pair = f'{_rx_literal(_json.dumps(key))}:({val})'
            parts.append((pair, key in required))
        if not parts:
            return r"\{\}"
        # canonical order; optional properties must be a trailing run
        # AFTER at least one required property, so every optional pair
        # carries its own leading comma and the grammar stays regular
        if not parts[0][1] and len(parts) > 1:
            raise ValueError(
                "guided JSON objects need the first property required "
                "(optional properties form a trailing run)")
        seen_optional = False
        body = ""
        for idx, (pair, req) in enumerate(parts):
            lead = "," if idx > 0 else ""
            if req:
                if seen_optional:
                    raise ValueError(
                        "required properties must precede optional ones "
                        "(canonical guided JSON)")
                body += lead + pair
            else:
                seen_optional = True
                body += f"({lead}{pair})?"
        return r"\{" + body + r"\}"
    raise ValueError(f"unsupported json schema: {schema!r}")
