"""OpenAI-compatible serving surface over the LLM engine.

Reference parity: the fork's serve.llm OpenAI-compatible router (vLLM's
/v1/completions and /v1/chat/completions). Deploy with
`build_openai_deployment(...)` at route_prefix="/v1"; the proxy routes
any /v1/* POST here and the body shape picks the API:

    {"prompt": ...}    -> completions
    {"messages": ...}  -> chat completions

Streaming follows the OpenAI contract: `"stream": true` returns SSE
`data:` chunks — for chat a leading {"delta": {"role": "assistant"}}
chunk, then content deltas, then a final chunk carrying finish_reason,
then `data: [DONE]`. `stop` accepts a string or a list of strings/ids;
single-token stop strings also stop generation inside the engine, and
every stop string is enforced host-side on the decoded text (so
multi-token sequences work too).
"""
from __future__ import annotations

import itertools
import time
from typing import Any, Dict, List, Optional, Tuple

from ..deployment import Application
from . import LLMServer, build_llm_deployment

_req_ids = itertools.count()

# SentencePiece word-boundary marker (U+2581 LOWER ONE EIGHTH BLOCK)
_SP_SPACE = "▁"


def _token_strings(tokenizer, vocab_size: int) -> List[str]:
    """Per-token appended text for guided-regex compilation.

    Prefers tokenizer PIECES (convert_ids_to_tokens) with the
    SentencePiece `▁` word-boundary marker mapped to a literal space:
    `decode([i])` strips the marker, so "model" and "▁model" both
    decoded to "model" and space-crossing guided regexes compiled
    against the wrong per-token text. Pieces without the marker (and
    tokenizers without a piece API) keep the decode([i]) byte-level
    approximation — byte-level BPEs encode spaces as other markers
    (Ġ, Ċ) that only their decoder maps correctly."""
    convert = getattr(tokenizer, "convert_ids_to_tokens", None)
    pieces: List[Optional[str]] = [None] * vocab_size
    if convert is not None:
        try:
            got = convert(list(range(vocab_size)))
            if got is not None and len(got) == vocab_size:
                pieces = list(got)
        except Exception:
            pass
    out = []
    for i in range(vocab_size):
        p = pieces[i]
        if isinstance(p, str) and _SP_SPACE in p:
            out.append(p.replace(_SP_SPACE, " "))
        else:
            out.append(tokenizer.decode([i]))
    return out


class OpenAIServer(LLMServer):
    """LLMServer speaking the OpenAI REST schema."""

    def __init__(self, model_factory, engine_config: Optional[dict] = None,
                 tokenizer: Optional[Any] = None,
                 cached_prefixes: Optional[list] = None,
                 model_name: str = "ray-tpu-llm"):
        super().__init__(model_factory, engine_config, tokenizer,
                         cached_prefixes=cached_prefixes)
        self._token_strings = None
        self._fsm_cache: Dict[Any, Any] = {}
        self.model_name = model_name

    # ---- request plumbing -------------------------------------------------
    def _sampling(self, body: Dict[str, Any], prompt_len: int
                  ) -> Tuple[Dict[str, Any], List[str], int]:
        """(engine submit kwargs, host-side stop strings, effective max
        new tokens after the engine's seq-budget clamp)."""
        stop = body.get("stop") or []
        if isinstance(stop, (str, int)):
            stop = [stop]
        stop_ids: List[int] = []
        stop_strings: List[str] = []
        for s in stop:
            if isinstance(s, int):
                stop_ids.append(s)
                continue
            stop_strings.append(s)
            if self.tokenizer is not None:
                ids = self.tokenizer.encode(s)
                if len(ids) == 1:
                    # single-token stops can end generation on-engine;
                    # longer ones rely on the host-side text match
                    stop_ids.append(ids[0])
        requested = body.get("max_tokens")
        cfg = self.engine.cfg
        effective = min(requested or cfg.max_new_tokens_default,
                        max(cfg.max_seq_len - prompt_len, 0))
        kwargs = dict(
            max_new_tokens=requested,
            temperature=float(body.get("temperature", 1.0)),
            top_p=float(body.get("top_p", 1.0)),
            stop_token_ids=stop_ids or None,
            presence_penalty=float(body.get("presence_penalty", 0.0)),
            frequency_penalty=float(body.get("frequency_penalty", 0.0)),
            logit_bias={int(k): float(v) for k, v in
                        (body.get("logit_bias") or {}).items()} or None)
        fsm = self._guided_fsm(body)
        if fsm is not None:
            kwargs["guided_fsm"] = fsm
        return kwargs, stop_strings, effective

    def _guided_fsm(self, body: Dict[str, Any]):
        """vLLM-style guided output: `guided_choice` (list of strings)
        or `guided_regex` (pattern over the detokenized output) compile
        to a serve.llm.guided.TokenFSM using this server's tokenizer
        (reference: the vLLM/outlines guided-output API the fork's
        serving north star exposes)."""
        choice = body.get("guided_choice")
        regex = body.get("guided_regex")
        schema = body.get("guided_json")
        if sum(x is not None for x in (choice, regex, schema)) > 1:
            raise ValueError("use guided_choice OR guided_regex OR "
                             "guided_json, not several")
        if schema is not None:
            if isinstance(schema, str):  # vLLM also accepts encoded
                import json as _json
                try:
                    schema = _json.loads(schema)
                except ValueError as e:
                    raise ValueError(f"guided_json is not valid JSON: "
                                     f"{e}") from e
            from .guided import json_schema_to_regex
            regex = json_schema_to_regex(schema)
        if choice is None and regex is None:
            return None
        if self.tokenizer is None:
            raise ValueError("guided output needs a tokenizer "
                             "(set tokenizer= on the deployment)")
        from .guided import GuidedSpec, compile_guided
        vs = int(self.engine.model.cfg.vocab_size)
        eos = self.engine.cfg.eos_token_id
        eos = vs if eos is None else int(eos)  # >=V: eos never unmasked
        key = (("choice", tuple(choice)) if choice
               else ("regex", regex)) + (vs, eos)
        fsm = self._fsm_cache.get(key)
        if fsm is not None:
            return fsm
        if choice:
            def tokenize(text):
                try:
                    return self.tokenizer.encode(
                        text, add_special_tokens=False)
                except TypeError:
                    return self.tokenizer.encode(text)
            fsm = compile_guided(GuidedSpec(choices=list(choice)),
                                 vocab_size=vs, eos_id=eos,
                                 tokenize=tokenize)
        else:
            if self._token_strings is None:
                self._token_strings = _token_strings(self.tokenizer, vs)
            fsm = compile_guided(GuidedSpec(regex=regex), vocab_size=vs,
                                 eos_id=eos,
                                 token_strings=self._token_strings)
        if len(self._fsm_cache) >= 64:  # bounded: drop oldest pattern
            self._fsm_cache.pop(next(iter(self._fsm_cache)))
        self._fsm_cache[key] = fsm
        return fsm

    def _chat_prompt(self, messages: List[Dict[str, str]]):
        tok = self.tokenizer
        if tok is not None and hasattr(tok, "apply_chat_template"):
            return tok.apply_chat_template(messages,
                                           add_generation_prompt=True)
        if tok is None:
            raise ValueError("chat API needs a tokenizer "
                             "(set tokenizer= on the deployment)")
        text = "".join(f"{m.get('role', 'user')}: {m.get('content', '')}\n"
                       for m in messages) + "assistant:"
        return tok.encode(text)

    def _decode_text(self, toks: List[int]) -> str:
        if self.tokenizer is not None:
            return self.tokenizer.decode(toks)
        return " ".join(str(t) for t in toks)

    @staticmethod
    def _apply_stops(text: str, stops: List[str]) -> Tuple[str, bool]:
        """Truncate at the earliest stop-string occurrence."""
        cut = None
        for s in stops:
            if not s:
                continue
            i = text.find(s)
            if i >= 0 and (cut is None or i < cut):
                cut = i
        return (text[:cut], True) if cut is not None else (text, False)

    def _finish_reason(self, n_out: int, effective: int, last_tok,
                       stop_ids, stopped_by_string: bool) -> str:
        if stopped_by_string:
            return "stop"
        if last_tok is not None and (
                last_tok == self.engine.cfg.eos_token_id
                or (stop_ids and last_tok in stop_ids)):
            return "stop"
        return "length" if n_out >= effective else "stop"

    def _collect(self, rid: str, stops: List[str]
                 ) -> Tuple[List[int], List, str, bool]:
        """Drain a request, aborting early when a stop string lands."""
        toks: List[int] = []
        lps: List = []
        text, by_string = "", False
        for tok, lp in self.engine.stream_detailed(rid):
            if by_string:
                continue  # draining to the end marker post-abort
            toks.append(tok)
            lps.append(lp)
            text, by_string = self._apply_stops(
                self._decode_text(toks), stops)
            if by_string:
                self.engine.abort(rid)
        return toks, lps, text, by_string

    # ---- the two APIs -----------------------------------------------------
    def __call__(self, body: Dict[str, Any]):
        try:
            if isinstance(body, dict) and "messages" in body:
                return self._chat(body)
            if isinstance(body, dict) and "prompt" in body:
                return self._completions(body)
        except ValueError as e:
            # invalid request (bad top_p, prompt too long for the
            # configured buckets, ...) -> OpenAI error object, not a 500
            err = {"error": {"message": str(e),
                             "type": "invalid_request_error"}}
            if isinstance(body, dict) and body.get("stream"):
                # a real generator: the replica's streaming path detects
                # generators, not arbitrary iterators
                def err_stream():
                    yield err
                    yield "[DONE]"
                return err_stream()
            return err
        return super().__call__(body)

    def _submit_n(self, n: int, suffix, prefix_id, sp) -> List[str]:
        """Submit all n choices; if the k-th submit raises (e.g. the
        pool can never admit it), abort the k-1 already-submitted
        request ids before re-raising — mirroring the _collect cleanup,
        so failed multi-choice calls never strand siblings on the
        engine."""
        from ..context import get_request_deadline
        rids: List[str] = []
        try:
            for _ in range(n):
                rids.append(self.engine.submit(
                    suffix, prefix_id=prefix_id,
                    deadline_ts=get_request_deadline(), **sp))
        except BaseException:
            for r in rids:
                try:
                    self.engine.abort(r)
                except Exception:
                    pass
            raise
        return rids

    @staticmethod
    def _n_choices(body: Dict[str, Any]) -> int:
        raw = body.get("n")
        n = 1 if raw is None else int(raw)
        if n < 1:
            raise ValueError("n must be >= 1")
        best_of = body.get("best_of")
        if best_of is not None and int(best_of) != n:
            raise ValueError("best_of != n is not supported")
        if body.get("stream") and n > 1:
            raise ValueError("streaming with n > 1 is not supported")
        return n

    def _completions(self, body: Dict[str, Any]):
        prompt = self._encode(body["prompt"])
        sp, stops, effective = self._sampling(body, len(prompt))
        suffix, prefix_id = self._match_prefix(prompt)
        n = self._n_choices(body)
        # all n submits enter the engine together and continuous-batch
        rids = self._submit_n(n, suffix, prefix_id, sp)
        oid = f"cmpl-{next(_req_ids)}"
        if body.get("stream"):
            return self._stream_events(
                rids[0], oid, "text_completion", stops, effective,
                sp["stop_token_ids"],
                content_chunk=lambda text: {"text": text},
                final_extra=lambda: {"text": ""})
        choices = []
        total_out = 0
        try:
            collected = [self._collect(rid, stops) for rid in rids]
        except BaseException:
            for r in rids:  # don't strand sibling choices on the engine
                try:
                    self.engine.abort(r)
                except Exception:
                    pass
            raise
        for idx, (toks, lps, text, by_string) in enumerate(collected):
            total_out += len(toks)
            logprobs = None
            if body.get("logprobs") and any(lp is not None
                                            for lp in lps):
                logprobs = {
                    "tokens": [self._decode_text([t]) for t in toks],
                    "token_logprobs": lps,
                    "top_logprobs": None, "text_offset": None}
            choices.append({
                "index": idx, "text": text,
                "finish_reason": self._finish_reason(
                    len(toks), effective, toks[-1] if toks else None,
                    sp["stop_token_ids"], by_string),
                "logprobs": logprobs})
        return {
            "id": oid, "object": "text_completion",
            "created": int(time.time()), "model": self.model_name,
            "choices": choices,
            "usage": {"prompt_tokens": len(prompt),
                      "completion_tokens": total_out,
                      "total_tokens": len(prompt) + total_out}}

    def _chat(self, body: Dict[str, Any]):
        prompt = self._chat_prompt(body["messages"])
        sp, stops, effective = self._sampling(body, len(prompt))
        suffix, prefix_id = self._match_prefix(prompt)
        n = self._n_choices(body)
        rids = self._submit_n(n, suffix, prefix_id, sp)
        rid = rids[0]
        oid = f"chatcmpl-{next(_req_ids)}"
        if body.get("stream"):
            return self._stream_events(
                rid, oid, "chat.completion.chunk", stops, effective,
                sp["stop_token_ids"],
                content_chunk=lambda text: {"delta": {"content": text}},
                final_extra=lambda: {"delta": {}},
                lead_chunk={"delta": {"role": "assistant"}})
        try:
            collected = [self._collect(r, stops) for r in rids]
        except BaseException:
            for r in rids:
                try:
                    self.engine.abort(r)
                except Exception:
                    pass
            raise
        choices = []
        total_out = 0
        for idx, (toks, _lps, text, by_string) in enumerate(collected):
            total_out += len(toks)
            choices.append({
                "index": idx,
                "message": {"role": "assistant", "content": text},
                "finish_reason": self._finish_reason(
                    len(toks), effective, toks[-1] if toks else None,
                    sp["stop_token_ids"], by_string)})
        return {
            "id": oid, "object": "chat.completion",
            "created": int(time.time()), "model": self.model_name,
            "choices": choices,
            "usage": {"prompt_tokens": len(prompt),
                      "completion_tokens": total_out,
                      "total_tokens": len(prompt) + total_out}}

    def _stream_events(self, rid: str, oid: str, obj: str,
                       stops: List[str], effective: int, stop_ids,
                       *, content_chunk, final_extra, lead_chunk=None):
        created = int(time.time())

        def wrap(choice: Dict[str, Any],
                 finish: Optional[str] = None) -> Dict[str, Any]:
            return {"id": oid, "object": obj, "created": created,
                    "model": self.model_name,
                    "choices": [{"index": 0, **choice,
                                 "finish_reason": finish}]}

        def holdback(text: str) -> int:
            """Length of the longest suffix of `text` that is a prefix
            of some stop string. That tail is withheld from the client:
            if the stop completes on a later token it must never have
            been sent (streamed and unary outputs would diverge)."""
            h = 0
            for s in stops:
                for k in range(min(len(s), len(text)), h, -1):
                    if text.endswith(s[:k]):
                        h = max(h, k)
                        break
            return h

        def gen():
            if lead_chunk is not None:
                yield wrap(lead_chunk)
            emitted = ""     # decoded text already sent to the client
            toks: List[int] = []
            last_tok = None
            by_string = False
            full = ""
            for tok, _lp in self.engine.stream_detailed(rid):
                if by_string:
                    continue  # draining to the end marker post-abort
                toks.append(tok)
                last_tok = tok
                full, by_string = self._apply_stops(
                    self._decode_text(toks), stops)
                # withhold any tail that could still grow into a stop
                # match (a suffix of the truncated text never reaches
                # back into already-emitted text: that prefix was itself
                # a stop prefix and was withheld on the earlier step)
                safe = full if by_string else full[:len(full)
                                                   - holdback(full)]
                delta = safe[len(emitted):]
                if delta:
                    emitted = safe
                    yield wrap(content_chunk(delta))
                if by_string:
                    # stop sequence landed: cut the engine request short
                    # but keep consuming so its stream closes cleanly
                    self.engine.abort(rid)
            if not by_string and len(full) > len(emitted):
                # stream ended (budget/EOS) with a withheld partial stop
                # match that can no longer complete: flush it
                yield wrap(content_chunk(full[len(emitted):]))
            yield wrap(final_extra(), finish=self._finish_reason(
                len(toks), effective, last_tok, stop_ids, by_string))
            yield "[DONE]"

        return gen()


def build_openai_deployment(model_factory, *, engine_config=None,
                            tokenizer=None, model_name="ray-tpu-llm",
                            name: str = "OpenAIServer",
                            num_replicas: int = 1,
                            route_prefix: str = "/v1",
                            cached_prefixes=None,
                            max_ongoing_requests: int = 64) -> Application:
    """An Application serving /v1/completions + /v1/chat/completions.

    cached_prefixes: shared prompt prefixes (e.g. the system prompt's
    token ids or text) prefilled once at startup; any request starting
    with one adopts its KV instead of re-prefilling (prefix caching)."""
    engine_config = dict(engine_config or {})
    # the completions `logprobs` field needs the engine to fetch them
    engine_config.setdefault("logprobs", True)
    return build_llm_deployment(
        model_factory, engine_config=engine_config, tokenizer=tokenizer,
        name=name, num_replicas=num_replicas,
        max_ongoing_requests=max_ongoing_requests,
        cached_prefixes=cached_prefixes,
        server_cls=OpenAIServer,
        server_kwargs={"model_name": model_name},
        route_prefix=route_prefix)


__all__ = ["OpenAIServer", "build_openai_deployment"]
