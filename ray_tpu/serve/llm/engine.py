"""JAX continuous-batching LLM engine.

Reference parity: the fork's vLLM-style serving path (continuous batching,
paged KV, streaming) — re-designed TPU-first:

* Slot-based KV cache: one preallocated HBM buffer per layer of shape
  (max_slots, max_seq_len, n_kv_heads, head_dim). Static shapes, so the
  decode step compiles ONCE and every subsequent step reuses it.
* Continuous batching: ONE jitted decode step advances ALL active slots
  together (the MXU sees batch=max_slots matmuls, not per-request calls).
  Requests join/leave between steps with no recompile.
* Prefill: prompts are padded to power-of-two buckets -> a handful of
  compiles total; KV is written straight into the request's slot via
  dynamic_update_slice.
* Sampling (greedy / temperature / top-k) happens on-device inside the
  jitted step; only the sampled token ids (max_slots int32) cross to host
  per step.
"""
from __future__ import annotations

import itertools
import queue as queue_mod
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np


@dataclass
class LLMEngineConfig:
    max_slots: int = 8              # max concurrently-decoding sequences
    max_seq_len: int = 1024         # prompt + generation budget per slot
    prefill_buckets: tuple = (32, 64, 128, 256, 512, 1024)
    eos_token_id: Optional[int] = None
    max_new_tokens_default: int = 64
    top_k: int = 0                  # 0 = full softmax sampling


@dataclass
class _Request:
    request_id: str
    prompt: np.ndarray              # (P,) int32
    max_new_tokens: int
    temperature: float
    out_queue: queue_mod.Queue = field(
        default_factory=lambda: queue_mod.Queue(maxsize=4096))
    slot: int = -1
    generated: int = 0
    submit_ts: float = field(default_factory=time.time)
    first_token_ts: Optional[float] = None


_END = ("__end__", None)


class LLMEngine:
    """Continuous-batching engine over a ray_tpu Llama-family model.

    `model` must follow the ray_tpu/models/llama.py contract:
    apply({"params": params}, tokens, cache=..., positions=...) ->
    (logits, new_cache) with cache = [per-layer (k, v, lengths)].
    """

    def __init__(self, model, params, cfg: LLMEngineConfig):
        import jax
        import jax.numpy as jnp
        self._jax, self._jnp = jax, jnp
        self.model = model
        self.params = params
        self.cfg = cfg
        mcfg = model.cfg
        if cfg.eos_token_id is None:
            cfg.eos_token_id = getattr(mcfg, "eos_token_id", None)
        S, L = cfg.max_slots, cfg.max_seq_len
        self._cache = [
            (jnp.zeros((S, L, mcfg.n_kv_heads, mcfg.head_dim), mcfg.dtype),
             jnp.zeros((S, L, mcfg.n_kv_heads, mcfg.head_dim), mcfg.dtype),
             jnp.zeros((S,), jnp.int32))
            for _ in range(mcfg.n_layers)]
        self._last_tokens = jnp.zeros((S,), jnp.int32)
        self._free_slots = list(range(S))
        self._active: Dict[int, _Request] = {}
        self._waiting: "queue_mod.Queue[_Request]" = queue_mod.Queue()
        self._requests: Dict[str, _Request] = {}
        self._req_counter = itertools.count()
        self._lock = threading.Lock()
        self._rng_key = jax.random.PRNGKey(0)
        self._shutdown = threading.Event()
        self.stats = {"prefills": 0, "decode_steps": 0,
                      "tokens_generated": 0, "preempted": 0}

        self._prefill_jit = jax.jit(
            self._prefill_impl, static_argnames=("pad_len",),
            donate_argnums=(1,))
        self._decode_jit = jax.jit(self._decode_impl, donate_argnums=(1,))
        self._loop_thread = threading.Thread(
            target=self._engine_loop, daemon=True, name="llm-engine")
        self._loop_thread.start()

    # ---- jitted kernels ---------------------------------------------------
    def _prefill_impl(self, params, cache, tokens, slot, true_len,
                      pad_len: int):
        """Run the prompt through the model writing KV into `slot`.
        tokens: (1, pad_len); returns (last_logits (V,), cache')."""
        jnp = self._jnp
        lax = self._jax.lax
        # slice this slot's rows out of the big cache
        small = []
        for (ck, cv, lens) in cache:
            k1 = lax.dynamic_slice_in_dim(ck, slot, 1, axis=0)
            v1 = lax.dynamic_slice_in_dim(cv, slot, 1, axis=0)
            small.append((k1, v1, jnp.zeros((1,), jnp.int32)))
        positions = jnp.arange(pad_len)[None, :]
        logits, new_small = self.model.apply(
            {"params": params}, tokens, cache=small, positions=positions)
        out_cache = []
        for (ck, cv, lens), (k1, v1, _l1) in zip(cache, new_small):
            ck = lax.dynamic_update_slice_in_dim(ck, k1, slot, axis=0)
            cv = lax.dynamic_update_slice_in_dim(cv, v1, slot, axis=0)
            lens = lens.at[slot].set(true_len)
            out_cache.append((ck, cv, lens))
        last = logits[0, true_len - 1]
        return last, out_cache

    def _decode_impl(self, params, cache, last_tokens, active_mask,
                     temps, rng_key):
        """One decode step for every slot. Returns (next_tokens (S,),
        cache'). Inactive slots' lengths are restored so their state
        never drifts."""
        jnp = self._jnp
        jax = self._jax
        old_lengths = cache[0][2]
        positions = old_lengths[:, None]  # (S, 1): write at current end
        logits, new_cache = self.model.apply(
            {"params": params}, last_tokens[:, None], cache=cache,
            positions=positions)
        logits = logits[:, 0, :]  # (S, V)
        fixed = []
        for (ck, cv, lens) in new_cache:
            lens = jnp.where(active_mask, lens, old_lengths)
            fixed.append((ck, cv, lens))
        if self.cfg.top_k and self.cfg.top_k > 0:
            kth = jnp.sort(logits, axis=-1)[:, -self.cfg.top_k][:, None]
            logits = jnp.where(logits < kth, -jnp.inf, logits)
        greedy = jnp.argmax(logits, axis=-1)
        sampled = jax.random.categorical(
            rng_key, logits / jnp.maximum(temps, 1e-6)[:, None], axis=-1)
        nxt = jnp.where(temps > 0, sampled, greedy).astype(jnp.int32)
        nxt = jnp.where(active_mask, nxt, last_tokens)
        return nxt, fixed

    # ---- public API -------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: Optional[int] = None,
               temperature: float = 0.0) -> str:
        prompt = np.asarray(prompt_ids, dtype=np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        self._bucket(prompt.size)  # validate in the caller, not the loop
        budget = max_new_tokens or self.cfg.max_new_tokens_default
        if prompt.size + budget > self.cfg.max_seq_len:
            budget = self.cfg.max_seq_len - prompt.size
            if budget <= 0:
                raise ValueError(
                    f"prompt length {prompt.size} exceeds max_seq_len "
                    f"{self.cfg.max_seq_len}")
        req = _Request(request_id=f"req-{next(self._req_counter)}",
                       prompt=prompt, max_new_tokens=budget,
                       temperature=temperature)
        with self._lock:
            self._requests[req.request_id] = req
        self._waiting.put(req)
        return req.request_id

    def stream(self, request_id: str):
        """Blocking generator of token ids for one request."""
        req = self._requests.get(request_id)
        if req is None:
            raise KeyError(request_id)
        while True:
            kind, payload = req.out_queue.get()
            if kind == "token":
                yield payload
            elif kind == "error":
                raise payload
            else:  # end
                break
        with self._lock:
            self._requests.pop(request_id, None)

    def generate_sync(self, prompt_ids, max_new_tokens=None,
                      temperature: float = 0.0) -> List[int]:
        rid = self.submit(prompt_ids, max_new_tokens, temperature)
        return list(self.stream(rid))

    def get_stats(self) -> Dict[str, Any]:
        with self._lock:
            return {**self.stats, "active": len(self._active),
                    "waiting": self._waiting.qsize(),
                    "free_slots": len(self._free_slots)}

    def shutdown(self):
        self._shutdown.set()

    # ---- engine loop ------------------------------------------------------
    def _bucket(self, n: int) -> int:
        for b in self.cfg.prefill_buckets:
            if n <= b and b <= self.cfg.max_seq_len:
                return b
        raise ValueError(f"prompt length {n} exceeds largest prefill "
                         f"bucket {self.cfg.prefill_buckets[-1]}")

    def _admit_one(self) -> bool:
        jnp = self._jnp
        try:
            req = self._waiting.get_nowait()
        except queue_mod.Empty:
            return False
        slot = self._free_slots.pop()
        req.slot = slot
        try:
            pad_len = self._bucket(req.prompt.size)
            tokens = np.zeros((1, pad_len), np.int32)
            tokens[0, :req.prompt.size] = req.prompt
            last_logits, self._cache = self._prefill_jit(
                self.params, self._cache, jnp.asarray(tokens),
                jnp.int32(slot), jnp.int32(req.prompt.size),
                pad_len=pad_len)
            # first generated token comes straight from prefill logits
            if req.temperature > 0:
                self._rng_key, sub = self._jax.random.split(self._rng_key)
                tok = int(self._jax.random.categorical(
                    sub, last_logits / max(req.temperature, 1e-6)))
            else:
                tok = int(jnp.argmax(last_logits))
        except BaseException as e:  # noqa: BLE001
            self._free_slots.append(slot)
            req.slot = -1
            req.out_queue.put(("error", e))
            req.out_queue.put(_END)
            return True
        self.stats["prefills"] += 1
        req.first_token_ts = time.time()
        self._emit(req, tok)
        if req.generated < req.max_new_tokens:
            self._active[slot] = req
            self._last_tokens = self._last_tokens.at[slot].set(tok)
        else:
            self._release(req)
        return True

    def _emit(self, req: _Request, tok: int):
        req.generated += 1
        self.stats["tokens_generated"] += 1
        req.out_queue.put(("token", tok))
        if (self.cfg.eos_token_id is not None
                and tok == self.cfg.eos_token_id):
            req.max_new_tokens = req.generated  # finish after EOS

    def _release(self, req: _Request):
        req.out_queue.put(_END)
        if req.slot >= 0:
            self._free_slots.append(req.slot)
            self._active.pop(req.slot, None)
            req.slot = -1

    def _engine_loop(self):
        jnp = self._jnp
        S = self.cfg.max_slots
        while not self._shutdown.is_set():
            admitted = False
            try:
                while self._free_slots and self._admit_one():
                    admitted = True
            except BaseException:  # noqa: BLE001  loop must survive
                import traceback
                traceback.print_exc()
            if not self._active:
                if not admitted:
                    time.sleep(0.002)
                continue
            active_mask = np.zeros((S,), bool)
            temps = np.zeros((S,), np.float32)
            for slot, req in self._active.items():
                active_mask[slot] = True
                temps[slot] = req.temperature
            self._rng_key, sub = self._jax.random.split(self._rng_key)
            try:
                nxt, self._cache = self._decode_jit(
                    self.params, self._cache, self._last_tokens,
                    jnp.asarray(active_mask), jnp.asarray(temps), sub)
                self._last_tokens = nxt
                nxt_host = np.asarray(nxt)
            except BaseException as e:  # noqa: BLE001
                for req in list(self._active.values()):
                    req.out_queue.put(("error", e))
                    self._release(req)
                continue
            self.stats["decode_steps"] += 1
            for slot, req in list(self._active.items()):
                self._emit(req, int(nxt_host[slot]))
                full = (req.prompt.size + req.generated
                        >= self.cfg.max_seq_len)
                if req.generated >= req.max_new_tokens or full:
                    self._release(req)
