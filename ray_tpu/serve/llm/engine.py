"""JAX continuous-batching LLM engine.

Reference parity: the fork's vLLM-style serving path (continuous batching,
paged KV, streaming) — re-designed TPU-first:

* Slot-based KV cache: one preallocated HBM buffer per layer of shape
  (max_slots, max_seq_len, n_kv_heads, head_dim). Static shapes, so the
  decode step compiles ONCE and every subsequent step reuses it.
* Continuous batching: ONE jitted decode step advances ALL active slots
  together (the MXU sees batch=max_slots matmuls, not per-request calls).
  Requests join/leave between steps with no recompile.
* Prefill: prompts are padded to power-of-two buckets -> a handful of
  compiles total; KV is written straight into the request's slot via
  dynamic_update_slice.
* Sampling (greedy / temperature / global top-k / per-request nucleus
  top-p) happens on-device inside the jitted step; only the sampled
  token ids (max_slots int32) cross to host per step. Per-request stop
  token ids terminate a stream like EOS.
* Pipelined host loop: the loop runs `pipeline_depth` decode steps AHEAD
  of the host-side token fetch, with device->host copies started
  asynchronously (`copy_to_host_async`) at dispatch time. The device
  never waits on the host between steps, and fetch latency (which is
  ~65 ms over this image's TPU tunnel) overlaps with compute. Prefills
  dispatch back-to-back with no sync in between; the first token is
  sampled on-device inside the prefill and drains through the same
  pipeline. Termination decisions lag by `pipeline_depth` steps — at
  most that many wasted (discarded) tokens per finished request.
"""
from __future__ import annotations

import collections
import itertools
import queue as queue_mod
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from ...util import knobs


@dataclass
class LLMEngineConfig:
    max_slots: int = 8              # max concurrently-decoding sequences
    max_seq_len: int = 1024         # prompt + generation budget per slot
    prefill_buckets: tuple = (32, 64, 128, 256, 512, 1024)
    eos_token_id: Optional[int] = None
    max_new_tokens_default: int = 64
    top_k: int = 0                  # 0 = full softmax sampling
    # Decode steps dispatched ahead of the host-side token fetch. The
    # steady-state step period is roughly fetch_latency/(depth+1) (each
    # iteration drains the entry dispatched `depth` steps ago), so depth
    # trades termination lag (≤ depth*decode_block discarded tokens per
    # finished request) against hiding device->host latency — 66 ms over
    # this image's TPU tunnel.
    pipeline_depth: int = 10
    # Decode steps fused into ONE dispatch via lax.scan: each dispatch
    # emits decode_block tokens per slot, dividing per-token host work
    # (dispatch + mask/rng prep + fetch) by the block size. 1 = the
    # classic one-token step.
    decode_block: int = 1
    # Waiting prompts that share a length bucket prefill TOGETHER in one
    # jitted call of up to this many rows (padded to a power of two via a
    # scratch cache slot) — one dispatch and one model pass instead of
    # per-prompt calls. 1 disables batching.
    max_prefill_batch: int = 4
    # Chunked prefill (vLLM-style): prompts longer than this split into
    # prefill_chunk-token chunks, one chunk dispatched per engine-loop
    # iteration, so active decodes keep stepping DURING a long prompt's
    # prefill instead of stalling behind one monolithic call.
    # 0 disables chunking.
    prefill_chunk: int = 0
    # Fetch each sampled token's log-probability (of the raw model
    # distribution) to the host and expose it via stream_detailed().
    # Off by default: it adds one small device->host array per step.
    logprobs: bool = False
    # Compile every prefill bucket + the decode step during __init__
    # (blocking) so the first real request never pays a jit compile —
    # the dominant term in cold TTFT (seconds even for toy models).
    precompile: bool = False
    # Prefix caching (vLLM's automatic-prefix-caching, made explicit
    # and static-shape for TPU): register_prefix() prefills a shared
    # prompt prefix ONCE into a dedicated KV buffer; submits carrying
    # prefix_id adopt it with one on-device copy and prefill only
    # their suffix. 0 disables (no buffer allocated).
    # With kv_page_size > 0 there is NO dedicated buffer: a registered
    # prefix is pinned shared pages in the pool; adoption shares its
    # full pages by page-table reference (zero copy) and copies only
    # the final partial page.
    max_prefixes: int = 0
    # Paged KV cache (VERDICT r4 #4; vLLM's PagedAttention, TPU-first).
    # 0 = legacy contiguous per-slot (max_slots x max_seq_len) buffers.
    # >0 = a shared page pool: per-layer flat (n_pages * page_size)
    # token rows + per-slot page tables (static shapes — decode still
    # compiles once; see ops/attention.py:paged_cached_attention).
    # Slots reserve ceil((prompt+budget)/page_size) pages at admission,
    # so short requests no longer strand max_seq_len of HBM each and
    # concurrency is bounded by the real token budget, not slot count.
    kv_page_size: int = 0
    # Total pool budget in KV tokens (rounded up to whole pages).
    # 0 = max_slots * max_seq_len (same HBM as the legacy layout).
    kv_pool_tokens: int = 0
    # n-gram (prompt-lookup) speculative decoding: propose K tokens per
    # step by matching the trailing `ngram_order`-gram against the
    # request's own prompt+generation history, verify all K in ONE
    # forward (in-jit prefix acceptance), emit 1..K+1 tokens per
    # dispatch. Decode is weight-bandwidth-bound, so accepted tokens
    # amortize a full weight read — repetitive text (summaries, code,
    # RAG) decodes up to (1+K)x faster. Greedy (temp==0), non-guided
    # requests only; output is token-identical to plain decode.
    # Speculative traffic steps synchronously (proposals need the
    # previous step's tokens). 0 disables.
    ngram_speculation: int = 0
    ngram_order: int = 2
    # proposal lookback window (tokens of trailing history searched per
    # step, vLLM prompt-lookup style) — bounds host work per step
    ngram_lookback: int = 256
    # Wedged-engine watchdog: if the generation loop makes no forward
    # progress (no admit, no dispatch, no token drained) for this long
    # WHILE requests are admitted/waiting, the engine is declared
    # wedged — in-flight requests abort with EngineWedgedError (so the
    # serve handle can fail over) and health checks fail with a
    # `wedged` cause until the replica is replaced. None reads
    # RAY_TPU_ENGINE_WATCHDOG_S (default 30); <= 0 disables.
    watchdog_s: Optional[float] = None


@dataclass
class _Request:
    request_id: str
    prompt: np.ndarray              # (P,) int32
    max_new_tokens: int
    temperature: float
    top_p: float = 1.0
    stop_ids: frozenset = frozenset()
    out_queue: queue_mod.Queue = field(
        default_factory=lambda: queue_mod.Queue(maxsize=4096))
    slot: int = -1
    generated: int = 0
    aborted: bool = False
    prefix_id: int = -1             # registered-prefix KV to adopt
    prefill_pos: int = 0            # next prompt index (chunked prefill)
    submit_ts: float = field(default_factory=time.time)
    admit_ts: Optional[float] = None       # slot assigned
    prefill_dispatch_ms: float = 0.0       # host time in the prefill
                                           # call (compile on first use)
    first_token_ts: Optional[float] = None
    # guided decoding (serve/llm/guided.py): host-side token FSM whose
    # per-state vocab mask constrains sampling; state advances at emit
    fsm: Optional[object] = None
    fsm_state: int = 0
    # n-gram speculation: prompt+generated history (proposal source);
    # None when this request is ineligible (sampled/guided)
    hist: Optional[list] = None
    # sampling penalties (OpenAI semantics): subtract presence once and
    # frequency*count per occurrence of a GENERATED token; logit_bias is
    # a static {token_id: float} addend. Counts live ON DEVICE and
    # update in-jit from last_tokens, so pipelining is preserved.
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    logit_bias: Optional[dict] = None
    # absolute deadline propagated from the serve plane; a request
    # whose deadline expires while still QUEUED is shed at admission
    # (DeadlineExceededError) instead of executed
    deadline_ts: Optional[float] = None


_END = ("__end__", None)

_engine_ids = itertools.count()
_metrics_singletons = None


def _engine_metrics():
    """Shared built-in registry metrics, resolved through the catalog
    (util/metrics_catalog.py) so names stay `ray_tpu_`-prefixed and
    documented in one place. Per-engine series ride the `engine` tag —
    re-instantiating per engine would clobber the registry entry and
    drop earlier engines' series. A cleared registry (tests do that)
    is detected and the metrics re-register fresh."""
    global _metrics_singletons
    from ...util import metrics as metrics_mod  # noqa: PLC0415
    from ...util import metrics_catalog as mcat  # noqa: PLC0415
    if (_metrics_singletons is not None
            and metrics_mod.get_metric(
                "ray_tpu_llm_engine_tokens_generated")
            is not _metrics_singletons["tokens"]):
        _metrics_singletons = None
    if _metrics_singletons is None:
        _metrics_singletons = {
            "tokens": mcat.get("ray_tpu_llm_engine_tokens_generated"),
            "active": mcat.get("ray_tpu_llm_engine_active_slots"),
            "waiting": mcat.get("ray_tpu_llm_engine_waiting_requests"),
            "occupancy": mcat.get("ray_tpu_llm_engine_batch_occupancy"),
            "kv_util": mcat.get(
                "ray_tpu_llm_engine_kv_page_utilization"),
            "ttft": mcat.get("ray_tpu_llm_engine_ttft_s"),
            "tpot": mcat.get("ray_tpu_llm_engine_tpot_s"),
        }
    return _metrics_singletons



def _put_dropping_one(q: "queue_mod.Queue", item) -> None:
    """Publish a control item (_END / wedged error) to a possibly-full
    out_queue without ever blocking the engine loop: on Full, drop one
    buffered token to make room. Single producer (the loop), so the
    retry cannot race another put; a second Full means the consumer
    raced a get between our get and put — then the queue has room on
    the next consumer cycle anyway and the item is dropped."""
    try:
        q.put_nowait(item)
        return
    except queue_mod.Full:
        pass
    try:
        q.get_nowait()
    except queue_mod.Empty:
        pass
    try:
        q.put_nowait(item)
    except queue_mod.Full:
        pass


def _next_pow2(n: int) -> int:
    """Smallest power of two >= max(n, 1) (bucketing helper: prefill
    group sizes, prefix pads, decode page windows)."""
    p = 1
    while p < n:
        p *= 2
    return p


class LLMEngine:
    """Continuous-batching engine over a ray_tpu Llama-family model.

    `model` must follow the ray_tpu/models/llama.py contract:
    apply({"params": params}, tokens, cache=..., positions=...) ->
    (logits, new_cache) with cache = [per-layer (k, v, lengths)].
    """

    def __init__(self, model, params, cfg: LLMEngineConfig):
        import jax
        import jax.numpy as jnp
        self._jax, self._jnp = jax, jnp
        self.model = model
        self.params = params
        self.cfg = cfg
        mcfg = model.cfg
        if cfg.eos_token_id is None:
            cfg.eos_token_id = getattr(mcfg, "eos_token_id", None)
        model_max = getattr(mcfg, "max_seq_len", None)
        if model_max is not None and cfg.max_seq_len > model_max:
            # absolute-position models (GPT-2's learned wpe) would
            # silently reuse their last embedding past this; fail loudly
            raise ValueError(
                f"engine max_seq_len {cfg.max_seq_len} exceeds the "
                f"model's max_seq_len {model_max}")
        S, L = cfg.max_slots, cfg.max_seq_len
        self._paged = cfg.kv_page_size > 0
        # +1 scratch slot when prefill batching is on: padding rows of a
        # batched prefill write their KV there; it is never admitted, so
        # its garbage never decodes. With batching off there is no
        # scratch row (decode pays no extra-slot work). Paged engines
        # always keep it (costs one page-table row, not a KV row): it
        # anchors batch-padding writes AND prefix registration prefills.
        self._n_slots = (S + 1 if (cfg.max_prefill_batch > 1
                                   or self._paged) else S)
        self._scratch_slot = S
        if self._paged:
            ps = cfg.kv_page_size
            # per-slot gather width: whole pages covering max_seq_len
            self._pages_per_slot = -(-L // ps)
            pool_tokens = cfg.kv_pool_tokens or S * L
            # the configured budget is honored exactly (rounded up to a
            # page): oversized requests fail fast at submit() instead of
            # silently inflating the pool
            self._n_pages = max(1, -(-pool_tokens // ps))
            self._trash_page = self._n_pages  # extra page: writes by
            # released/padding slots land here and are never read valid
            n_flat = (self._n_pages + 1) * ps
            self._pools = [
                (jnp.zeros((n_flat, mcfg.n_kv_heads, mcfg.head_dim),
                           mcfg.dtype),
                 jnp.zeros((n_flat, mcfg.n_kv_heads, mcfg.head_dim),
                           mcfg.dtype))
                for _ in range(mcfg.n_layers)]
            self._page_table = jnp.full(
                (self._n_slots, self._pages_per_slot),
                self._trash_page, jnp.int32)
            self._lengths = jnp.zeros((self._n_slots,), jnp.int32)
            # host-side allocator
            self._free_pages: List[int] = list(range(self._n_pages))
            # slot -> (n_shared_prefix_pages, [all pages in table order])
            self._slot_pages: Dict[int, tuple] = {}
            self._prefix_pages: Dict[int, List[int]] = {}
            self._pending_head: Optional[_Request] = None
            self._page_hwm = 0      # peak pages in use (stats)
            self._cache = None
        else:
            self._cache = [
                (jnp.zeros((self._n_slots, L, mcfg.n_kv_heads,
                            mcfg.head_dim), mcfg.dtype),
                 jnp.zeros((self._n_slots, L, mcfg.n_kv_heads,
                            mcfg.head_dim), mcfg.dtype),
                 jnp.zeros((self._n_slots,), jnp.int32))
                for _ in range(mcfg.n_layers)]
        self._last_tokens = jnp.zeros((self._n_slots,), jnp.int32)
        self._free_slots = list(range(S))
        self._active: Dict[int, _Request] = {}
        self._waiting: "queue_mod.Queue[_Request]" = queue_mod.Queue()
        self._requests: Dict[str, _Request] = {}
        self._req_counter = itertools.count()
        self._lock = threading.Lock()
        self._rng_key = jax.random.PRNGKey(0)
        self._mask_dev = None
        self._temps_dev = None
        self._top_ps_dev = None
        self._guided_allow_buf = None
        self._guided_prev = None
        self._spec_idle = 0
        self._spec_retry = 0
        # penalties: device-resident per-slot token-count + static-bias
        # matrices, allocated on first use; seeded per slot assignment
        self._pen_counts = None
        self._pen_static = None
        self._pen_seeded: Dict[int, str] = {}
        self._pen_coef_dev = None
        self._pen_coef_dirty = True
        self._mask_dirty = True
        self._shutdown = threading.Event()
        # no "preempted" stat: slots are statically sized for
        # prompt+budget at admission, so mid-stream KV eviction (vLLM's
        # preemption trigger) cannot occur by construction
        self.stats = {"prefills": 0, "decode_steps": 0,
                      "tokens_generated": 0, "prefix_tokens_saved": 0}
        # TTFT breakdown (VERDICT r4 ask): queue wait vs prefill
        # dispatch (compile on a bucket's first use) vs emit lag.
        self._ttft_samples: collections.deque = collections.deque(
            maxlen=512)
        # recent per-request mean time-per-output-token (seconds) —
        # feeds get_stats()["tpot_p50_ms"] and through it the serve
        # autoscaler's tpot_slo_ms term
        self._tpot_samples: collections.deque = collections.deque(
            maxlen=512)
        self._prefill_compile_ms: Dict[int, float] = {}  # bucket -> ms
        # surfaced on the shared metrics registry (/metrics, dashboard);
        # one labeled series per engine instance. The dict is cached
        # here and refreshed once per engine-loop step — the per-token
        # emit path must not take the registry lock for clear-detection
        self._mtags = {"engine": f"llm-{next(_engine_ids)}"}
        self._m = _engine_metrics()

        # lifecycle events (util/events.py): request admit / preempt /
        # finish / abort land on the cluster event plane — when this
        # engine runs inside an actor the worker's telemetry flush
        # ships them to the driver like sys.metrics
        def _event(etype, message="", req=None, **attrs):
            try:
                from ...util import events as events_mod  # noqa: PLC0415
                events_mod.emit(
                    etype, message,
                    request_id=req.request_id if req is not None
                    else None,
                    engine=self._mtags["engine"], **attrs)
            except Exception:
                pass
        self._event = _event

        # prefix cache: per layer (n_prefixes, L, Hkv, D) k/v + host-side
        # token records; written by register_prefix, read (copied into a
        # slot) at admission of prefix-carrying requests
        self._prefix_cache = None
        self._prefixes: Dict[int, np.ndarray] = {}   # pid -> tokens
        self._prefix_counter = itertools.count()
        if cfg.max_prefixes > 0 and not self._paged:
            # +1 scratch row: precompile() warms fill/adopt/chunk paths
            # by EXECUTING a dummy prefix'd request against it (AOT
            # lower().compile() does not populate the jit call cache)
            self._prefix_cache = [
                (jnp.zeros((cfg.max_prefixes + 1, L, mcfg.n_kv_heads,
                            mcfg.head_dim), mcfg.dtype),
                 jnp.zeros((cfg.max_prefixes + 1, L, mcfg.n_kv_heads,
                            mcfg.head_dim), mcfg.dtype))
                for _ in range(mcfg.n_layers)]
            self._prefix_fill_jit = jax.jit(
                self._prefix_fill_impl, static_argnames=("pad_len",))
            self._adopt_prefix_jit = jax.jit(
                self._adopt_prefix_impl, donate_argnums=(0,))

        self._prefilling: collections.deque = collections.deque()
        self._prefill_jit = jax.jit(
            self._prefill_impl, static_argnames=("pad_len",),
            donate_argnums=(1,))
        self._prefill_chunk_jit = jax.jit(
            self._prefill_chunk_impl,
            static_argnames=("chunk", "sample"), donate_argnums=(1,))
        self._prefill_batch_jit = jax.jit(
            self._prefill_batch_impl, static_argnames=("pad_len",),
            donate_argnums=(1,))
        self._decode_jit = jax.jit(self._decode_impl, donate_argnums=(1,))
        self._verify_jit = jax.jit(self._verify_impl, donate_argnums=(1,))
        self._decode_block_jit = (
            jax.jit(self._decode_block_impl, donate_argnums=(1,))
            if cfg.decode_block > 1 else None)
        if self._paged:
            self._prefill_paged_jit = jax.jit(
                self._prefill_paged_impl, static_argnames=("pad_len",),
                donate_argnums=(1, 3))
            self._chunk_paged_jit = jax.jit(
                self._chunk_paged_impl,
                static_argnames=("chunk", "sample"), donate_argnums=(1, 3))
            self._decode_paged_jit = jax.jit(
                self._decode_paged_impl, donate_argnums=(1, 3),
                static_argnames=("window_pages",))
            self._verify_paged_jit = jax.jit(
                self._verify_paged_impl, donate_argnums=(1, 3),
                static_argnames=("window_pages",))
            self._decode_block_paged_jit = (
                jax.jit(self._decode_block_paged_impl,
                        donate_argnums=(1, 3),
                        static_argnames=("window_pages",))
                if cfg.decode_block > 1 else None)
            # host mirror of each slot's device length: picks the
            # power-of-2 page window covering the longest active
            # sequence at decode-dispatch time
            self._disp_len: Dict[int, int] = {}
            self._copy_page_jit = jax.jit(self._copy_page_impl,
                                          donate_argnums=(0,))
        # register_prefix (paged) must mutate the pools on the engine
        # loop thread — its dispatches donate them, so a concurrent
        # public-API mutation would race a stale buffer. Commands queue
        # here and the loop executes them between steps.
        self._control_q: "queue_mod.Queue" = queue_mod.Queue()
        # wedged-engine watchdog: _progress_ts advances on every admit /
        # token emit / idle tick; a separate thread observes staleness
        # (the loop thread itself may be stuck inside a device call, so
        # it cannot self-report)
        if cfg.watchdog_s is not None:
            self._watchdog_s = float(cfg.watchdog_s)
        else:
            self._watchdog_s = knobs.get_float(
                "RAY_TPU_ENGINE_WATCHDOG_S")
        self._progress_ts = time.time()
        self._wedged_since: Optional[float] = None
        # True while the loop thread is inside the admit/dispatch/drain
        # work section: a stall there can be a legitimate first-use jit
        # COMPILE (seconds..minutes for big models), so the watchdog
        # grants it _DISPATCH_GRACE x the budget. Host-side stalls —
        # a stuck control command, a lock deadlock, the loop wedged
        # between iterations — get the tight watchdog_s budget.
        self._in_dispatch = False
        self._loop_thread = threading.Thread(
            target=self._engine_loop, daemon=True, name="llm-engine")
        self._loop_thread.start()
        if self._watchdog_s > 0:
            self._watchdog_thread = threading.Thread(
                target=self._watchdog_loop, daemon=True,
                name="llm-engine-watchdog")
            self._watchdog_thread.start()
        if cfg.precompile:
            self.precompile()

    # ---- jitted kernels ---------------------------------------------------
    def _pen_bias(self, pen, last_tokens, active_mask):
        """In-jit penalty bias for one decode step. pen = (counts (S,V)
        i32 DEVICE state, static_bias (S,V) f32, presence (S,), freq
        (S,)). The previously-emitted token (last_tokens — incl. the
        prefill's first token) is counted here, so every generated
        token influences penalties from the NEXT step on, with no host
        round-trip: pipelining is preserved. The counts input is NOT
        donated (it shares one kwarg tuple with static_bias, which must
        survive across steps), so each penalized step allocates a fresh
        (S, V) i32 output — ~1 MB at 8x32k; split counts into its own
        donated arg if this ever shows at scale. Returns (bias or None,
        updated counts or None)."""
        if pen is None:
            return None, None
        jnp = self._jnp
        counts, static_bias, presence, freq = pen
        S = counts.shape[0]
        inc = active_mask.astype(counts.dtype)
        counts = counts.at[jnp.arange(S), last_tokens].add(inc)
        bias = (static_bias
                - presence[:, None] * (counts > 0)
                - freq[:, None] * counts)
        return bias, counts
    def _sample_tokens(self, logits, temps, top_ps, rng_key, allow=None,
                       bias=None):
        """Sample per row of logits (N, V): greedy when temp==0, else
        temperature + optional global top-k + per-row nucleus top-p.
        All on device; returns (tokens (N,) int32, logprobs (N,) f32 of
        the chosen token under the RAW model distribution).

        allow (N, V) bool, optional: guided-decoding mask — tokens
        outside it are impossible under every sampling mode (reported
        logprobs stay raw-model). bias (N, V) float, optional: additive
        logit adjustments (logit_bias + presence/frequency penalties).
        None at trace time keeps the plain compile identical."""
        jnp = self._jnp
        jax = self._jax
        # cfg.logprobs is a plain Python bool at trace time: disabled
        # engines compile WITHOUT the full-vocab log_softmax + gather
        raw_logp = (jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
                    if self.cfg.logprobs else None)
        if bias is not None:
            logits = logits + bias.astype(logits.dtype)
        if allow is not None:
            logits = jnp.where(allow, logits, -jnp.inf)
        if self.cfg.top_k and self.cfg.top_k > 0:
            kth = jnp.sort(logits, axis=-1)[:, -self.cfg.top_k][:, None]
            logits = jnp.where(logits < kth, -jnp.inf, logits)
        greedy = jnp.argmax(logits, axis=-1)
        scaled = logits / jnp.maximum(temps, 1e-6)[:, None]

        def nucleus(scaled):
            # smallest prefix of the prob-sorted vocab whose mass reaches
            # top_p (always keeps the argmax)
            n, _v = scaled.shape
            sort_idx = jnp.argsort(-scaled, axis=-1)
            sorted_probs = jax.nn.softmax(
                jnp.take_along_axis(scaled, sort_idx, axis=-1), axis=-1)
            cum = jnp.cumsum(sorted_probs, axis=-1)
            keep_sorted = (cum - sorted_probs) < top_ps[:, None]
            keep = jnp.zeros_like(keep_sorted).at[
                jnp.arange(n)[:, None], sort_idx].set(keep_sorted)
            use_top_p = (top_ps < 1.0)[:, None]
            return jnp.where(use_top_p & ~keep, -jnp.inf, scaled)

        # the full-vocab sort only runs when some active request asked
        # for top_p < 1 — the default path stays argmax + categorical
        scaled = jax.lax.cond(jnp.any(top_ps < 1.0), nucleus,
                              lambda s: s, scaled)
        sampled = jax.random.categorical(rng_key, scaled, axis=-1)
        toks = jnp.where(temps > 0, sampled, greedy).astype(jnp.int32)
        if raw_logp is None:
            logps = jnp.zeros(toks.shape, jnp.float32)
        else:
            logps = jnp.take_along_axis(raw_logp, toks[:, None],
                                        axis=-1)[:, 0]
        return toks, logps

    def _prefill_impl(self, params, cache, tokens, slot, true_len, temp,
                      top_p, rng_key, pad_len: int, allow=None,
                      bias=None):
        """Run the prompt through the model writing KV into `slot`, and
        sample the first generated token ON DEVICE (no host sync).
        tokens: (1, pad_len); returns (token () int32, cache')."""
        jnp = self._jnp
        jax = self._jax
        lax = jax.lax
        # slice this slot's rows out of the big cache
        small = []
        for (ck, cv, lens) in cache:
            k1 = lax.dynamic_slice_in_dim(ck, slot, 1, axis=0)
            v1 = lax.dynamic_slice_in_dim(cv, slot, 1, axis=0)
            small.append((k1, v1, jnp.zeros((1,), jnp.int32)))
        positions = jnp.arange(pad_len)[None, :]
        logits, new_small = self.model.apply(
            {"params": params}, tokens, cache=small, positions=positions)
        out_cache = []
        for (ck, cv, lens), (k1, v1, _l1) in zip(cache, new_small):
            ck = lax.dynamic_update_slice_in_dim(ck, k1, slot, axis=0)
            cv = lax.dynamic_update_slice_in_dim(cv, v1, slot, axis=0)
            lens = lens.at[slot].set(true_len)
            out_cache.append((ck, cv, lens))
        last = logits[0, true_len - 1]
        toks, logps = self._sample_tokens(last[None, :], temp[None],
                                          top_p[None], rng_key,
                                          allow=allow, bias=bias)
        return toks[0], logps[0], out_cache

    def _prefill_chunk_impl(self, params, cache, tokens, slot, start,
                            new_len, temp, top_p, rng_key,
                            chunk: int, sample: bool, allow=None,
                            bias=None):
        """One chunk of a long prompt through the CACHED path: tokens
        (1, chunk) written at positions [start, start+chunk); the slot's
        length becomes `new_len` (start + true tokens in this chunk, so
        tail padding of the final chunk stays invisible — pad queries
        only ever attend pad keys and their outputs are discarded).
        sample=True (final chunk) also samples the first generated token
        from the last true position."""
        jnp = self._jnp
        jax = self._jax
        lax = jax.lax
        small = []
        # The slot's true current length IS `start` — a reused slot's
        # stored length would be stale from the previous occupant and
        # leak its KV into the chunk's valid-mask.
        l1 = jnp.reshape(start, (1,)).astype(jnp.int32)
        for (ck, cv, lens) in cache:
            k1 = lax.dynamic_slice_in_dim(ck, slot, 1, axis=0)
            v1 = lax.dynamic_slice_in_dim(cv, slot, 1, axis=0)
            small.append((k1, v1, l1))
        positions = start + jnp.arange(chunk)[None, :]
        logits, new_small = self.model.apply(
            {"params": params}, tokens, cache=small, positions=positions)
        out_cache = []
        for (ck, cv, lens), (k1, v1, _l1) in zip(cache, new_small):
            ck = lax.dynamic_update_slice_in_dim(ck, k1, slot, axis=0)
            cv = lax.dynamic_update_slice_in_dim(cv, v1, slot, axis=0)
            lens = lens.at[slot].set(new_len)
            out_cache.append((ck, cv, lens))
        if not sample:
            return jnp.int32(0), jnp.float32(0), out_cache
        last = logits[0, new_len - start - 1]
        toks, logps = self._sample_tokens(last[None, :], temp[None],
                                          top_p[None], rng_key,
                                          allow=allow, bias=bias)
        return toks[0], logps[0], out_cache

    def _prefill_batch_impl(self, params, cache, tokens, slots, true_lens,
                            temps, top_ps, rng_key, pad_len: int,
                            allow=None, bias=None):
        """Prefill G prompts of one length bucket in a single model pass.
        tokens: (G, pad_len); slots/true_lens/temps: (G,). Padding rows
        target the scratch slot. Returns (tokens (G,) int32, cache')."""
        jnp = self._jnp
        jax = self._jax
        g = tokens.shape[0]
        mcfg = self.model.cfg
        small = [(jnp.zeros((g, pad_len, mcfg.n_kv_heads, mcfg.head_dim),
                            mcfg.dtype),
                  jnp.zeros((g, pad_len, mcfg.n_kv_heads, mcfg.head_dim),
                            mcfg.dtype),
                  jnp.zeros((g,), jnp.int32))
                 for _ in range(mcfg.n_layers)]
        positions = jnp.broadcast_to(jnp.arange(pad_len)[None, :],
                                     (g, pad_len))
        logits, new_small = self.model.apply(
            {"params": params}, tokens, cache=small, positions=positions)
        out_cache = []
        for (ck, cv, lens), (k1, v1, _l1) in zip(cache, new_small):
            # scatter each row's KV into its slot (duplicate scratch
            # indices from padding rows are harmless: slot is inert)
            ck = ck.at[slots, :pad_len].set(k1)
            cv = cv.at[slots, :pad_len].set(v1)
            lens = lens.at[slots].set(true_lens)
            out_cache.append((ck, cv, lens))
        last = logits[jnp.arange(g), true_lens - 1]          # (G, V)
        toks, logps = self._sample_tokens(last, temps, top_ps, rng_key,
                                          allow=allow, bias=bias)
        return toks, logps, out_cache

    def _prefix_fill_impl(self, params, prefix_cache, tokens, pid,
                          pad_len: int):
        """Prefill a registered prefix into row `pid` of the prefix KV
        buffers. tokens: (1, pad_len). NOT donated: concurrent adopts
        of other prefixes keep reading the old buffer safely."""
        jnp = self._jnp
        mcfg = self.model.cfg
        small = [(jnp.zeros((1, pad_len, mcfg.n_kv_heads,
                             mcfg.head_dim), mcfg.dtype),
                  jnp.zeros((1, pad_len, mcfg.n_kv_heads,
                             mcfg.head_dim), mcfg.dtype),
                  jnp.zeros((1,), jnp.int32))
                 for _ in range(mcfg.n_layers)]
        positions = jnp.arange(pad_len)[None, :]
        _logits, new_small = self.model.apply(
            {"params": params}, tokens, cache=small,
            positions=positions)
        out = []
        for (pk, pv), (k1, v1, _l) in zip(prefix_cache, new_small):
            pk = pk.at[pid, :pad_len].set(k1[0])
            pv = pv.at[pid, :pad_len].set(v1[0])
            out.append((pk, pv))
        return out

    def _adopt_prefix_impl(self, cache, prefix_cache, slot, pid, plen):
        """Copy prefix `pid`'s KV into `slot` and set its length to
        `plen` — the whole point: a shared system prompt costs ONE
        on-device copy per request instead of a re-prefill."""
        jax = self._jax
        lax = jax.lax
        out = []
        for (ck, cv, lens), (pk, pv) in zip(cache, prefix_cache):
            row_k = lax.dynamic_slice_in_dim(pk, pid, 1, axis=0)
            row_v = lax.dynamic_slice_in_dim(pv, pid, 1, axis=0)
            ck = lax.dynamic_update_slice_in_dim(ck, row_k, slot, axis=0)
            cv = lax.dynamic_update_slice_in_dim(cv, row_v, slot, axis=0)
            lens = lens.at[slot].set(plen)
            out.append((ck, cv, lens))
        return out

    # ---- paged-KV kernels (cfg.kv_page_size > 0) --------------------------
    def _paged_entries(self, pools, page_table, lengths):
        """Per-layer PagedKV cache entries over the shared pool. The
        gather/scatter happens INSIDE each layer's attention, so only
        one layer's contiguous view is ever live at a time."""
        from ...ops.attention import PagedKV  # noqa: PLC0415
        return [PagedKV(k, v, page_table, lengths, self.cfg.kv_page_size)
                for (k, v) in pools]

    def _prefill_paged_impl(self, params, pools, page_table, lengths,
                            tokens, slots, true_lens, temps, top_ps,
                            rng_key, pad_len: int, allow=None,
                            bias=None):
        """Prefill G prompts (single and batched unified): KV streams
        straight into each slot's pages — no small-cache copy-back.
        tokens: (G, pad_len); slots/true_lens/temps/top_ps: (G,).
        Padding rows target the scratch slot, whose page-table row is
        all-trash, so their writes vanish by construction."""
        jnp = self._jnp
        ps = self.cfg.kv_page_size
        g = tokens.shape[0]
        rows = page_table[slots]                   # (G, P)
        rows_p = rows[:, :-(-pad_len // ps)]       # pages covering pad
        from ...ops.attention import PagedKV  # noqa: PLC0415
        # fresh=True: pure prefill — attention runs straight over the
        # prompt (flash-eligible on TPU), no page gather; KV still
        # scatters into the pages
        entries = [PagedKV(k, v, rows_p, jnp.zeros((g,), jnp.int32),
                           ps, fresh=True)
                   for (k, v) in pools]
        positions = jnp.broadcast_to(jnp.arange(pad_len)[None, :],
                                     (g, pad_len))
        logits, new_entries = self.model.apply(
            {"params": params}, tokens, cache=entries,
            positions=positions)
        new_pools = [(e.k_flat, e.v_flat) for e in new_entries]
        lengths = lengths.at[slots].set(true_lens)
        last = logits[jnp.arange(g), true_lens - 1]
        toks, logps = self._sample_tokens(last, temps, top_ps, rng_key,
                                          allow=allow, bias=bias)
        return toks, logps, new_pools, lengths

    def _chunk_paged_impl(self, params, pools, page_table, lengths,
                          tokens, slot, start, new_len, temp, top_p,
                          rng_key, chunk: int, sample: bool,
                          allow=None, bias=None):
        """One chunk of a long prompt (paged): gathers the slot's full
        page row (start is dynamic, so the attention window cannot be
        statically narrowed the way bucketed prefill narrows it)."""
        jnp = self._jnp
        jax = self._jax
        ps = self.cfg.kv_page_size
        row = jax.lax.dynamic_slice_in_dim(page_table, slot, 1, axis=0)
        from ...ops.attention import PagedKV  # noqa: PLC0415
        l1 = jnp.reshape(start, (1,)).astype(jnp.int32)
        entries = [PagedKV(k, v, row, l1, ps) for (k, v) in pools]
        positions = start + jnp.arange(chunk)[None, :]
        logits, new_entries = self.model.apply(
            {"params": params}, tokens, cache=entries,
            positions=positions)
        new_pools = [(e.k_flat, e.v_flat) for e in new_entries]
        lengths = lengths.at[slot].set(new_len)
        if not sample:
            return jnp.int32(0), jnp.float32(0), new_pools, lengths
        last = logits[0, new_len - start - 1]
        toks, logps = self._sample_tokens(last[None, :], temp[None],
                                          top_p[None], rng_key,
                                          allow=allow, bias=bias)
        return toks[0], logps[0], new_pools, lengths

    def _decode_paged_impl(self, params, pools, page_table, lengths,
                           last_tokens, active_mask, temps, top_ps,
                           rng_key, window_pages: int = 0, allow=None,
                           pen=None):
        """One decode step for every slot over the page pool. Released
        slots' page-table rows point at the trash page, so their writes
        are inert; inactive lengths are restored so state never
        drifts.

        window_pages > 0 statically narrows the attention window to the
        first `window_pages` page-table columns (a power-of-2 bucket
        covering the longest ACTIVE sequence, host-tracked): decode
        cost then scales with real lengths, not max_seq_len — the
        XLA-gather path's analog of the Pallas kernel's page skipping.
        """
        jnp = self._jnp
        if window_pages and window_pages < page_table.shape[1]:
            page_table = page_table[:, :window_pages]
        entries = self._paged_entries(pools, page_table, lengths)
        positions = lengths[:, None]
        logits, new_entries = self.model.apply(
            {"params": params}, last_tokens[:, None], cache=entries,
            positions=positions)
        logits = logits[:, 0, :]
        new_pools = [(e.k_flat, e.v_flat) for e in new_entries]
        new_lengths = jnp.where(active_mask, new_entries[0].lengths,
                                lengths)
        bias, new_counts = self._pen_bias(pen, last_tokens, active_mask)
        nxt, logps = self._sample_tokens(logits, temps, top_ps, rng_key,
                                         allow=allow, bias=bias)
        nxt = jnp.where(active_mask, nxt, last_tokens)
        if pen is not None:
            return nxt, logps, new_pools, new_lengths, new_counts
        return nxt, logps, new_pools, new_lengths

    def _decode_block_paged_impl(self, params, pools, page_table,
                                 lengths, last_tokens, active_mask,
                                 temps, top_ps, rng_key,
                                 window_pages: int = 0):
        jax = self._jax
        keys = jax.random.split(rng_key, self.cfg.decode_block)

        def body(carry, key):
            pools, lengths, last = carry
            nxt, logps, pools, lengths = self._decode_paged_impl(
                params, pools, page_table, lengths, last, active_mask,
                temps, top_ps, key, window_pages=window_pages)
            return (pools, lengths, nxt), (nxt, logps)

        (pools, lengths, last), (toks, logps) = jax.lax.scan(
            body, (pools, lengths, last_tokens), keys)
        return toks, logps, pools, lengths, last

    def _copy_page_impl(self, pools, src_page, dst_page):
        """Copy one page's k/v rows in every layer — the only device
        copy prefix adoption pays (its final PARTIAL page; full pages
        are shared by page-table reference)."""
        lax = self._jax.lax
        ps = self.cfg.kv_page_size
        out = []
        for (k, v) in pools:
            rk = lax.dynamic_slice_in_dim(k, src_page * ps, ps, axis=0)
            rv = lax.dynamic_slice_in_dim(v, src_page * ps, ps, axis=0)
            k = lax.dynamic_update_slice_in_dim(k, rk, dst_page * ps,
                                                axis=0)
            v = lax.dynamic_update_slice_in_dim(v, rv, dst_page * ps,
                                                axis=0)
            out.append((k, v))
        return out

    def _verify_impl(self, params, cache, last_tokens, proposals,
                     active_mask, temps, top_ps, rng_key):
        """n-gram speculation verify (contiguous cache): ONE forward of
        [last, p1..pK] per slot; in-jit greedy prefix acceptance.
        proposals (S, K) int32, -1 = no proposal at that offset.
        Returns (out (S, K+1), n_emit (S,), logps (S, K+1), cache',
        last') — emit out[s, :n_emit[s]]. Rejected positions' KV is
        invisible (attention masks by length) and overwritten by later
        writes at the same positions."""
        jnp = self._jnp
        jax = self._jax
        K = proposals.shape[1]
        old_lengths = cache[0][2]
        toks_in = jnp.concatenate(
            [last_tokens[:, None], jnp.maximum(proposals, 0)], axis=1)
        positions = old_lengths[:, None] + jnp.arange(K + 1)[None, :]
        logits, new_cache = self.model.apply(
            {"params": params}, toks_in, cache=cache,
            positions=positions)                       # (S, K+1, V)
        out, n_emit, logps, last = self._verify_accept(
            logits, proposals, last_tokens, active_mask, temps, top_ps,
            rng_key)
        new_len = old_lengths + n_emit
        fixed = [(ck, cv, new_len) for (ck, cv, _l) in new_cache]
        return out, n_emit, logps, fixed, last

    def _verify_paged_impl(self, params, pools, page_table, lengths,
                           last_tokens, proposals, active_mask, temps,
                           top_ps, rng_key, window_pages: int = 0):
        """n-gram speculation verify over the page pool (see
        _verify_impl). Accepted tokens always land in reserved pages
        (acceptance <= remaining budget); overshoot writes may hit the
        trash page, which is by-construction inert."""
        jnp = self._jnp
        K = proposals.shape[1]
        if window_pages and window_pages < page_table.shape[1]:
            page_table = page_table[:, :window_pages]
        entries = self._paged_entries(pools, page_table, lengths)
        toks_in = jnp.concatenate(
            [last_tokens[:, None], jnp.maximum(proposals, 0)], axis=1)
        positions = lengths[:, None] + jnp.arange(K + 1)[None, :]
        logits, new_entries = self.model.apply(
            {"params": params}, toks_in, cache=entries,
            positions=positions)
        new_pools = [(e.k_flat, e.v_flat) for e in new_entries]
        out, n_emit, logps, last = self._verify_accept(
            logits, proposals, last_tokens, active_mask, temps, top_ps,
            rng_key)
        new_lengths = lengths + n_emit
        return out, n_emit, logps, new_pools, new_lengths, last

    def _verify_accept(self, logits, proposals, last_tokens, active_mask,
                       temps, top_ps, rng_key):
        """Shared in-jit acceptance: greedy chain for speculating rows,
        normal sampling (position 0 only) for sampled rows."""
        jnp = self._jnp
        jax = self._jax
        K = proposals.shape[1]
        S = logits.shape[0]
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # (S,K+1)
        match = (proposals == greedy[:, :K]) & (proposals >= 0)
        acc = jnp.cumprod(match.astype(jnp.int32), axis=1)
        m = acc.sum(axis=1)                                     # (S,)
        out0, lp0 = self._sample_tokens(logits[:, 0], temps, top_ps,
                                        rng_key)
        out = greedy.at[:, 0].set(out0)  # _sample_tokens is greedy at
        #                                  temp==0, so this is uniform
        if self.cfg.logprobs:
            lsm = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
            logps = jnp.take_along_axis(
                lsm, out[..., None].astype(jnp.int32), -1)[..., 0]
            logps = logps.at[:, 0].set(lp0)
        else:
            logps = jnp.zeros(out.shape, jnp.float32)
        n_emit = jnp.where(temps > 0, 1, m + 1)
        n_emit = jnp.where(active_mask, n_emit, 0).astype(jnp.int32)
        last = out[jnp.arange(S), jnp.maximum(n_emit - 1, 0)]
        last = jnp.where(active_mask, last, last_tokens)
        return out, n_emit, logps, last

    def _decode_impl(self, params, cache, last_tokens, active_mask,
                     temps, top_ps, rng_key, allow=None, pen=None):
        """One decode step for every slot. Returns (next_tokens (S,),
        cache'). Inactive slots' lengths are restored so their state
        never drifts."""
        jnp = self._jnp
        jax = self._jax
        old_lengths = cache[0][2]
        positions = old_lengths[:, None]  # (S, 1): write at current end
        logits, new_cache = self.model.apply(
            {"params": params}, last_tokens[:, None], cache=cache,
            positions=positions)
        logits = logits[:, 0, :]  # (S, V)
        fixed = []
        for (ck, cv, lens) in new_cache:
            lens = jnp.where(active_mask, lens, old_lengths)
            fixed.append((ck, cv, lens))
        bias, new_counts = self._pen_bias(pen, last_tokens, active_mask)
        nxt, logps = self._sample_tokens(logits, temps, top_ps, rng_key,
                                         allow=allow, bias=bias)
        nxt = jnp.where(active_mask, nxt, last_tokens)
        if pen is not None:
            return nxt, logps, fixed, new_counts
        return nxt, logps, fixed

    def _decode_block_impl(self, params, cache, last_tokens, active_mask,
                           temps, top_ps, rng_key):
        """decode_block fused steps under one dispatch (lax.scan).
        Returns (tokens (K, S), cache', last_tokens'). Host-side
        termination decisions lag up to K-1 extra tokens; drain guards
        discard them."""
        jax = self._jax
        keys = jax.random.split(rng_key, self.cfg.decode_block)

        def body(carry, key):
            cache, last = carry
            nxt, logps, cache = self._decode_impl(params, cache, last,
                                                  active_mask, temps,
                                                  top_ps, key)
            return (cache, nxt), (nxt, logps)

        (cache, last), (toks, logps) = jax.lax.scan(
            body, (cache, last_tokens), keys)
        return toks, logps, cache, last

    # ---- public API -------------------------------------------------------
    def register_prefix(self, prefix_ids) -> int:
        """Prefill a shared prompt prefix (e.g. a system prompt) once;
        returns a prefix_id for submit(prefix_id=...). Requires
        cfg.max_prefixes > 0. Slots are append-only (static buffers):
        registering more than max_prefixes raises. Thread-safe."""
        if self._prefix_cache is None and not (
                self._paged and self.cfg.max_prefixes > 0):
            raise ValueError("engine built with max_prefixes=0")
        prefix = np.asarray(prefix_ids, np.int32).reshape(-1)
        if prefix.size == 0:
            raise ValueError("empty prefix")
        if prefix.size >= self.cfg.max_seq_len - 1:
            raise ValueError(f"prefix length {prefix.size} leaves no "
                             f"room in max_seq_len "
                             f"{self.cfg.max_seq_len}")
        pid = next(self._prefix_counter)
        if pid >= self.cfg.max_prefixes:
            raise ValueError(
                f"prefix slots exhausted ({self.cfg.max_prefixes})")
        if self._paged:
            self._run_on_loop(
                lambda: self._register_prefix_paged(pid, prefix))
        else:
            self._fill_prefix_row(pid, prefix)
        return pid

    def _run_on_loop(self, fn) -> None:
        """Execute `fn` on the engine loop thread (pool mutations must
        not race dispatches that donate the pool buffers); blocks until
        done and re-raises its exception. Shutdown-safe: the wait polls
        the shutdown event so a command the exiting loop never drains
        raises instead of hanging the caller forever."""
        from concurrent.futures import Future  # noqa: PLC0415
        from concurrent.futures import TimeoutError as FutTimeout
        if self._shutdown.is_set():
            raise RuntimeError("engine is shut down")
        fut: Future = Future()
        self._control_q.put((fn, fut))
        while True:
            try:
                fut.result(timeout=0.1)
                return
            except FutTimeout:
                if self._shutdown.is_set() and not fut.done():
                    raise RuntimeError(
                        "engine shut down before command ran") from None

    def _register_prefix_paged(self, pid: int, prefix: np.ndarray
                               ) -> None:
        """Prefill a prefix into freshly-allocated PINNED pages (loop
        thread only). No dedicated buffers: the prefix lives in the
        pool; adopters share its full pages by reference."""
        jnp = self._jnp
        ps = self.cfg.kv_page_size
        pages = self._alloc_pages(-(-prefix.size // ps))
        if pages is None:
            raise ValueError("page pool exhausted registering prefix")
        scratch = self._scratch_slot
        pad = min(_next_pow2(prefix.size), self.cfg.max_seq_len)
        tokens = np.zeros((1, pad), np.int32)
        tokens[0, :prefix.size] = prefix
        self._set_page_row(scratch, pages)
        try:
            self._rng_key, sub = self._jax.random.split(self._rng_key)
            _t, _l, self._pools, self._lengths = self._prefill_paged_jit(
                self.params, self._pools, self._page_table,
                self._lengths, jnp.asarray(tokens),
                jnp.asarray(np.asarray([scratch], np.int32)),
                jnp.asarray(np.asarray([prefix.size], np.int32)),
                jnp.zeros((1,), jnp.float32), jnp.ones((1,), jnp.float32),
                sub, pad_len=pad)
        except BaseException:
            self._free_pages.extend(pages)
            raise
        finally:
            # scratch row back to all-trash: batch-padding rows write
            # through it and must never touch the pinned prefix pages
            self._set_page_row(scratch, [])
        self._prefix_pages[pid] = pages
        self._prefixes[pid] = prefix

    def _unregister_prefix_paged(self, pid: int) -> None:
        """Free a prefix's pinned pages (loop thread; internal — only
        safe once no active slot shares them, e.g. precompile's warm
        prefix after its streams drain)."""
        pages = self._prefix_pages.pop(pid, None)
        self._prefixes.pop(pid, None)
        if pages:
            self._free_pages.extend(pages)

    def _fill_prefix_row(self, pid: int, prefix: np.ndarray) -> None:
        """Fill buffer row `pid` (the scratch row included) under the
        lock — the buffer swap is a read-modify-write; a concurrent
        unsynchronized registration would silently drop one fill."""
        pad = min(_next_pow2(prefix.size), self.cfg.max_seq_len)
        tokens = np.zeros((1, pad), np.int32)
        tokens[0, :prefix.size] = prefix
        with self._lock:
            self._prefix_cache = self._prefix_fill_jit(
                self.params, self._prefix_cache,
                self._jnp.asarray(tokens), self._jnp.int32(pid),
                pad_len=pad)
            self._prefixes[pid] = prefix

    def submit(self, prompt_ids, max_new_tokens: Optional[int] = None,
               temperature: float = 0.0, top_p: float = 1.0,
               stop_token_ids=None,
               prefix_id: Optional[int] = None,
               guided_fsm=None,
               presence_penalty: float = 0.0,
               frequency_penalty: float = 0.0,
               logit_bias: Optional[dict] = None,
               deadline_ts: Optional[float] = None) -> str:
        """guided_fsm: a serve.llm.guided.TokenFSM constraining this
        request's output (per-step vocab masks; EOS only at accepting
        states). Guided traffic decodes synchronously (pipeline drains
        each step) so the mask can depend on the previous token.

        deadline_ts: absolute deadline (epoch seconds, propagated from
        the serve plane). A deadline that already cannot be met is
        rejected HERE — before any queueing — and one that expires
        while queued is shed at admission, both with
        DeadlineExceededError."""
        from ...exceptions import DeadlineExceededError  # noqa: PLC0415
        if self.wedged:
            from ...exceptions import EngineWedgedError  # noqa: PLC0415
            raise EngineWedgedError(
                "engine is wedged; replica awaiting replacement")
        if deadline_ts is not None and time.time() >= deadline_ts:
            # same shed-telemetry contract as the queued-expiry path:
            # every shed is visible, whichever gate catches it
            self._event("serve.request.shed", reason="deadline_expired",
                        stage="submit",
                        late_s=round(time.time() - deadline_ts, 3))
            from ...util import events as events_mod  # noqa: PLC0415
            events_mod.emit_safe(
                counter="ray_tpu_serve_requests_shed_total",
                counter_tags={"reason": "deadline_expired"})
            raise DeadlineExceededError(
                "deadline already expired at submit; request rejected "
                "at admission")
        prompt = np.asarray(prompt_ids, dtype=np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if not -2.0 <= presence_penalty <= 2.0 \
                or not -2.0 <= frequency_penalty <= 2.0:
            raise ValueError("presence/frequency penalties must be in "
                             "[-2, 2] (OpenAI semantics)")
        if guided_fsm is not None:
            vs = getattr(getattr(self.model, "cfg", None),
                         "vocab_size", None)
            if vs is not None and guided_fsm.vocab_size != vs:
                raise ValueError(
                    f"guided_fsm.vocab_size {guided_fsm.vocab_size} != "
                    f"model vocab_size {vs}")
            if (self.cfg.eos_token_id is not None
                    and guided_fsm.eos_id != self.cfg.eos_token_id):
                raise ValueError(
                    f"guided_fsm.eos_id {guided_fsm.eos_id} != engine "
                    f"eos_token_id {self.cfg.eos_token_id}")
            if not guided_fsm.allowed(guided_fsm.start).any():
                raise ValueError("guided_fsm allows no token at its "
                                 "start state (empty language)")
        if prefix_id is not None:
            prefix = self._prefixes.get(prefix_id)
            if prefix is None:
                raise ValueError(f"unknown prefix_id {prefix_id}")
            # prompt_ids is the SUFFIX; the engine re-attaches the
            # prefix tokens (for stop/position bookkeeping) but its KV
            # is adopted by copy, never re-prefilled
            prompt = np.concatenate([prefix, prompt])
        elif not self._use_chunked(prompt.size):
            # chunked prompts bypass the buckets; all others must fit one
            self._bucket(prompt.size)  # validate in the caller, not loop
        budget = max_new_tokens or self.cfg.max_new_tokens_default
        if prompt.size + budget > self.cfg.max_seq_len:
            budget = self.cfg.max_seq_len - prompt.size
            if budget <= 0:
                raise ValueError(
                    f"prompt length {prompt.size} exceeds max_seq_len "
                    f"{self.cfg.max_seq_len}")
        if self._paged:
            ps = self.cfg.kv_page_size
            if -(-(prompt.size + budget) // ps) > self._n_pages:
                raise ValueError(
                    f"request needs {-(-(prompt.size + budget) // ps)} "
                    f"KV pages; pool has {self._n_pages} total — it "
                    f"could never be admitted")
        req = _Request(request_id=f"req-{next(self._req_counter)}",
                       prompt=prompt, max_new_tokens=budget,
                       temperature=temperature, top_p=float(top_p),
                       stop_ids=frozenset(stop_token_ids or ()),
                       prefix_id=-1 if prefix_id is None else prefix_id,
                       fsm=guided_fsm,
                       fsm_state=(guided_fsm.start
                                  if guided_fsm is not None else 0),
                       presence_penalty=float(presence_penalty),
                       frequency_penalty=float(frequency_penalty),
                       logit_bias=dict(logit_bias) if logit_bias
                       else None,
                       deadline_ts=deadline_ts,
                       hist=(list(map(int, prompt))
                             if (self.cfg.ngram_speculation > 0
                                 and temperature == 0.0
                                 and guided_fsm is None
                                 and not (presence_penalty
                                          or frequency_penalty
                                          or logit_bias)) else None))
        with self._lock:
            self._requests[req.request_id] = req
        self._waiting.put(req)
        return req.request_id

    def stream(self, request_id: str):
        """Blocking generator of token ids for one request."""
        for tok, _lp in self.stream_detailed(request_id):
            yield tok

    def stream_detailed(self, request_id: str):
        """Like stream() but yields (token_id, logprob) — logprob is
        None unless the engine was built with logprobs=True."""
        req = self._requests.get(request_id)
        if req is None:
            raise KeyError(request_id)
        while True:
            # raylint: disable=RT003 the engine loop cannot exit with this
            # request registered: its catch-all errors every active
            # request's queue, failed admits error theirs, and the wedge
            # watchdog aborts stalled requests — while a timeout here
            # would kill legitimate multi-minute first-jit prefills
            kind, payload = req.out_queue.get()
            if kind == "token":
                yield payload
            elif kind == "error":
                raise payload
            else:  # end
                break
        with self._lock:
            self._requests.pop(request_id, None)

    def abort(self, request_id: str) -> None:
        """Best-effort early termination. Decoding requests collapse
        their budget to what they have already generated, so the engine
        releases the slot at the next drain (the consumer should keep
        draining to the end marker; a few lagged tokens may still
        arrive). Requests that have not produced a token yet — still
        queued or chunk-prefilling — are cancelled outright: no prefill
        runs, no token is forced."""
        req = self._requests.get(request_id)
        if req is None:
            return
        req.aborted = True
        self._event("llm_engine.request_abort", req=req,
                    generated=req.generated)
        if req.generated == 0 and req.slot == -1:
            # still in _waiting: the loop discards it at admission;
            # unblock the consumer immediately (a duplicate end marker
            # from a concurrent admission is harmless — the consumer
            # stops at the first one)
            req.out_queue.put(_END)
        elif req.generated > 0:
            req.max_new_tokens = min(req.max_new_tokens, req.generated)
        # else: slot assigned but no token yet (chunk-prefilling / prefill
        # in flight) — the loop cancels it at its next touch point

    def precompile(self) -> None:
        """Warm every jitted path before real traffic: one dummy request
        per prefill bucket plus one chunked prompt when chunking is on,
        each generating 2 tokens (prefill sample + one decode step).
        Blocks until the dummy streams drain; afterwards all slots are
        free again (stats do count the dummy work)."""
        rids = []
        prev = 0
        for b in sorted(self.cfg.prefill_buckets):
            if b > self.cfg.max_seq_len:
                continue
            # smallest prompt length that maps to THIS bucket and takes
            # the bucket (non-chunked) path — a length-b dummy would be
            # routed through chunked prefill whenever b > prefill_chunk,
            # leaving the bucket's jit cold (review r4)
            n = min(b, self.cfg.max_seq_len - 2)
            if self.cfg.prefill_chunk > 0:
                n = min(n, self.cfg.prefill_chunk)
            n = max(1, n)
            if n <= prev:
                prev = b
                continue  # no non-chunked prompt can reach this bucket
            rids.append(self.submit(np.ones((n,), np.int32),
                                    max_new_tokens=2))
            prev = b
        if self.cfg.prefill_chunk > 0:
            n = max(1, min(self.cfg.prefill_chunk + 1,
                           self.cfg.max_seq_len - 2))
            rids.append(self.submit(np.ones((n,), np.int32),
                                    max_new_tokens=2))
        for rid in rids:
            for _ in self.stream(rid):
                pass
        if self.cfg.max_prefixes > 0:
            # Warm fill + adopt + the per-bucket chunk kernels by
            # EXECUTING dummy prefix'd requests against the scratch
            # prefix row (pid == max_prefixes — never handed out), one
            # suffix length per reachable chunk width. AOT
            # lower().compile() would NOT populate the jit call cache.
            scratch = self.cfg.max_prefixes
            if self._paged:
                self._run_on_loop(lambda: self._register_prefix_paged(
                    scratch, np.ones((2,), np.int32)))
            else:
                self._fill_prefix_row(scratch, np.ones((2,), np.int32))
            widths = ({self.cfg.prefill_chunk}
                      if self.cfg.prefill_chunk > 0 else
                      {b for b in self.cfg.prefill_buckets
                       if b <= self.cfg.max_seq_len})
            lens = {max(1, min(w, self.cfg.max_seq_len - 4))
                    for w in widths}
            if self.cfg.prefill_chunk <= 0 and widths:
                # a suffix LONGER than the largest bucket dispatches the
                # (largest, sample=False) multi-chunk variant — the one
                # width-suffix pairs above can never reach (per-dispatch
                # widths always cover the remaining suffix)
                lens.add(max(1, min(max(widths) + 1,
                                    self.cfg.max_seq_len - 4)))
            warm = []
            for n in sorted(lens):
                warm.append(self.submit(np.ones((n,), np.int32),
                                        max_new_tokens=2,
                                        prefix_id=scratch))
            for rid in warm:
                for _ in self.stream(rid):
                    pass
            if self._paged:
                self._run_on_loop(
                    lambda: self._unregister_prefix_paged(scratch))
            else:
                self._prefixes.pop(scratch, None)
            self.stats["prefix_tokens_saved"] = 0   # dummy adoptions

    def generate_sync(self, prompt_ids, max_new_tokens=None,
                      temperature: float = 0.0, top_p: float = 1.0,
                      stop_token_ids=None,
                      prefix_id: Optional[int] = None,
                      guided_fsm=None, presence_penalty: float = 0.0,
                      frequency_penalty: float = 0.0,
                      logit_bias: Optional[dict] = None) -> List[int]:
        rid = self.submit(prompt_ids, max_new_tokens, temperature,
                          top_p=top_p, stop_token_ids=stop_token_ids,
                          guided_fsm=guided_fsm,
                          presence_penalty=presence_penalty,
                          frequency_penalty=frequency_penalty,
                          logit_bias=logit_bias,
                          prefix_id=prefix_id)
        return list(self.stream(rid))

    def get_stats(self) -> Dict[str, Any]:
        with self._lock:
            out = {**self.stats, "active": len(self._active),
                   "waiting": self._waiting.qsize(),
                   "prefilling": len(self._prefilling),
                   "free_slots": len(self._free_slots)}
            if self._paged:
                pinned = sum(len(p) for p in self._prefix_pages.values())
                out["kv_pages"] = {
                    "page_size": self.cfg.kv_page_size,
                    "total": self._n_pages,
                    "free": len(self._free_pages),
                    "in_use": self._n_pages - len(self._free_pages),
                    "pinned_prefix": pinned,
                    "peak_in_use": self._page_hwm,
                }
            samples = list(self._ttft_samples)
            tpots = sorted(self._tpot_samples)
        if tpots:
            out["tpot_p50_ms"] = round(
                tpots[len(tpots) // 2] * 1000, 2)
        if samples:
            def p50(key):
                vals = sorted(s[key] for s in samples)
                return round(vals[len(vals) // 2], 1)
            out["ttft_breakdown_p50_ms"] = {
                k: p50(k) for k in ("queue_ms", "prefill_dispatch_ms",
                                    "emit_ms", "total_ms")}
        out["prefill_compile_ms"] = dict(self._prefill_compile_ms)
        return out

    def shutdown(self):
        self._shutdown.set()

    # ---- wedged-engine watchdog -------------------------------------------
    @property
    def wedged(self) -> bool:
        """True once the watchdog declared this engine wedged (sticky:
        the replica is about to fail health checks and be replaced —
        un-wedging a half-dead engine under traffic is not a state we
        try to recover)."""
        return self._wedged_since is not None

    def _note_progress(self) -> None:
        self._progress_ts = time.time()

    def _has_work(self) -> bool:
        return bool(self._active or self._prefilling
                    or not self._waiting.empty())

    # In-dispatch stall budget multiplier: a first-use jit compile is a
    # legitimate multi-second (big models: multi-minute — use
    # precompile=True) stall inside a dispatch, indistinguishable
    # in-flight from a hung device call. Give dispatches grace x the
    # budget so compiles pass and true device hangs are still caught.
    _DISPATCH_GRACE = 10.0

    # A consumer whose out_queue stays full this long without draining
    # a single token is treated as gone and its request aborted (see
    # _emit's bounded put) — the bound that keeps per-request
    # backpressure from parking the shared loop indefinitely.
    _CONSUMER_STALL_TTL_S = 60.0

    def _watchdog_loop(self) -> None:
        period = max(0.05, min(1.0, self._watchdog_s / 4.0))
        while not self._shutdown.is_set():
            self._shutdown.wait(period)
            if self._wedged_since is not None:
                continue
            if not self._has_work():
                # idle is not wedged; keep the clock fresh so the first
                # request after a quiet hour isn't instantly blamed
                self._note_progress()
                continue
            budget = self._watchdog_s * (
                self._DISPATCH_GRACE if self._in_dispatch else 1.0)
            stall = time.time() - self._progress_ts
            if stall <= budget:
                continue
            self._declare_wedged(stall)

    def _declare_wedged(self, stall_s: float) -> None:
        from ...exceptions import EngineWedgedError  # noqa: PLC0415
        self._wedged_since = time.time()
        self._event("llm_engine.wedged",
                    f"no forward progress for {stall_s:.1f}s "
                    f"(watchdog_s={self._watchdog_s}); aborting "
                    f"in-flight requests", stall_s=round(stall_s, 2),
                    active=len(self._active),
                    waiting=self._waiting.qsize())
        err = EngineWedgedError(
            f"engine wedged: no forward progress for {stall_s:.1f}s "
            f"(> RAY_TPU_ENGINE_WATCHDOG_S={self._watchdog_s}); "
            "request aborted for failover")
        # deliberately lock-free: if the loop wedged while HOLDING the
        # engine lock, taking it here would hang the watchdog too; a
        # snapshot of the dict values is safe to iterate in CPython
        reqs = list(self._requests.values())
        for req in reqs:
            req.aborted = True
            # error (not _END) so consumers raise and the serve handle
            # fails the stream over to a healthy replica; bounded put —
            # a full queue (slow consumer) must not swallow the error
            _put_dropping_one(req.out_queue, ("error", err))

    def _chaos_stall(self, seconds: float) -> None:
        """Deterministic wedge injection (serve/chaos.py, tests): park
        the engine loop thread via the control queue — the real
        watchdog path then observes the stall exactly as it would a
        hung device call. Returns immediately."""
        from concurrent.futures import Future  # noqa: PLC0415
        self._control_q.put((lambda: time.sleep(seconds), Future()))

    # ---- engine loop ------------------------------------------------------
    def _bucket(self, n: int) -> int:
        for b in self.cfg.prefill_buckets:
            if n <= b and b <= self.cfg.max_seq_len:
                return b
        raise ValueError(f"prompt length {n} exceeds largest prefill "
                         f"bucket {self.cfg.prefill_buckets[-1]}")

    def _largest_bucket(self) -> int:
        """0 when NO bucket fits max_seq_len — callers that need a
        usable width must supply their own fallback (a non-zero default
        here would flip _use_chunked's always-chunk invariant)."""
        return max((b for b in self.cfg.prefill_buckets
                    if b <= self.cfg.max_seq_len), default=0)

    def _chunk_for(self, remaining: int) -> int:
        """Chunk width for one chunked-prefill dispatch. With chunking
        on, the configured chunk. Otherwise (prefix-adoption fallback)
        the SMALLEST bucket covering the remaining suffix — a short
        suffix after a long prefix must not pay a largest-bucket-wide
        model pass (that would out-cost the prefill the prefix cache
        saved)."""
        if self.cfg.prefill_chunk > 0:
            return self.cfg.prefill_chunk
        for b in sorted(self.cfg.prefill_buckets):
            if remaining <= b <= self.cfg.max_seq_len:
                return b
        return self._largest_bucket() or self.cfg.max_seq_len

    def _use_chunked(self, n: int) -> bool:
        """Chunked prefill serves prompts longer than prefill_chunk AND
        any prompt that overflows the largest bucket (so bucket coverage
        never rejects what the chunked path could handle)."""
        if self.cfg.prefill_chunk <= 0:
            return False
        return n > self.cfg.prefill_chunk or n > self._largest_bucket()

    def _admit_paged(self, req: _Request) -> str:
        """Paged admission: reserve pages + a slot. Returns "ok",
        "nopages" (hold the request), or "failed" (stream errored).
        Prefix-carrying requests share the prefix's full pages by
        page-table reference and copy only its partial last page."""
        jnp = self._jnp
        ps = self.cfg.kv_page_size
        need_total = self._pages_needed(req)
        # Unservable guard: pinned prefix pages never return to the
        # pool, so a request needing more than (total - pinned [- shared
        # pages it adopts]) could park in _pending_head FOREVER and
        # head-of-line-block every later request. Error it instead —
        # submit()'s static check can't see pins made after submit.
        pinned = sum(len(p) for p in self._prefix_pages.values())
        n_shared_adopt = (int(self._prefixes[req.prefix_id].size) // ps
                          if req.prefix_id >= 0 else 0)
        if need_total - n_shared_adopt > self._n_pages - pinned:
            req.out_queue.put(("error", ValueError(
                f"request needs {need_total - n_shared_adopt} exclusive "
                f"KV pages but only {self._n_pages - pinned} can ever "
                f"be free ({pinned} pinned by prefixes)")))
            req.out_queue.put(_END)
            return "failed"
        if req.prefix_id >= 0:
            prefix_pages = self._prefix_pages[req.prefix_id]
            plen = int(self._prefixes[req.prefix_id].size)
            n_shared = plen // ps
            excl = self._alloc_pages(need_total - n_shared)
            if excl is None:
                return "nopages"
            slot = self._free_slots.pop()
            req.slot = slot
            req.admit_ts = time.time()
            if plen % ps:
                try:
                    self._pools = self._copy_page_jit(
                        self._pools, jnp.int32(prefix_pages[n_shared]),
                        jnp.int32(excl[0]))
                except BaseException as e:  # noqa: BLE001
                    self._free_pages.extend(excl)
                    self._free_slots.append(slot)
                    req.slot = -1
                    req.out_queue.put(("error", e))
                    req.out_queue.put(_END)
                    return "failed"
            all_pages = prefix_pages[:n_shared] + excl
            self._slot_pages[slot] = (n_shared, all_pages)
            self._set_page_row(slot, all_pages)
            self._lengths = self._lengths.at[slot].set(plen)
            self._disp_len[slot] = plen
            req.prefill_pos = plen
            self.stats["prefix_tokens_saved"] = (
                self.stats.get("prefix_tokens_saved", 0) + plen)
            return "ok"
        pages = self._alloc_pages(need_total)
        if pages is None:
            return "nopages"
        slot = self._free_slots.pop()
        req.slot = slot
        req.admit_ts = time.time()
        self._slot_pages[slot] = (0, pages)
        self._set_page_row(slot, pages)
        # reset the slot's device length NOW: a reused slot's stale
        # length would aim inactive decode-steps' garbage writes at an
        # arbitrary position — under a narrowed decode window the
        # clamped scatter could then corrupt the NEW occupant's pages.
        # With length 0, garbage always lands exactly where the next
        # prefill/chunk write goes (overwritten before any read).
        self._lengths = self._lengths.at[slot].set(0)
        self._disp_len[slot] = 0
        return "ok"

    def _admit_all(self, inflight) -> None:
        """Dispatch prefills for every waiting request that can get a
        slot — back to back, NO host syncs. Requests sharing a length
        bucket prefill TOGETHER (up to max_prefill_batch per call); the
        sampled first tokens drain through the same pipeline as decode
        steps, preserving per-request emission order."""
        taken: List[tuple] = []
        while self._free_slots:
            if self._paged and self._pending_head is not None:
                req, self._pending_head = self._pending_head, None
            else:
                try:
                    req = self._waiting.get_nowait()
                except queue_mod.Empty:
                    break
            if req.aborted:
                # cancelled before admission: abort() already unblocked
                # the consumer; never take a slot or prefill
                self._requests.pop(req.request_id, None)
                continue
            if (req.deadline_ts is not None
                    and time.time() >= req.deadline_ts):
                # deadline expired while queued: shed instead of
                # spending prefill+decode on an answer nobody waits for
                self._shed_expired(req)
                continue
            self._progress_ts = time.time()   # watchdog: admission
            if self._paged:
                outcome = self._admit_paged(req)
                if outcome == "nopages":
                    # hold the head request (FIFO — Queue has no
                    # push-front) until releases replenish the pool
                    self._pending_head = req
                    if not getattr(req, "preempt_emitted", False):
                        req.preempt_emitted = True
                        self._event("llm_engine.request_preempt",
                                    "KV page pool exhausted; holding "
                                    "at admission", req=req)
                    break
                if outcome == "failed":
                    continue
                self._event("llm_engine.request_admit", req=req,
                            slot=req.slot, prompt_len=int(
                                req.prompt.size))
                if req.prefix_id >= 0 or self._use_chunked(
                        req.prompt.size):
                    self._prefilling.append(req)
                else:
                    taken.append((self._bucket(req.prompt.size), req,
                                  req.slot))
                continue
            slot = self._free_slots.pop()
            req.slot = slot
            req.admit_ts = time.time()
            self._event("llm_engine.request_admit", req=req, slot=slot,
                        prompt_len=int(req.prompt.size))
            if req.prefix_id >= 0:
                # adopt the registered prefix's KV with ONE on-device
                # copy, then chunk-prefill only the suffix
                plen = int(self._prefixes[req.prefix_id].size)
                try:
                    self._cache = self._adopt_prefix_jit(
                        self._cache, self._prefix_cache,
                        self._jnp.int32(slot),
                        self._jnp.int32(req.prefix_id),
                        self._jnp.int32(plen))
                except BaseException as e:  # noqa: BLE001
                    # same per-request containment as the sibling
                    # dispatch paths: free the slot, error the stream
                    self._free_slots.append(slot)
                    req.slot = -1
                    req.out_queue.put(("error", e))
                    req.out_queue.put(_END)
                    continue
                req.prefill_pos = plen
                self.stats["prefix_tokens_saved"] = (
                    self.stats.get("prefix_tokens_saved", 0) + plen)
                self._prefilling.append(req)
                continue
            if self._use_chunked(req.prompt.size):
                # long prompt: prefill in chunks interleaved with decode
                # steps (one chunk per loop iteration)
                self._prefilling.append(req)
                continue
            taken.append((self._bucket(req.prompt.size), req, slot))
        if not taken:
            return
        groups: Dict[int, List[tuple]] = {}
        for pad_len, req, slot in taken:
            groups.setdefault(pad_len, []).append((req, slot))
        cap = max(1, self.cfg.max_prefill_batch)
        for pad_len, members in groups.items():
            for i in range(0, len(members), cap):
                self._dispatch_prefill(inflight, pad_len,
                                       members[i:i + cap])

    def _dispatch_prefill(self, inflight, pad_len: int, members) -> None:
        """One prefill call for `members` = [(req, slot), ...] of a
        shared bucket; group size pads to a power of two (scratch slot
        rows) so compile count stays O(buckets * log2(cap))."""
        jnp = self._jnp
        g_real = len(members)
        t_dispatch = time.time()
        try:
            self._rng_key, sub = self._jax.random.split(self._rng_key)
            if self._paged:
                # unified single/batched paged prefill: pad group size
                # to a power of two; padding rows hit the scratch slot
                # whose page row is all-trash
                g = _next_pow2(g_real)
                tokens = np.zeros((g, pad_len), np.int32)
                slots = np.full((g,), self._scratch_slot, np.int32)
                lens = np.ones((g,), np.int32)
                temps = np.zeros((g,), np.float32)
                top_ps = np.ones((g,), np.float32)
                for i, (req, slot) in enumerate(members):
                    tokens[i, :req.prompt.size] = req.prompt
                    slots[i] = slot
                    lens[i] = req.prompt.size
                    temps[i] = req.temperature
                    top_ps[i] = req.top_p
                allow = self._guided_prefill_allow(
                    [r for r, _ in members], g)
                kw = {} if allow is None else {"allow": allow}
                pbias = self._pen_prefill_bias(
                    [r for r, _ in members], g)
                if pbias is not None:
                    kw["bias"] = pbias
                toks_dev, lps_dev, self._pools, self._lengths = \
                    self._prefill_paged_jit(
                        self.params, self._pools, self._page_table,
                        self._lengths, jnp.asarray(tokens),
                        jnp.asarray(slots), jnp.asarray(lens),
                        jnp.asarray(temps), jnp.asarray(top_ps), sub,
                        pad_len=pad_len, **kw)
                toks_dev = toks_dev[:g_real]
                lps_dev = lps_dev[:g_real]
            elif g_real == 1 and self.cfg.max_prefill_batch <= 1:
                req, slot = members[0]
                tokens = np.zeros((1, pad_len), np.int32)
                tokens[0, :req.prompt.size] = req.prompt
                allow = self._guided_prefill_allow([req], 1)
                kw = {} if allow is None else {"allow": allow}
                pbias = self._pen_prefill_bias([req], 1)
                if pbias is not None:
                    kw["bias"] = pbias
                tok_dev, lp_dev, self._cache = self._prefill_jit(
                    self.params, self._cache, jnp.asarray(tokens),
                    jnp.int32(slot), jnp.int32(req.prompt.size),
                    jnp.float32(req.temperature),
                    jnp.float32(req.top_p), sub, pad_len=pad_len, **kw)
                toks_dev, lps_dev = tok_dev[None], lp_dev[None]
            else:
                g = _next_pow2(g_real)
                tokens = np.zeros((g, pad_len), np.int32)
                slots = np.full((g,), self._scratch_slot, np.int32)
                lens = np.ones((g,), np.int32)
                temps = np.zeros((g,), np.float32)
                top_ps = np.ones((g,), np.float32)
                for i, (req, slot) in enumerate(members):
                    tokens[i, :req.prompt.size] = req.prompt
                    slots[i] = slot
                    lens[i] = req.prompt.size
                    temps[i] = req.temperature
                    top_ps[i] = req.top_p
                allow = self._guided_prefill_allow(
                    [r for r, _ in members], g)
                kw = {} if allow is None else {"allow": allow}
                pbias = self._pen_prefill_bias(
                    [r for r, _ in members], g)
                if pbias is not None:
                    kw["bias"] = pbias
                toks_dev, lps_dev, self._cache = self._prefill_batch_jit(
                    self.params, self._cache, jnp.asarray(tokens),
                    jnp.asarray(slots), jnp.asarray(lens),
                    jnp.asarray(temps), jnp.asarray(top_ps), sub,
                    pad_len=pad_len, **kw)
                toks_dev = toks_dev[:g_real]
                lps_dev = lps_dev[:g_real]
            real_slots = jnp.asarray(
                np.asarray([s for _, s in members], np.int32))
            self._last_tokens = self._last_tokens.at[real_slots].set(
                toks_dev)
        except BaseException as e:  # noqa: BLE001
            for req, slot in members:
                self._free_slot_pages(slot)
                self._free_slots.append(slot)
                req.slot = -1
                req.out_queue.put(("error", e))
                req.out_queue.put(_END)
            return
        dispatch_ms = (time.time() - t_dispatch) * 1000
        # first dispatch of a bucket blocks on its jit compile: record it
        self._prefill_compile_ms.setdefault(pad_len, round(dispatch_ms, 1))
        self.stats["prefills"] += g_real
        for req, slot in members:
            req.prefill_dispatch_ms = dispatch_ms
            if self._paged:
                self._disp_len[slot] = req.prompt.size
            self._active[slot] = req
        self._mask_dirty = True
        self._pen_coef_dirty = True
        self._start_fetch(toks_dev)
        if self.cfg.logprobs:
            self._start_fetch(lps_dev)
        inflight.append(("prefill_batch", [r for r, _ in members],
                         toks_dev, lps_dev if self.cfg.logprobs else None))

    def _dispatch_chunk(self, inflight) -> None:
        """Advance the oldest chunk-prefilling request by ONE chunk. The
        final chunk samples the first token and activates the slot."""
        jnp = self._jnp
        req = self._prefilling[0]
        if req.aborted:
            # cancelled mid-chunk-prefill: drop remaining chunks, free
            # the slot, close the stream with no token forced
            self._prefilling.popleft()
            self._release(req)
            return
        start = req.prefill_pos
        C = self._chunk_for(req.prompt.size - start)
        true = min(C, req.prompt.size - start)
        is_last = start + true >= req.prompt.size
        tokens = np.zeros((1, C), np.int32)
        tokens[0, :true] = req.prompt[start:start + true]
        t_dispatch = time.time()
        try:
            self._rng_key, sub = self._jax.random.split(self._rng_key)
            kw = {}
            if is_last and req.fsm is not None:
                kw["allow"] = self._guided_prefill_allow([req], 1)
            if is_last and req.logit_bias:
                kw["bias"] = self._pen_prefill_bias([req], 1)
            if self._paged:
                tok_dev, lp_dev, self._pools, self._lengths = \
                    self._chunk_paged_jit(
                        self.params, self._pools, self._page_table,
                        self._lengths, jnp.asarray(tokens),
                        jnp.int32(req.slot), jnp.int32(start),
                        jnp.int32(start + true),
                        jnp.float32(req.temperature),
                        jnp.float32(req.top_p), sub, chunk=C,
                        sample=is_last, **kw)
            else:
                tok_dev, lp_dev, self._cache = self._prefill_chunk_jit(
                    self.params, self._cache, jnp.asarray(tokens),
                    jnp.int32(req.slot), jnp.int32(start),
                    jnp.int32(start + true),
                    jnp.float32(req.temperature),
                    jnp.float32(req.top_p), sub, chunk=C,
                    sample=is_last, **kw)
        except BaseException as e:  # noqa: BLE001
            self._prefilling.popleft()
            self._free_slot_pages(req.slot)
            self._free_slots.append(req.slot)
            req.slot = -1
            req.out_queue.put(("error", e))
            req.out_queue.put(_END)
            return
        req.prefill_pos = start + true
        if self._paged:
            self._disp_len[req.slot] = req.prefill_pos
        req.prefill_dispatch_ms += (time.time() - t_dispatch) * 1000
        self._progress_ts = time.time()   # watchdog: chunk advanced
        if is_last:
            self._prefilling.popleft()
            self.stats["prefills"] += 1
            self._last_tokens = self._last_tokens.at[req.slot].set(tok_dev)
            self._active[req.slot] = req
            self._mask_dirty = True
            self._pen_coef_dirty = True
            toks_dev, lps_dev = tok_dev[None], lp_dev[None]
            self._start_fetch(toks_dev)
            if self.cfg.logprobs:
                self._start_fetch(lps_dev)
            inflight.append(("prefill_batch", [req], toks_dev,
                             lps_dev if self.cfg.logprobs else None))

    @staticmethod
    def _start_fetch(arr):
        try:
            arr.copy_to_host_async()
        except (AttributeError, NotImplementedError):
            pass  # fetch happens synchronously at drain time instead

    def _emit(self, req: _Request, tok: int,
              logp: Optional[float] = None):
        req.generated += 1
        self.stats["tokens_generated"] += 1
        self._progress_ts = time.time()   # watchdog: forward progress
        m = self._m
        m["tokens"].inc(1.0, tags=self._mtags)
        if req.first_token_ts is None:
            now = time.time()
            req.first_token_ts = now
            admit = req.admit_ts or req.submit_ts
            self._ttft_samples.append({
                "queue_ms": (admit - req.submit_ts) * 1000,
                "prefill_dispatch_ms": req.prefill_dispatch_ms,
                "emit_ms": max(0.0, (now - admit) * 1000
                               - req.prefill_dispatch_ms),
                "total_ms": (now - req.submit_ts) * 1000})
            m["ttft"].observe(now - req.submit_ts, tags=self._mtags)
        if req.hist is not None:
            req.hist.append(tok)
        # Bounded-wait put: a FULL out_queue means the CONSUMER is slow
        # or gone, not that the engine is wedged — refresh the watchdog
        # clock while parked so per-request backpressure can't get the
        # whole replica declared wedged and replaced. The park itself
        # is bounded: a consumer silent past _CONSUMER_STALL_TTL_S
        # (abandoned generator, crashed client that never cancelled)
        # gets its request aborted so one dead reader can't stall the
        # shared loop forever while keeping the watchdog green.
        parked_since = None
        while True:
            try:
                req.out_queue.put(("token", (tok, logp)), timeout=1.0)
                break
            except queue_mod.Full:
                if req.aborted:
                    break
                now = time.time()
                if parked_since is None:
                    parked_since = now
                    # flag the stall while it is still LIVE so hangs
                    # the TTL will later mitigate show up in `stuck`
                    # output and post-mortems as they happen
                    self._event("sched.hang.suspected",
                                "request output queue full; consumer "
                                "stalled (TTL abort after "
                                f"{self._CONSUMER_STALL_TTL_S:.0f}s)",
                                req=req, kind="consumer_stalled")
                elif now - parked_since > self._CONSUMER_STALL_TTL_S:
                    req.aborted = True
                    req.max_new_tokens = min(req.max_new_tokens,
                                             req.generated)
                    self._event("llm_engine.request_abort", req=req,
                                generated=req.generated,
                                reason="consumer_stalled")
                    # hang-mitigation telemetry: the TTL abort IS a
                    # resolved hang — make it visible to the wait
                    # plane's post-mortems, not just the engine log
                    self._event("sched.hang.resolved",
                                f"consumer stalled "
                                f"{now - parked_since:.0f}s; request "
                                "aborted by the consumer-stall TTL",
                                req=req, kind="consumer_stalled",
                                stalled_s=round(now - parked_since, 1))
                    break
                self._progress_ts = now
        if ((self.cfg.eos_token_id is not None
             and tok == self.cfg.eos_token_id)
                or tok in req.stop_ids):
            req.max_new_tokens = req.generated  # finish after EOS/stop
        if req.fsm is not None:
            # guided: advance the automaton; a dead state (can't happen
            # under the mask, but belt-and-braces) or a completed match
            # ends the request like EOS
            req.fsm_state = req.fsm.advance(req.fsm_state, tok)
            if (req.fsm_state < 0
                    or req.fsm.is_complete(req.fsm_state)):
                req.max_new_tokens = min(req.max_new_tokens,
                                         req.generated)

    # ---- page allocator (host side) ---------------------------------------
    def _pages_needed(self, req: _Request) -> int:
        """Whole pages reserved at admission: prompt + generation budget.
        Full reservation means decode can never hit page exhaustion
        mid-stream (no preemption machinery needed)."""
        ps = self.cfg.kv_page_size
        return -(-(req.prompt.size + req.max_new_tokens) // ps)

    def _alloc_pages(self, n: int) -> "Optional[List[int]]":
        if len(self._free_pages) < n:
            return None
        pages = [self._free_pages.pop() for _ in range(n)]
        in_use = self._n_pages - len(self._free_pages)
        self._page_hwm = max(self._page_hwm, in_use)
        return pages

    def _set_page_row(self, slot: int, pages: "List[int]") -> None:
        """Write a slot's page-table row (unused entries -> trash)."""
        row = np.full((self._pages_per_slot,), self._trash_page, np.int32)
        row[:len(pages)] = pages
        self._page_table = self._page_table.at[slot].set(
            self._jnp.asarray(row))

    def _free_slot_pages(self, slot: int) -> None:
        """Return the slot's exclusive pages to the pool (shared prefix
        pages stay pinned) and point its row at the trash page so lagged
        decode writes can't corrupt a reused page."""
        if not self._paged:
            return
        entry = self._slot_pages.pop(slot, None)
        self._disp_len.pop(slot, None)
        if entry is None:
            return
        n_shared, pages = entry
        self._free_pages.extend(pages[n_shared:])
        self._set_page_row(slot, [])

    def _shed_expired(self, req: _Request) -> None:
        """Queued request whose propagated deadline passed: error the
        consumer (typed, retriable upstream decision) without ever
        taking a slot. Load shedding, not failure containment."""
        from ...exceptions import DeadlineExceededError  # noqa: PLC0415
        self._requests.pop(req.request_id, None)
        self._event("serve.request.shed", req=req,
                    reason="deadline_expired",
                    late_s=round(time.time() - req.deadline_ts, 3))
        from ...util import events as events_mod  # noqa: PLC0415
        events_mod.emit_safe(
            counter="ray_tpu_serve_requests_shed_total",
            counter_tags={"reason": "deadline_expired"})
        req.out_queue.put(("error", DeadlineExceededError(
            f"deadline expired {time.time() - req.deadline_ts:.3f}s "
            f"before engine admission of {req.request_id}")))

    def _release(self, req: _Request):
        # Slot bookkeeping FIRST, end marker LAST: putting _END wakes the
        # consumer thread, and _set_page_row's jax dispatch below drops
        # the GIL — publishing completion before the slot leaves _active
        # let clients observe (and act on) a request that looked finished
        # while still holding engine state (soak regression: a drained
        # request lingering in _active with its slot already re-freed).
        # The finally guarantees the consumer ALWAYS unblocks, even if a
        # bookkeeping dispatch raises.
        try:
            if req.slot >= 0:
                self._free_slot_pages(req.slot)
                self._free_slots.append(req.slot)
                self._active.pop(req.slot, None)
                self._mask_dirty = True
                self._pen_coef_dirty = True
                req.slot = -1
            if req.first_token_ts is not None and req.generated > 1:
                tpot = ((time.time() - req.first_token_ts)
                        / (req.generated - 1))
                self._tpot_samples.append(tpot)
                try:
                    self._m["tpot"].observe(tpot, tags=self._mtags)
                except Exception:
                    pass
        finally:
            self._event("llm_engine.request_finish", req=req,
                        generated=req.generated, aborted=req.aborted)
            # bounded end-marker publish: a full queue (stalled/gone
            # consumer, e.g. the _CONSUMER_STALL_TTL_S abort path)
            # must not park the loop on a blocking put
            _put_dropping_one(req.out_queue, _END)

    def _decode_window_pages(self) -> int:
        """Power-of-2 page window covering every slot that holds KV
        (active AND chunk-prefilling — a narrower window would let the
        decode scatter's clamped index corrupt a prefilling slot's
        pages) plus this dispatch's new tokens. 0 = full width. The
        static window buckets keep compile count at O(log2 P) while
        decode cost tracks the longest REAL sequence."""
        ps = self.cfg.kv_page_size
        need = (max(self._disp_len.values(), default=0)
                + max(1, self.cfg.decode_block))
        w = _next_pow2(-(-need // ps))
        return 0 if w >= self._pages_per_slot else w

    def _propose_ngram(self, req) -> "Optional[List[int]]":
        """Prompt-lookup proposal: the K tokens that followed the most
        recent earlier occurrence of the trailing `ngram_order`-gram in
        this request's own history. None = no match (plain decode)."""
        k = self.cfg.ngram_speculation
        g = max(1, self.cfg.ngram_order)
        h = req.hist
        if h is None or len(h) < g + 1:
            return None
        key = h[-g:]
        lo = max(0, len(h) - g - 1 - max(g + 1, self.cfg.ngram_lookback))
        for i in range(len(h) - g - 1, lo - 1, -1):
            if h[i:i + g] == key:
                prop = h[i + g:i + g + k]
                return prop or None
        return None

    def _guided_prefill_allow(self, reqs, g: int):
        """(g, V) bool mask rows for a prefill group (padding rows all
        True); None when no member is guided."""
        fsms = [r.fsm for r in reqs if r.fsm is not None]
        if not fsms:
            return None
        V = fsms[0].vocab_size
        A = np.ones((g, V), dtype=bool)
        for i, r in enumerate(reqs):
            if r.fsm is not None:
                A[i] = r.fsm.allowed(r.fsm_state)
        return self._jnp.asarray(A)

    def _guided_decode_allow(self):
        """(S, V) bool mask over all slots for one decode step; None
        when no active request is guided (the unguided decode call then
        stays byte-identical to the ungated build). The host buffer is
        kept across steps and only rows whose FSM state moved are
        rewritten — per step the unavoidable cost is the H2D transfer,
        not a fresh (S, V) allocation + full rebuild."""
        guided = {slot: r for slot, r in self._active.items()
                  if r.fsm is not None}
        if not guided:
            self._guided_prev = None
            return None
        V = next(iter(guided.values())).fsm.vocab_size
        buf = self._guided_allow_buf
        prev = self._guided_prev
        if buf is None or buf.shape != (self._n_slots, V) \
                or prev is None:
            buf = self._guided_allow_buf = np.ones(
                (self._n_slots, V), dtype=bool)
            prev = {}
        for slot in [sl for sl in prev if sl not in guided]:
            buf[slot] = True
            del prev[slot]
        for slot, r in guided.items():
            # key on the request_id, NOT id(r): a freed _Request's
            # address can be reused by a new guided request, which
            # would then silently inherit the stale mask row
            key = (r.request_id, r.fsm_state)
            if prev.get(slot) != key:
                buf[slot] = r.fsm.allowed(r.fsm_state)
                prev[slot] = key
        self._guided_prev = prev
        return self._jnp.asarray(buf)

    def _spec_plan(self):
        """(proposals (S, K) int32 device array, host counts) for one
        speculative verify step, or None when no active slot proposes
        anything or any slot is too close to max_seq_len (the verify
        forward writes K+1 positions). Spec-eligible requests exist
        only when cfg.ngram_speculation > 0 (req.hist gating)."""
        k = self.cfg.ngram_speculation
        if not k:
            return None
        eligible = [(slot, r) for slot, r in self._active.items()
                    if r.hist is not None]
        if not eligible:
            return None
        props = np.full((self._n_slots, k), -1, np.int32)
        any_prop = False
        for slot, r in self._active.items():
            if r.prompt.size + r.generated + k + 1 > self.cfg.max_seq_len:
                return None  # one overlong slot vetoes the step
        for slot, r in eligible:
            p = self._propose_ngram(r)
            if p:
                props[slot, :len(p)] = p
                any_prop = True
        if not any_prop:
            self._spec_idle += 1
            return None
        self._spec_idle = 0
        return self._jnp.asarray(props)

    def _spec_sync_active(self) -> bool:
        """True when speculation wants synchronous stepping (any
        spec-eligible active request): proposals derive from tokens the
        host must have seen."""
        if not self.cfg.ngram_speculation or not any(
                r.hist is not None for r in self._active.values()):
            return False
        # backoff: after 8 consecutive no-proposal steps fall back to
        # pipelined plain decode (sync-only costs throughput for
        # nothing); periodically re-probe in case repetition develops
        self._spec_retry = (self._spec_retry + 1) % 64
        if self._spec_retry == 0:
            self._spec_idle = 0
        return self._spec_idle < 8

    @staticmethod
    def _bias_row(r, V: int) -> "np.ndarray":
        row = np.zeros((V,), np.float32)
        for tid, b in (r.logit_bias or {}).items():
            tid = int(tid)
            if 0 <= tid < V:
                row[tid] = float(b)
        return row

    @staticmethod
    def _req_has_pen(r) -> bool:
        return bool(r.presence_penalty or r.frequency_penalty
                    or r.logit_bias)

    def _pen_active(self) -> bool:
        return any(self._req_has_pen(r) for r in self._active.values())

    def _pen_args(self):
        """(counts, static_bias, presence, freq) device tuple for one
        decode step, or None when no active request uses penalties.
        Seeds count/static rows exactly once per slot assignment (the
        engine loop is the only mutator, and always holds the LATEST
        counts array — prior ones were donated)."""
        if not self._pen_active():
            return None
        jnp = self._jnp
        V = int(self.model.cfg.vocab_size)
        S = self._n_slots
        if self._pen_counts is None:
            self._pen_counts = jnp.zeros((S, V), jnp.int32)
            self._pen_static = jnp.zeros((S, V), jnp.float32)
        for slot, r in self._active.items():
            if self._pen_seeded.get(slot) == r.request_id:
                continue
            self._pen_seeded[slot] = r.request_id
            self._pen_counts = self._pen_counts.at[slot].set(0)
            self._pen_static = self._pen_static.at[slot].set(
                jnp.asarray(self._bias_row(r, V)))
        for slot in [sl for sl in self._pen_seeded
                     if sl not in self._active]:
            del self._pen_seeded[slot]
        if self._pen_coef_dirty or self._pen_coef_dev is None:
            pres = np.zeros((S,), np.float32)
            freq = np.zeros((S,), np.float32)
            for slot, r in self._active.items():
                pres[slot] = r.presence_penalty
                freq[slot] = r.frequency_penalty
            self._pen_coef_dev = (jnp.asarray(pres), jnp.asarray(freq))
            self._pen_coef_dirty = False
        pres_dev, freq_dev = self._pen_coef_dev
        return (self._pen_counts, self._pen_static, pres_dev, freq_dev)

    def _pen_prefill_bias(self, reqs, g: int):
        """(g, V) static logit_bias rows for a prefill group's first
        sampled tokens (presence/frequency are zero then); None when no
        member has a logit_bias."""
        if not any(r.logit_bias for r in reqs):
            return None
        V = int(self.model.cfg.vocab_size)
        B = np.zeros((g, V), np.float32)
        for i, r in enumerate(reqs):
            B[i] = self._bias_row(r, V)
        return self._jnp.asarray(B)

    def _device_mask_temps(self):
        """(active_mask, temps, top_ps) as device arrays, rebuilt only
        when the active set changed — not every step."""
        if self._mask_dirty or self._mask_dev is None:
            S = self._n_slots
            mask = np.zeros((S,), bool)
            temps = np.zeros((S,), np.float32)
            top_ps = np.ones((S,), np.float32)
            for slot, req in self._active.items():
                mask[slot] = True
                temps[slot] = req.temperature
                top_ps[slot] = req.top_p
            self._mask_dev = self._jnp.asarray(mask)
            self._temps_dev = self._jnp.asarray(temps)
            self._top_ps_dev = self._jnp.asarray(top_ps)
            self._mask_dirty = False
        return self._mask_dev, self._temps_dev, self._top_ps_dev

    def _drain_verify(self, snapshot, out_dev, ne_lp):
        """Emit a speculative verify step's 1..K+1 tokens per slot.
        Host emission may stop early (EOS / budget) — those requests
        release immediately, so the device-side length overshoot is
        moot."""
        ne_dev, lp_dev = ne_lp
        try:
            out = np.asarray(out_dev)
            n_emit = np.asarray(ne_dev)
            lps = np.asarray(lp_dev) if lp_dev is not None else None
        except BaseException as e:  # noqa: BLE001
            for slot, req in snapshot:
                if req.slot == slot:
                    req.out_queue.put(("error", e))
                    self._release(req)
            return
        self.stats["decode_steps"] += 1
        for slot, req in snapshot:
            if req.slot != slot:
                continue  # released/reused slot
            if req.generated >= req.max_new_tokens:
                self._release(req)
                continue
            n = int(n_emit[slot])
            emitted = 0
            for j in range(n):
                if req.generated >= req.max_new_tokens:
                    break
                self._emit(req, int(out[slot, j]),
                           float(lps[slot, j]) if lps is not None
                           else None)
                emitted += 1
            self.stats["spec_accepted"] = (
                self.stats.get("spec_accepted", 0) + max(0, emitted - 1))
            if self._paged and req.slot == slot \
                    and slot in self._disp_len:
                # resync the window mirror to the true length (the
                # dispatch bumped it by the K+1 upper bound)
                self._disp_len[slot] = req.prompt.size + req.generated
            full = (req.prompt.size + req.generated
                    >= self.cfg.max_seq_len)
            if req.generated >= req.max_new_tokens or full:
                self._release(req)

    def _drain_one(self, inflight):
        """Fetch the oldest in-flight result and emit its tokens.
        Termination/EOS checks happen here, `pipeline_depth` steps behind
        dispatch; lagged tokens for finished/reused slots are discarded
        by the (req.slot == slot, generated < budget) guards."""
        kind, payload, arr, lp_arr = inflight.popleft()
        if kind == "verify":
            self._drain_verify(payload, arr, lp_arr)
            return
        try:
            host = np.asarray(arr)
            lps = np.asarray(lp_arr) if lp_arr is not None else None
        except BaseException as e:  # noqa: BLE001  device-side failure
            targets = (list(payload) if kind == "prefill_batch"
                       else [r for _, r in payload])
            for req in targets:
                if req.slot >= 0:
                    req.out_queue.put(("error", e))
                    self._release(req)
            return
        if kind == "prefill_batch":
            reqs = payload
            firsts = host.reshape(-1)
            flat_lps = lps.reshape(-1) if lps is not None else None
            for i, req in enumerate(reqs):
                if req.slot < 0:
                    continue
                if req.aborted and req.generated == 0:
                    # aborted while the prefill was in flight: discard
                    # its first token and release without emitting
                    self._release(req)
                    continue
                self._emit(req, int(firsts[i]),
                           float(flat_lps[i]) if flat_lps is not None
                           else None)
                if (req.generated >= req.max_new_tokens
                        or req.prompt.size + req.generated
                        >= self.cfg.max_seq_len):
                    self._release(req)
            return
        rows = host if host.ndim == 2 else host[None, :]  # (K, S)
        lp_rows = None
        if lps is not None:
            lp_rows = lps if lps.ndim == 2 else lps[None, :]
        self.stats["decode_steps"] += rows.shape[0]
        for ri, row in enumerate(rows):
            for slot, req in payload:
                if req.slot != slot:
                    continue  # released/reused slot: lagged, discard
                if req.generated >= req.max_new_tokens:
                    # budget shrank out-of-band (abort()): no further
                    # token will cross the threshold inside _emit, so
                    # release here or the slot decodes forever
                    self._release(req)
                    continue
                self._emit(req, int(row[slot]),
                           float(lp_rows[ri][slot])
                           if lp_rows is not None else None)
                full = (req.prompt.size + req.generated
                        >= self.cfg.max_seq_len)
                if req.generated >= req.max_new_tokens or full:
                    self._release(req)

    def _engine_loop(self):
        inflight = collections.deque()
        while not self._shutdown.is_set():
            try:
                while True:
                    # control commands (paged prefix registration) run
                    # HERE so pool mutations never race a donated buffer
                    try:
                        fn, done = self._control_q.get_nowait()
                    except queue_mod.Empty:
                        break
                    # commands are engine work too: a first-use prefix
                    # prefill can jit-compile for >watchdog_s, so they
                    # get the same compile grace as dispatches (a truly
                    # stuck command still wedges after grace x budget —
                    # the chaos stall exercises exactly that)
                    self._in_dispatch = True
                    try:
                        fn()
                        done.set_result(None)
                    except BaseException as e:  # noqa: BLE001
                        done.set_exception(e)
                    finally:
                        self._in_dispatch = False
                self._in_dispatch = True   # watchdog: compile grace on
                self._admit_all(inflight)
                if self._prefilling:
                    self._dispatch_chunk(inflight)
                allow = (self._guided_decode_allow()
                         if self._active else None)
                pen = self._pen_args() if self._active else None
                # penalties pipeline fine but the verify kernels don't
                # thread them: speculation (and its sync stepping)
                # disables entirely while any penalized request is active
                spec_sync = (self._active and pen is None
                             and self._spec_sync_active())
                need_sync = allow is not None or spec_sync
                if self._active and (not need_sync or not inflight):
                    # guided traffic with results in flight waits for
                    # the drain below: the next mask depends on tokens
                    # the host hasn't seen yet
                    mask, temps, top_ps = self._device_mask_temps()
                    self._rng_key, sub = self._jax.random.split(
                        self._rng_key)
                    snapshot = list(self._active.items())
                    props = (self._spec_plan()
                             if spec_sync and allow is None else None)
                    if props is not None:
                        K = self.cfg.ngram_speculation
                        if self._paged:
                            for slot in self._active:
                                self._disp_len[slot] += K + 1
                            window = self._decode_window_pages()
                            out, n_emit, logps, self._pools, \
                                self._lengths, last = \
                                self._verify_paged_jit(
                                    self.params, self._pools,
                                    self._page_table, self._lengths,
                                    self._last_tokens, props, mask,
                                    temps, top_ps, sub,
                                    window_pages=window)
                        else:
                            out, n_emit, logps, self._cache, last = \
                                self._verify_jit(
                                    self.params, self._cache,
                                    self._last_tokens, props, mask,
                                    temps, top_ps, sub)
                        self._last_tokens = last
                        self._start_fetch(out)
                        self._start_fetch(n_emit)
                        if self.cfg.logprobs:
                            self._start_fetch(logps)
                        self.stats["spec_steps"] = \
                            self.stats.get("spec_steps", 0) + 1
                        inflight.append(
                            ("verify", snapshot, out,
                             (n_emit, logps if self.cfg.logprobs
                              else None)))
                    elif self._paged:
                        window = self._decode_window_pages()
                        akw = {} if allow is None else {"allow": allow}
                        if pen is not None:
                            akw["pen"] = pen
                        if self._decode_block_paged_jit is not None \
                                and allow is None and pen is None:
                            toks, logps, self._pools, self._lengths, \
                                last = self._decode_block_paged_jit(
                                    self.params, self._pools,
                                    self._page_table, self._lengths,
                                    self._last_tokens, mask, temps,
                                    top_ps, sub, window_pages=window)
                            block = max(1, self.cfg.decode_block)
                        else:
                            res = self._decode_paged_jit(
                                self.params, self._pools,
                                self._page_table, self._lengths,
                                self._last_tokens, mask, temps,
                                top_ps, sub, window_pages=window,
                                **akw)
                            if pen is not None:
                                (toks, logps, self._pools,
                                 self._lengths, self._pen_counts) = res
                            else:
                                (toks, logps, self._pools,
                                 self._lengths) = res
                            last = toks
                            block = 1
                        for slot in self._active:
                            # KeyError here = an admission path forgot
                            # to seed _disp_len; fail loudly — a silent
                            # 0 default would shrink the window and
                            # corrupt KV untraceably
                            self._disp_len[slot] += block
                    elif self._decode_block_jit is not None \
                            and allow is None and pen is None:
                        toks, logps, self._cache, last = \
                            self._decode_block_jit(
                                self.params, self._cache,
                                self._last_tokens, mask, temps, top_ps,
                                sub)
                    else:
                        dkw = {} if allow is None else {"allow": allow}
                        if pen is not None:
                            dkw["pen"] = pen
                        res = self._decode_jit(
                            self.params, self._cache, self._last_tokens,
                            mask, temps, top_ps, sub, **dkw)
                        if pen is not None:
                            toks, logps, self._cache, \
                                self._pen_counts = res
                        else:
                            toks, logps, self._cache = res
                        last = toks
                    if props is None:
                        self._last_tokens = last
                        self._start_fetch(toks)
                        if self.cfg.logprobs:
                            self._start_fetch(logps)
                        inflight.append(("decode", snapshot, toks,
                                         logps if self.cfg.logprobs
                                         else None))
                m = self._m = _engine_metrics()
                m["active"].set(float(len(self._active)),
                                tags=self._mtags)
                m["waiting"].set(float(self._waiting.qsize()),
                                 tags=self._mtags)
                m["occupancy"].set(
                    len(self._active) / max(1, self.cfg.max_slots),
                    tags=self._mtags)
                if self._paged:
                    m["kv_util"].set(
                        (self._n_pages - len(self._free_pages))
                        / max(1, self._n_pages), tags=self._mtags)
                if not inflight:
                    self._in_dispatch = False
                    time.sleep(0.002)
                    continue
                # stay `pipeline_depth` steps ahead while decoding;
                # drain fully once nothing is active
                target = self.cfg.pipeline_depth if self._active else 0
                if allow is not None or spec_sync:
                    target = 0  # guided masks / n-gram proposals need
                    #             the previous step's tokens on host
                while len(inflight) > target:
                    self._drain_one(inflight)
                self._in_dispatch = False
            except BaseException as e:  # noqa: BLE001  loop must survive
                import traceback
                traceback.print_exc()
                self._in_dispatch = False
                for req in list(self._active.values()):
                    req.out_queue.put(("error", e))
                    self._release(req)
                inflight.clear()
