"""ray_tpu.serve.llm — LLM serving on the continuous-batching engine.

Reference parity: the fork's `serve.llm` vLLM integration
(build_llm_deployment / LLMServer): one replica owns the TPU chip and an
LLMEngine; requests stream tokens via the serve streaming path.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ..deployment import Application, deployment_decorator
from .engine import LLMEngine, LLMEngineConfig
from .guided import (GuidedSpec, TokenFSM, compile_guided,
                     json_schema_to_regex)


class LLMServer:
    """Deployment class wrapping an LLMEngine.

    `model_factory` is a zero-arg callable returning (model, params) —
    kept as a factory so weights load inside the replica process (on the
    TPU host), not in the driver.

    `cached_prefixes`: shared prompt prefixes (strings or token lists,
    e.g. the system prompt) registered on the engine at startup; any
    request whose prompt starts with one adopts its KV instead of
    re-prefilling it (engine prefix caching).

    Matching is TOKEN-level (correctness is never at risk — a miss
    just pays the normal full prefill). For STRING prefixes under a
    BPE tokenizer, prefer passing token ids that align with how full
    prompts tokenize: a merge across the prefix/suffix boundary (or a
    chat template) makes encode(prefix) not a token-prefix of
    encode(prefix + suffix) and the cache silently never matches —
    watch the engine's `prefix_tokens_saved` stat to confirm hits.
    """

    def __init__(self, model_factory, engine_config: Optional[dict] = None,
                 tokenizer: Optional[Any] = None,
                 cached_prefixes: Optional[list] = None):
        model, params = model_factory()
        engine_config = dict(engine_config or {})
        if cached_prefixes:
            engine_config.setdefault("max_prefixes",
                                     len(cached_prefixes))
        cfg = LLMEngineConfig(**engine_config)
        self.engine = LLMEngine(model, params, cfg)
        self.tokenizer = tokenizer
        import threading
        self._prefix_lock = threading.Lock()
        self._prefix_keys = {}          # affinity key -> engine pid
        self._prefix_inflight = set()   # keys mid-registration
        self._cached_prefixes = []      # (tokens, pid), longest first
        for p in cached_prefixes or []:
            ids = np.asarray(self._encode(p), np.int32).reshape(-1)
            pid = self.engine.register_prefix(ids)
            self._cached_prefixes.append((ids, pid))
        self._cached_prefixes.sort(key=lambda t: -t[0].size)

    def _match_prefix(self, prompt):
        """(submit_prompt, prefix_id): strip the longest registered
        prefix the prompt starts with; the engine re-attaches its
        tokens but adopts its KV by copy."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        with self._prefix_lock:
            prefixes = list(self._cached_prefixes)
        for ids, pid in prefixes:
            if prompt.size > ids.size and np.array_equal(
                    prompt[:ids.size], ids):
                return prompt[ids.size:], pid
        return prompt, None

    def register_prefix(self, body: Dict[str, Any]) -> Dict[str, Any]:
        """Dynamic shared-prefix registration (scale-out router path):
        the serve controller pushes `serve.register_prefix(...)`
        payloads here — to the affinity ring owner at registration time
        and to every replica started afterwards. body: {"prefix":
        str | [token ids], "key": affinity key}. Idempotent per key;
        requires engine_config max_prefixes > 0 (the engine's KV slots
        for warm prefixes)."""
        key = body.get("key") or ""
        prefix = body["prefix"]
        ids = np.asarray(self._encode(prefix), np.int32).reshape(-1)
        with self._prefix_lock:
            pid = self._prefix_keys.get(key) if key else None
            if pid is not None:
                return {"key": key, "prefix_id": int(pid),
                        "prefix_tokens": int(ids.size)}
            if key in self._prefix_inflight:
                # a concurrent push (controller re-warm racing the
                # _check_started push) is already prefilling this key —
                # don't burn a second engine prefix slot on it
                return {"key": key, "prefix_id": -1, "pending": True}
            self._prefix_inflight.add(key)
        try:
            # the prefill can take seconds cold — never under the lock
            # (the request path's _match_prefix reads under it)
            pid = self.engine.register_prefix(ids)
        finally:
            with self._prefix_lock:
                self._prefix_inflight.discard(key)
        with self._prefix_lock:
            if key:
                self._prefix_keys[key] = pid
            self._cached_prefixes.append((ids, pid))
            self._cached_prefixes.sort(key=lambda t: -t[0].size)
        return {"key": key, "prefix_id": int(pid),
                "prefix_tokens": int(ids.size)}

    def _encode(self, prompt):
        if isinstance(prompt, str):
            if self.tokenizer is None:
                raise ValueError(
                    "text prompt but no tokenizer configured; pass token "
                    "ids or set tokenizer=")
            return self.tokenizer.encode(prompt)
        return prompt

    def _decode_tok(self, tok: int):
        if self.tokenizer is not None:
            return self.tokenizer.decode([tok])
        return tok

    def __call__(self, body: Dict[str, Any]):
        """Unary or streaming generate. body: {"prompt": [ids] | str,
        "max_tokens": int, "temperature": float, "top_p": float,
        "stop_token_ids": [ids], "stream": bool}."""
        from ..context import get_request_deadline
        prompt, prefix_id = self._match_prefix(
            self._encode(body["prompt"]))
        max_tokens = body.get("max_tokens")
        temperature = float(body.get("temperature", 0.0))
        rid = self.engine.submit(
            prompt, max_tokens, temperature,
            top_p=float(body.get("top_p", 1.0)),
            stop_token_ids=body.get("stop_token_ids"),
            prefix_id=prefix_id,
            deadline_ts=get_request_deadline())
        if body.get("stream"):
            def gen():
                for tok in self.engine.stream(rid):
                    yield self._decode_tok(tok)
            return gen()
        toks = list(self.engine.stream(rid))
        if self.tokenizer is not None:
            return {"text": self.tokenizer.decode(toks), "tokens": toks}
        return {"tokens": toks}

    def generate(self, body: Dict[str, Any]):
        return self(body)

    def stats(self, _body=None) -> Dict[str, Any]:
        return self.engine.get_stats()

    def autoscale_metrics(self) -> Dict[str, Any]:
        """Replica.get_autoscale_metrics hook: the live engine signals
        the serve autoscaler's SLO terms key on (queue depth, TTFT/TPOT,
        KV-page utilization) plus prefix-cache savings for the router's
        affinity accounting."""
        s = self.engine.get_stats()
        out: Dict[str, Any] = {
            "queue_depth": float(s.get("waiting", 0) or 0),
            "active_slots": float(s.get("active", 0) or 0),
            "prefix_tokens_saved": float(
                s.get("prefix_tokens_saved", 0) or 0),
        }
        kv = s.get("kv_pages") or {}
        if kv.get("total"):
            out["kv_util"] = kv["in_use"] / max(kv["total"], 1)
        ttft = s.get("ttft_breakdown_p50_ms") or {}
        if ttft.get("total_ms") is not None:
            out["ttft_p50_ms"] = float(ttft["total_ms"])
        if s.get("tpot_p50_ms") is not None:
            out["tpot_ms"] = float(s["tpot_p50_ms"])
        return out

    def check_health(self):
        if not self.engine._loop_thread.is_alive():
            raise RuntimeError("engine loop died")
        if self.engine.wedged:
            from ...exceptions import EngineWedgedError
            raise EngineWedgedError(
                "wedged: engine loop made no forward progress past "
                "its watchdog window; replica must be replaced")


def build_llm_deployment(model_factory, *, engine_config=None,
                         tokenizer=None, name: str = "LLMServer",
                         num_replicas: int = 1,
                         max_ongoing_requests: int = 32,
                         cached_prefixes=None,
                         server_cls=None, server_kwargs=None,
                         route_prefix: str = "/") -> Application:
    """Build a ready-to-run LLM serving app:
    `serve.run(build_llm_deployment(factory))`. `server_cls` swaps the
    deployment class (e.g. openai_api.OpenAIServer); `cached_prefixes`
    registers shared prompt prefixes for engine prefix caching."""
    dep = deployment_decorator(
        server_cls or LLMServer, name=name, num_replicas=num_replicas,
        max_ongoing_requests=max_ongoing_requests,
        route_prefix=route_prefix)
    return dep.bind(model_factory, engine_config=engine_config,
                    tokenizer=tokenizer,
                    cached_prefixes=cached_prefixes,
                    **(server_kwargs or {}))


def __getattr__(name):
    if name in ("OpenAIServer", "build_openai_deployment"):
        from . import openai_api
        return getattr(openai_api, name)
    raise AttributeError(name)


__all__ = ["LLMEngine", "LLMEngineConfig", "GuidedSpec",
           "json_schema_to_regex",
           "TokenFSM", "compile_guided", "LLMServer",
           "build_llm_deployment", "OpenAIServer",
           "build_openai_deployment"]
