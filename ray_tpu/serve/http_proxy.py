"""HTTP ingress actor.

Reference parity: python/ray/serve/_private/proxy.py + http_util.py —
re-based on the stdlib ThreadingHTTPServer (no uvicorn/starlette in-image).
Routes by longest-prefix match against the controller's route table; JSON
in/out; `Accept: text/event-stream` upgrades the call to the streaming
path and emits SSE `data:` events per chunk.
"""
from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..exceptions import classify_request_failure
from .asgi import START_KEY
from .config import default_request_timeout_s as _default_timeout_s
from .handle import DeploymentHandle

PROXY_NAME = "_SERVE_PROXY"

# symbolic failure class (exceptions.classify_request_failure — shared
# with the gRPC ingress) -> (http_status, retry_after_s | None).
# Shed/no-capacity outcomes are RETRIABLE: 429/503 with Retry-After so
# well-behaved clients back off and resubmit; a deadline that expired
# mid-execution is the client's budget running out: 504.
_STATUS_BY_CLASS = {"backpressure": (429, 1),
                    "no_capacity": (503, 1),
                    "shed": (503, 1),         # never executed
                    "interrupted": (503, 1),  # retriable mid-stream loss
                    "timeout": (504, None),   # executed, budget blown
                    "error": (500, None)}


def _status_for(exc: BaseException):
    return _STATUS_BY_CLASS[classify_request_failure(exc)]


class HTTPProxy:
    """Actor: owns the HTTP server; refreshes routes from the controller."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        self._host = host
        self._port = port
        self._routes = {}           # prefix -> DeploymentHandle
        self._asgi = {}             # prefix -> bool (serve.ingress app)
        self._routes_lock = threading.Lock()
        self._server: Optional[ThreadingHTTPServer] = None
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # silence request logging
                pass

            def _match(self):
                """(handle, prefix, is_asgi) for the longest prefix."""
                with proxy._routes_lock:
                    routes = dict(proxy._routes)
                    asgi = dict(proxy._asgi)
                path = self.path.split("?", 1)[0]
                for prefix in sorted(routes, key=len, reverse=True):
                    norm = prefix.rstrip("/") or "/"
                    if path == norm or path.startswith(
                            norm if norm == "/" else norm + "/"):
                        return (routes[prefix], norm,
                                asgi.get(prefix, False))
                return None, None, False

            def _body(self):
                n = int(self.headers.get("Content-Length") or 0)
                raw = self.rfile.read(n) if n else b""
                ctype = self.headers.get("Content-Type", "")
                if "application/json" in ctype and raw:
                    return json.loads(raw)
                return raw.decode() if raw else None

            def _respond(self, code, body, ctype="application/json",
                         retry_after=None):
                data = body if isinstance(body, bytes) else body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                if retry_after is not None:
                    self.send_header("Retry-After", str(retry_after))
                self.end_headers()
                self.wfile.write(data)

            def _affinity_kw(self):
                """Session affinity from the X-Serve-Session-Id header:
                requests carrying it are sticky-routed to the session's
                bound replica (warm KV prefix) by the handle's router.
                Dict bodies may carry session_id/a registered prefix
                instead — the handle extracts those itself."""
                sid = self.headers.get("X-Serve-Session-Id")
                return {"__serve_affinity_key": sid} if sid else {}

            def _deadline(self):
                """Absolute deadline for this request: client-supplied
                X-Serve-Timeout-S budget, else the proxy default. It
                propagates proxy -> handle -> replica -> engine
                admission; retries keep the ORIGINAL deadline. Only
                the OPERATOR env knob may disable the bound (<= 0 →
                no deadline); a non-positive client header falls back
                to the default — an untrusted header must not be able
                to pin proxy threads forever."""
                raw = self.headers.get("X-Serve-Timeout-S")
                budget = None
                if raw:
                    try:
                        # cap: an untrusted header may shrink the bound
                        # but never extend it past an hour
                        budget = min(float(raw), 3600.0)
                    except ValueError:
                        budget = None
                if budget is None or budget <= 0:
                    budget = _default_timeout_s()
                return None if budget <= 0 else time.time() + budget

            def _fail(self, e, headers_sent=False, emit=None):
                """Map a request failure to a response (pre-headers) or
                a terminal SSE error event (mid-stream)."""
                code, retry_after = _status_for(e)
                try:
                    if headers_sent:
                        if emit is not None:
                            # mid-stream failure: a second status line
                            # would corrupt the chunked body — emit one
                            # final error event and end the stream
                            emit(json.dumps({"error": repr(e)}).encode())
                            self.wfile.write(b"0\r\n\r\n")
                    else:
                        self._respond(code, json.dumps(
                            {"error": repr(e)}), retry_after=retry_after)
                except Exception:  # noqa: BLE001  client went away
                    pass

            def _serialize(self, result):
                if isinstance(result, bytes):
                    return result, "application/octet-stream"
                if isinstance(result, str):
                    return result, "text/plain"
                return json.dumps(result), "application/json"

            def _handle_asgi(self, handle, prefix):
                """serve.ingress(app) route: ship the RAW request to the
                replica (the ASGI wrapper drives the app there) and
                relay its streamed response — start item first, then
                body chunks — as a chunked HTTP response. SSE and plain
                responses flow through the same path."""
                path = self.path.split("?", 1)[0]
                query = (self.path.split("?", 1)[1]
                         if "?" in self.path else "")
                n = int(self.headers.get("Content-Length") or 0)
                request = {
                    "method": self.command,
                    "path": path,
                    "query": query,
                    "root_path": "" if prefix == "/" else prefix,
                    "headers": list(self.headers.items()),
                    "body": self.rfile.read(n) if n else b"",
                }
                headers_sent = False
                bodiless = False   # 1xx/204/304: no body, no chunking
                gen = None
                try:
                    gen = handle.options(stream=True).remote(
                        request, __serve_deadline_ts=self._deadline(),
                        **self._affinity_kw())
                    for item in gen:
                        if isinstance(item, dict) and item.get(START_KEY):
                            status = item["status"]
                            bodiless = (status in (204, 304)
                                        or 100 <= status < 200)
                            self.send_response(status)
                            for k, v in item["headers"]:
                                if k.lower() in ("content-length",
                                                 "transfer-encoding"):
                                    continue  # we re-frame as chunked
                                self.send_header(k, v)
                            if not bodiless:
                                self.send_header("Transfer-Encoding",
                                                 "chunked")
                            self.end_headers()
                            headers_sent = True
                            continue
                        if bodiless:
                            continue  # RFC: such responses have no body
                        chunk = (item if isinstance(item, bytes)
                                 else bytes(item))
                        self.wfile.write(f"{len(chunk):x}\r\n".encode()
                                         + chunk + b"\r\n")
                        self.wfile.flush()
                    if not headers_sent:
                        raise RuntimeError("ASGI app sent no response")
                    if not bodiless:
                        self.wfile.write(b"0\r\n\r\n")
                except Exception as e:  # noqa: BLE001
                    try:
                        if headers_sent:
                            # mid-stream failure: closing WITHOUT the
                            # chunked terminator signals truncation —
                            # a clean terminator would make the partial
                            # body indistinguishable from success
                            self.close_connection = True
                        else:
                            code, retry_after = _status_for(e)
                            self._respond(code, json.dumps(
                                {"error": repr(e)}),
                                retry_after=retry_after)
                    except Exception:  # noqa: BLE001  client went away
                        pass
                finally:
                    if gen is not None:
                        gen.close()

            def _handle(self):
                handle, prefix, is_asgi = self._match()
                if handle is None:
                    self._respond(404, json.dumps(
                        {"error": f"no route for {self.path}"}))
                    return
                if is_asgi:
                    self._handle_asgi(handle, prefix)
                    return
                try:
                    body = self._body()
                except (ValueError, json.JSONDecodeError) as e:
                    self._respond(400, json.dumps({"error": repr(e)}))
                    return
                # SSE when the client asks via Accept OR via the
                # OpenAI-style {"stream": true} body field
                wants_stream = ("text/event-stream" in (
                    self.headers.get("Accept") or "")
                    or (isinstance(body, dict) and bool(
                        body.get("stream"))))
                deadline_ts = self._deadline()
                headers_sent = False
                gen = None
                emit = None
                try:
                    if wants_stream:
                        gen = handle.options(stream=True).remote(
                            body, __serve_deadline_ts=deadline_ts,
                            **self._affinity_kw())
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         "text/event-stream")
                        self.send_header("Cache-Control", "no-cache")
                        self.send_header("Transfer-Encoding", "chunked")
                        self.end_headers()
                        headers_sent = True

                        def emit(payload: bytes):
                            event = b"data: " + payload + b"\n\n"
                            self.wfile.write(
                                f"{len(event):x}\r\n".encode()
                                + event + b"\r\n")
                            self.wfile.flush()

                        for chunk in gen:
                            payload, _ = self._serialize(chunk)
                            if isinstance(payload, str):
                                payload = payload.encode()
                            emit(payload)
                        self.wfile.write(b"0\r\n\r\n")
                    else:
                        result = handle.remote(
                            body, __serve_deadline_ts=deadline_ts,
                            **self._affinity_kw()
                        ).result(timeout_s=(
                            None if deadline_ts is None
                            else max(0.1, deadline_ts - time.time())))
                        payload, ctype = self._serialize(result)
                        self._respond(200, payload, ctype)
                except Exception as e:  # noqa: BLE001
                    self._fail(e, headers_sent=headers_sent, emit=emit)
                finally:
                    if gen is not None:
                        # abandoned stream (client hung up): release
                        # the replica's manual in-flight count — reused
                        # handles would otherwise leak it forever
                        gen.close()

            do_GET = do_POST = do_PUT = do_DELETE = _handle

        class Server(ThreadingHTTPServer):
            # socketserver's default listen backlog of 5 RSTs excess
            # connections under a concurrent burst (observed: 24
            # simultaneous clients losing 4 to ECONNRESET) — a serve
            # ingress must absorb bursts, not reset them
            request_queue_size = 128

        self._server = Server((host, port), Handler)
        self._port = self._server.server_address[1]
        threading.Thread(target=self._server.serve_forever, daemon=True,
                         name="serve-http").start()
        threading.Thread(target=self._route_refresh_loop, daemon=True,
                         name="serve-http-routes").start()

    def _route_refresh_loop(self):
        from ._proxy_util import rebuild_handles, refresh_routes_forever

        def apply(routes):
            with self._routes_lock:
                self._routes = rebuild_handles(self._routes, routes)
                self._asgi = {k: bool(len(v) > 2 and v[2])
                              for k, v in routes.items()}

        refresh_routes_forever(lambda ctrl: ctrl.get_routes.remote(),
                               apply)

    def address(self):
        return (self._host, self._port)

    def ready(self) -> int:
        return self._port

    def ping(self) -> bool:
        return True


def start_proxy(host: str = "127.0.0.1", port: int = 8000):
    """Start (or fetch) the proxy actor; returns (handle, bound_port)."""
    from ._proxy_util import get_or_create_proxy
    return get_or_create_proxy(PROXY_NAME, HTTPProxy, host, port)
