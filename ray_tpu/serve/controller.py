"""Serve controller actor: reconciles deployment state.

Reference parity: python/ray/serve/_private/controller.py +
deployment_state.py (target-state reconciliation, health checks, rolling
updates) and autoscaling_state.py (metrics-driven replica counts). One
controller actor per cluster; a background thread runs the reconcile loop
so control-plane progress never depends on incoming calls.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Optional

from .config import DeploymentConfig, ReplicaInfo

CONTROLLER_NAME = "_SERVE_CONTROLLER"
_LOOP_PERIOD_S = 0.25
# how often each RUNNING replica is polled for autoscale metrics when
# the deployment sets no autoscaling_config (victim selection for
# least-busy scale-down still wants a load sample)
_METRICS_PERIOD_S = 0.5
# sticky session/prefix bindings remembered per deployment for the
# state API / dashboard router table
_BINDINGS_CAP = 1024


def _env_float(name: str, default: float) -> float:
    """Env knob with a per-deployment-config fallback: the serve FT
    knobs (RAY_TPU_SERVE_HEALTH_PERIOD_S/_TIMEOUT_S/_THRESHOLD) apply
    cluster-wide when set; otherwise each deployment's config wins."""
    from ..util import knobs
    return knobs.get_float(name, default=default)


def _emit_serve_event(etype: str, message: str = "", **attrs) -> None:
    """Serve-plane lifecycle event; ships via the worker telemetry
    channel like every other event. Never fails control-plane work."""
    from ..util import events as events_mod
    events_mod.emit_safe(etype, message, **attrs)


class _DeploymentState:
    def __init__(self, app_name: str, name: str, callable_bytes: bytes,
                 init_args, init_kwargs, config: DeploymentConfig,
                 version: str, route_prefix: Optional[str],
                 is_ingress: bool, is_asgi: bool = False):
        self.app_name = app_name
        self.name = name
        self.callable_bytes = callable_bytes
        self.init_args = init_args
        self.init_kwargs = init_kwargs
        self.config = config
        self.version = version
        self.route_prefix = route_prefix
        self.is_ingress = is_ingress
        self.is_asgi = is_asgi
        self.replicas: List[ReplicaInfo] = []
        self.target_num: int = self._initial_target()
        self._replica_seq = 0
        self._last_metrics: Dict[str, float] = {}
        self._ongoing_history: List[tuple] = []  # (ts, total_ongoing)
        self._last_scale_ts = 0.0
        # shared prompt prefixes registered against this deployment
        # (serve.register_prefix): rows {"key", "prefix"}. Pushed to
        # the affinity ring owner at registration and to every replica
        # that starts afterwards, so warmth survives replacement.
        self.registered_prefixes: List[dict] = []
        # placement-group bundles reserved by a scale-up, consumed one
        # per _start_replica: [(pg_id, bundle_index), ...]
        self._pending_pg_bundles: List[tuple] = []
        # sticky-routing bindings reported by handles (router table)
        self.bindings: "collections.OrderedDict[str, dict]" = \
            collections.OrderedDict()
        self.binding_counts: Dict[str, int] = {}
        self._start_failures = 0  # consecutive replica-init failures
        # replica ids killed for unhealthiness/death whose replacement
        # hasn't started yet: _start_replica pops one per start and
        # emits serve.replica.replaced linking old -> new
        self._pending_replacements: List[str] = []
        self.status = "UPDATING"
        self.message = ""

    def _initial_target(self) -> int:
        ac = self.config.autoscaling_config
        if ac is not None:
            return ac.initial_replicas if ac.initial_replicas is not None \
                else ac.min_replicas
        return self.config.num_replicas

    def next_replica_id(self) -> str:
        self._replica_seq += 1
        return f"{self.app_name}#{self.name}#{self._replica_seq}"


class ServeController:
    """Actor. Owns all deployment state; creates/destroys replica actors."""

    def __init__(self, http_options: Optional[dict] = None):
        from .autoscaler import ServeAutoscaler
        self._deployments: Dict[str, _DeploymentState] = {}  # key: app/name
        self._apps: Dict[str, List[str]] = {}  # app -> deployment keys
        # deployment states removed from _deployments that still have
        # STOPPING replicas draining; the control loop finishes them
        self._stopping_states: List[_DeploymentState] = []
        self._autoscaler = ServeAutoscaler()
        # placement-group refcounts: pg removed when its last replica is
        # gone (pg_id -> live replica count); removals queue here and
        # the control loop drains them OUTSIDE the lock (the removal is
        # a driver round trip)
        self._pg_refs: Dict[str, int] = {}
        self._pgs_to_remove: List[str] = []
        self._lock = threading.RLock()
        self._shutdown = threading.Event()
        self._http_options = http_options or {}
        self._loop_thread = threading.Thread(
            target=self._control_loop, daemon=True, name="serve-controller")
        self._loop_thread.start()

    # ---- API called by serve.api ------------------------------------------
    def deploy_application(self, app_name: str,
                           deployments: List[dict]) -> None:
        """Set target state for an app. Idempotent; changed versions roll."""
        with self._lock:
            keys = []
            for d in deployments:
                key = f"{app_name}/{d['name']}"
                keys.append(key)
                cfg = DeploymentConfig(**d["config"])
                existing = self._deployments.get(key)
                if existing is None:
                    self._deployments[key] = _DeploymentState(
                        app_name, d["name"], d["callable_bytes"],
                        d["init_args"], d["init_kwargs"], cfg, d["version"],
                        d.get("route_prefix"), d.get("is_ingress", False),
                        d.get("is_asgi", False))
                else:
                    existing.callable_bytes = d["callable_bytes"]
                    existing.init_args = d["init_args"]
                    existing.init_kwargs = d["init_kwargs"]
                    existing.config = cfg
                    existing.route_prefix = d.get("route_prefix")
                    existing.is_ingress = d.get("is_ingress", False)
                    existing.is_asgi = d.get("is_asgi", False)
                    if existing.version != d["version"]:
                        existing.version = d["version"]
                        existing.status = "UPDATING"
                    existing._start_failures = 0  # redeploy resets backoff
                    if existing.config.autoscaling_config is None:
                        existing.target_num = cfg.num_replicas
            # drop deployments removed from the app
            for key in list(self._apps.get(app_name, [])):
                if key not in keys:
                    self._stop_deployment(key)
            self._apps[app_name] = keys

    def delete_application(self, app_name: str) -> None:
        with self._lock:
            for key in self._apps.pop(app_name, []):
                self._stop_deployment(key)

    def list_applications(self) -> Dict[str, List[str]]:
        with self._lock:
            return {a: [k.split("/", 1)[1] for k in keys]
                    for a, keys in self._apps.items()}

    def get_replicas(self, app_name: str, deployment_name: str) -> List[Any]:
        """Routing table for handles: [(replica_id, actor_handle), ...]."""
        with self._lock:
            st = self._deployments.get(f"{app_name}/{deployment_name}")
            if st is None:
                return []
            return [(r.replica_id, r.actor_handle) for r in st.replicas
                    if r.state == "RUNNING"]

    def get_deployment_info(self, app_name: str,
                            deployment_name: str) -> Optional[dict]:
        with self._lock:
            st = self._deployments.get(f"{app_name}/{deployment_name}")
            if st is None:
                return None
            return {"name": st.name, "app": st.app_name,
                    "version": st.version, "status": st.status,
                    "message": st.message,
                    "target_num_replicas": st.target_num,
                    "num_running": sum(1 for r in st.replicas
                                       if r.state == "RUNNING"),
                    "route_prefix": st.route_prefix,
                    "is_ingress": st.is_ingress,
                    "max_ongoing_requests":
                        st.config.max_ongoing_requests,
                    "max_queued_requests":
                        st.config.max_queued_requests,
                    "registered_prefixes":
                        [dict(row) for row in st.registered_prefixes]}

    # ---- scale-out router surface -----------------------------------------
    def register_prefix(self, app_name: str, deployment_name: str,
                        prefix, key: Optional[str] = None) -> str:
        """Register a shared prompt prefix against a deployment.

        The prefix is pushed (via the deployment callable's
        `register_prefix` method, e.g. LLMServer's) to the replica that
        owns `key` on the affinity hash ring — the SAME deterministic
        ring every handle routes prefix-keyed requests with, so traffic
        lands on the warm replica without coordination — and to every
        replica that starts later (replacements, scale-ups), so warmth
        survives replica death. Returns the affinity key."""
        from .router import prefix_key, ring_order
        if key is None:
            key = prefix_key(prefix)
        row = {"key": key, "prefix": prefix}
        with self._lock:
            st = self._deployments.get(f"{app_name}/{deployment_name}")
            if st is None:
                raise KeyError(
                    f"no deployment {app_name}/{deployment_name}")
            if any(r["key"] == key for r in st.registered_prefixes):
                return key               # idempotent
            st.registered_prefixes.append(row)
            running = [(r.replica_id, r.actor_handle)
                       for r in st.replicas if r.state == "RUNNING"]
        order = ring_order(key, [rid for rid, _h in running])
        if order:
            target = dict(running)[order[0]]
            try:
                # fire-and-forget: a failed push only costs the first
                # request a cold prefill (the replica registers lazily
                # through its own register_prefix handler)
                target.handle_request.remote(
                    "register_prefix", (dict(row),), {})
            except Exception:  # noqa: BLE001
                pass
        return key

    def note_session_binding(self, app_name: str, deployment_name: str,
                             key: str, replica_id: str,
                             outcome: str) -> None:
        """Handles report sticky-binding transitions here (best-effort)
        so the router table is centrally introspectable — and so a
        registered prefix FOLLOWS its key: when a key re-binds (its
        warm replica died or was diverted), the prefix is pushed to the
        new home, which re-warms it for every request after the first.
        Replacement replicas get prefixes eagerly in _check_started;
        this covers keys remapped onto pre-existing replicas."""
        push = None
        with self._lock:
            st = self._deployments.get(f"{app_name}/{deployment_name}")
            if st is None:
                return
            st.bindings[key] = {"replica_id": replica_id,
                                "outcome": outcome, "ts": time.time()}
            st.bindings.move_to_end(key)
            while len(st.bindings) > _BINDINGS_CAP:
                st.bindings.popitem(last=False)
            st.binding_counts[outcome] = \
                st.binding_counts.get(outcome, 0) + 1
            row = next((p for p in st.registered_prefixes
                        if p["key"] == key), None)
            if row is not None:
                handle = next((r.actor_handle for r in st.replicas
                               if r.replica_id == replica_id
                               and r.state == "RUNNING"), None)
                if handle is not None:
                    push = (handle, dict(row))
        if push is not None:
            try:
                # idempotent replica-side (keyed); a lost push costs
                # cold prefills until the next binding transition
                push[0].handle_request.remote(
                    "register_prefix", (push[1],), {})
            except Exception:  # noqa: BLE001
                pass

    def get_router_table(self) -> Dict[str, Any]:
        """Per-deployment routing view: RUNNING replica ids (the hash
        ring membership), registered prefixes, and the recent sticky
        bindings handles reported."""
        from .router import ring_order
        with self._lock:
            out = {}
            for dep_key, st in self._deployments.items():
                running = [r.replica_id for r in st.replicas
                           if r.state == "RUNNING"]
                out[dep_key] = {
                    "replicas": running,
                    "registered_prefixes": [
                        {"key": row["key"],
                         "owner": (ring_order(row["key"], running) or
                                   [None])[0]}
                        for row in st.registered_prefixes],
                    "bindings": {k: dict(v)
                                 for k, v in st.bindings.items()},
                    "binding_transitions": dict(st.binding_counts),
                }
            return out

    def get_autoscaler_status(self) -> Dict[str, Any]:
        """Autoscaler targets + the recent decision log (scale_up /
        scale_down rows with reasons and placement annotations)."""
        with self._lock:
            per = {}
            for dep_key, st in self._deployments.items():
                ac = st.config.autoscaling_config
                per[dep_key] = {
                    "target_num_replicas": st.target_num,
                    "num_running": sum(1 for r in st.replicas
                                       if r.state == "RUNNING"),
                    "autoscaling": None if ac is None else {
                        "min_replicas": ac.min_replicas,
                        "max_replicas": ac.max_replicas,
                        "target_ongoing_requests":
                            ac.target_ongoing_requests,
                        "ttft_slo_ms": ac.ttft_slo_ms,
                        "tpot_slo_ms": ac.tpot_slo_ms,
                        "target_queue_depth": ac.target_queue_depth},
                }
            return {"deployments": per,
                    "decisions": self._autoscaler.snapshot()}

    def get_app_status(self, app_name: str) -> dict:
        with self._lock:
            keys = self._apps.get(app_name, [])
            deps = {}
            overall = "RUNNING"  # reference ApplicationStatus: RUNNING=ok
            for key in keys:
                st = self._deployments[key]
                deps[st.name] = {"status": st.status,
                                 "replicas": len([r for r in st.replicas
                                                  if r.state == "RUNNING"]),
                                 "target": st.target_num}
                if st.status == "DEPLOY_FAILED":
                    overall = "DEPLOY_FAILED"
                elif st.status != "HEALTHY" and overall == "RUNNING":
                    overall = "DEPLOYING"
            return {"app": app_name, "status": overall,
                    "deployments": deps}

    def get_http_config(self) -> dict:
        return dict(self._http_options)

    def get_routes(self) -> Dict[str, tuple]:
        """route_prefix -> (app_name, ingress name, is_asgi)."""
        with self._lock:
            routes = {}
            for key, st in self._deployments.items():
                if st.is_ingress and st.route_prefix is not None:
                    routes[st.route_prefix] = (st.app_name, st.name,
                                               st.is_asgi)
            return routes

    def get_ingress_targets(self) -> Dict[str, str]:
        """app_name -> ingress deployment name, INCLUDING apps with
        route_prefix=None (gRPC-only apps have no HTTP prefix but are
        still addressable by application name)."""
        with self._lock:
            return {st.app_name: st.name
                    for st in self._deployments.values()
                    if st.is_ingress}

    def list_replicas(self, app_name: str,
                      deployment_name: str) -> List[dict]:
        """Full replica-state snapshot (all states, health counters) —
        chaos tooling and tests introspect through this."""
        with self._lock:
            st = self._deployments.get(f"{app_name}/{deployment_name}")
            if st is None:
                return []
            return [{"replica_id": r.replica_id, "state": r.state,
                     "version": r.version,
                     "health_failures": r.health_failures,
                     "actor_id": getattr(r.actor_handle, "actor_id",
                                         None)}
                    for r in st.replicas]

    def graceful_shutdown(self) -> None:
        with self._lock:
            # the drain wait must honor the LONGEST configured
            # per-deployment graceful_shutdown_timeout_s (snapshot
            # before delete_application moves states to _stopping)
            max_drain = max(
                (st.config.graceful_shutdown_timeout_s
                 for st in self._deployments.values()), default=0.0)
            for app in list(self._apps):
                self.delete_application(app)
        # let the control loop finish draining STOPPING replicas before
        # tearing the loop down (bounded: drains are themselves bounded
        # by each deployment's graceful_shutdown_timeout_s)
        deadline = time.time() + max_drain + 2.0
        while time.time() < deadline:
            with self._lock:
                if not self._stopping_states:
                    break
            time.sleep(0.05)
        self._shutdown.set()

    def ping(self) -> bool:
        return True

    # ---- driver-restart persistence ---------------------------------------
    # The controller is a NAMED actor, so a resumed driver
    # (init(resume=True), core/persistence.py) restarts it and hands
    # back the last checkpoint: __ray_save__ captures the deployment
    # TARGETS (code, config, version, routes — not live replica
    # handles), __ray_restore__ re-deploys them and the reconcile loop
    # starts fresh replicas, so traffic resumes after a driver crash.
    def __ray_save__(self) -> dict:
        with self._lock:
            apps = {}
            for app, keys in self._apps.items():
                rows = []
                for key in keys:
                    st = self._deployments.get(key)
                    if st is None:
                        continue
                    rows.append({
                        "name": st.name,
                        "callable_bytes": st.callable_bytes,
                        "init_args": st.init_args,
                        "init_kwargs": st.init_kwargs,
                        "config": st.config.to_dict(),
                        "version": st.version,
                        "route_prefix": st.route_prefix,
                        "is_ingress": st.is_ingress,
                        "is_asgi": st.is_asgi,
                        "registered_prefixes":
                            [dict(p) for p in st.registered_prefixes],
                    })
                apps[app] = rows
            return {"apps": apps,
                    "http_options": dict(self._http_options)}

    def __ray_restore__(self, saved: dict) -> None:
        self._http_options = saved.get("http_options") \
            or self._http_options
        for app, deployments in (saved.get("apps") or {}).items():
            if deployments:
                self.deploy_application(app, deployments)
                # restore registered prefixes: replicas started by the
                # redeploy get them pushed on the _check_started path
                with self._lock:
                    for d in deployments:
                        st = self._deployments.get(f"{app}/{d['name']}")
                        if st is not None:
                            st.registered_prefixes = [
                                dict(p) for p in
                                (d.get("registered_prefixes") or [])]

    # ---- reconcile loop ---------------------------------------------------
    def _control_loop(self) -> None:
        import ray_tpu
        while not self._shutdown.is_set():
            try:
                with self._lock:
                    keys = list(self._deployments.keys())
                for key in keys:
                    # metric collection blocks on replicas -> outside lock
                    self._collect_autoscale_metrics(ray_tpu, key)
                    # autoscale decisions do driver round trips
                    # (feasibility, pg reserve) -> phased locking inside
                    self._autoscale_step(key)
                    self._reconcile(ray_tpu, key)
                self._drain_pg_removals()
                # deployments deleted mid-drain: their STOPPING replicas
                # still need the drain poll until done/timeout
                with self._lock:
                    for st in list(self._stopping_states):
                        self._check_draining(ray_tpu, st)
                        if not any(r.state == "STOPPING"
                                   for r in st.replicas):
                            self._stopping_states.remove(st)
            except Exception:  # noqa: BLE001  control loop must survive
                import traceback
                traceback.print_exc()
            self._shutdown.wait(_LOOP_PERIOD_S)

    _MAX_START_FAILURES = 3

    def _reconcile(self, ray_tpu, key: str) -> None:
        with self._lock:
            # re-check under lock: the app may have been deleted between
            # the loop's snapshot and now (else we'd resurrect replicas
            # onto an orphaned state object).
            st = self._deployments.get(key)
            if st is None:
                return
            self._check_started(ray_tpu, st)
            self._probe_health(ray_tpu, st)
            self._check_draining(ray_tpu, st)
            running = [r for r in st.replicas if r.state == "RUNNING"]
            starting = [r for r in st.replicas if r.state == "STARTING"]
            # version rollout: replace at most one stale replica per tick,
            # only when we're at/above target so capacity never dips.
            stale = [r for r in running if r.version != st.version]
            if stale and len(running) + len(starting) >= st.target_num:
                self._stop_replica(ray_tpu, st, stale[0])
            live = [r for r in st.replicas
                    if r.state in ("RUNNING", "STARTING")]
            if len(live) >= st.target_num and st._pending_replacements:
                # no deficit: the unhealthy kill was absorbed (e.g. a
                # concurrent scale-down) and no replacement will start
                # — drop the pending link so a LATER unrelated start
                # (autoscale-up) isn't mislabeled serve.replica.replaced
                st._pending_replacements.clear()
            if len(live) < st.target_num:
                if st._start_failures < self._MAX_START_FAILURES:
                    for _ in range(st.target_num - len(live)):
                        self._start_replica(ray_tpu, st)
                # else: stay DEPLOY_FAILED until a redeploy resets backoff
            elif len(live) > st.target_num:
                # prefer stopping stale versions, then the replica with
                # the FEWEST in-flight requests (live autoscale sample)
                # — draining a busy replica while an idle peer survives
                # wastes the drain window and fails more streams over
                extras = sorted(
                    live, key=lambda r: (r.version == st.version,
                                         self._replica_load(r),
                                         r.replica_id))
                for r in extras[:len(live) - st.target_num]:
                    self._stop_replica(ray_tpu, st, r)
            current = [r for r in st.replicas if r.state == "RUNNING"]
            if (len(current) >= st.target_num
                    and all(r.version == st.version for r in current)):
                st.status = "HEALTHY"
            st.replicas = [r for r in st.replicas if r.state != "DEAD"]

    def _start_replica(self, ray_tpu, st: _DeploymentState) -> None:
        from .autoscaler import PlacementGroupRef
        from .replica import Replica
        rid = st.next_replica_id()
        opts = dict(st.config.ray_actor_options)
        opts.setdefault("max_concurrency", st.config.max_ongoing_requests + 8)
        pg_id = None
        if st._pending_pg_bundles:
            # consume one reserved bundle from the latest scale-up batch
            pg_id, bundle_index = st._pending_pg_bundles.pop(0)
            opts["placement_group"] = PlacementGroupRef(pg_id)
            opts["bundle_index"] = bundle_index
        handle = ray_tpu.remote(Replica).options(**opts).remote(
            st.name, rid, st.callable_bytes, st.init_args, st.init_kwargs,
            user_config=st.config.user_config,
            max_ongoing_requests=st.config.max_ongoing_requests)
        info = ReplicaInfo(replica_id=rid, deployment_name=st.name,
                           app_name=st.app_name, version=st.version,
                           actor_handle=handle, state="STARTING",
                           start_ref=handle.ready.remote(), pg_id=pg_id)
        if pg_id:
            self._pg_refs[pg_id] = self._pg_refs.get(pg_id, 0) + 1
        st.replicas.append(info)
        if st._pending_replacements:
            old = st._pending_replacements.pop(0)
            _emit_serve_event(
                "serve.replica.replaced",
                f"replacement {rid} started for {old}",
                actor_id=getattr(handle, "actor_id", None),
                deployment=st.name, app=st.app_name,
                replaces=old, replica_id=rid)

    def _check_started(self, ray_tpu, st: _DeploymentState) -> None:
        for r in st.replicas:
            if r.state != "STARTING":
                continue
            ready, _ = ray_tpu.wait([r.start_ref], timeout=0)
            if ready:
                try:
                    ray_tpu.get(r.start_ref)
                    r.state = "RUNNING"
                    st._start_failures = 0
                    # propagate registered prefixes: every replica that
                    # starts after a register_prefix() call pre-warms
                    # them, so affinity survives replacement/scale-up
                    for row in st.registered_prefixes:
                        try:
                            r.actor_handle.handle_request.remote(
                                "register_prefix", (dict(row),), {})
                        except Exception:  # noqa: BLE001  lazy re-warm
                            pass
                except Exception as e:  # noqa: BLE001  init failed
                    r.state = "DEAD"
                    st._start_failures += 1
                    st.status = "DEPLOY_FAILED"
                    st.message = repr(e)
                    # a failed init never reaches _kill_replica, so its
                    # pg reservation must be released here or it leaks
                    self._release_pg(r.pg_id)
                    r.pg_id = None

    def _stop_replica(self, ray_tpu, st: _DeploymentState,
                      r: ReplicaInfo, graceful: bool = True) -> None:
        """Graceful: flip the replica to STOPPING — it stops admitting
        (prepare_for_shutdown sets its draining flag; routing drops it
        because get_replicas only returns RUNNING) and the drain poll
        kills it once its ongoing count (streams included) hits zero or
        graceful_shutdown_timeout_s passes. Non-graceful (unhealthy /
        never-started): immediate kill."""
        if graceful and r.state == "RUNNING":
            r.state = "STOPPING"
            r.draining_since = time.time()
            try:
                r.drain_ref = r.actor_handle.prepare_for_shutdown.remote()
            except Exception:  # noqa: BLE001  already dead
                self._kill_replica(ray_tpu, r)
            return
        self._kill_replica(ray_tpu, r)

    def _kill_replica(self, ray_tpu, r: ReplicaInfo) -> None:
        r.state = "DEAD"
        try:
            ray_tpu.kill(r.actor_handle)
        except Exception:  # noqa: BLE001
            pass
        self._release_pg(r.pg_id)
        r.pg_id = None

    def _check_draining(self, ray_tpu, st: _DeploymentState) -> None:
        """Drive STOPPING replicas to DEAD: poll the ongoing-request
        count (never blocking) and kill at zero or at the graceful
        timeout. Lock held; wait(timeout=0) only."""
        now = time.time()
        for r in st.replicas:
            if r.state != "STOPPING":
                continue
            timed_out = (now - r.draining_since
                         > st.config.graceful_shutdown_timeout_s)
            done = False
            if r.drain_ref is not None:
                ready, _ = ray_tpu.wait([r.drain_ref], timeout=0)
                if ready:
                    ref, r.drain_ref = r.drain_ref, None
                    try:
                        done = ray_tpu.get(ref) <= 0
                    except Exception:  # noqa: BLE001  replica died
                        done = True
            elif not timed_out:
                try:
                    # prepare_for_shutdown doubles as the drain poll
                    # (idempotent; counts handlers + undrained streams,
                    # unlike the autoscaler's get_queue_len)
                    r.drain_ref = \
                        r.actor_handle.prepare_for_shutdown.remote()
                except Exception:  # noqa: BLE001  replica died
                    done = True
            if done or timed_out:
                self._kill_replica(ray_tpu, r)
                _emit_serve_event(
                    "serve.replica.drain",
                    f"drain {'timed out' if timed_out and not done else 'completed'}"
                    f" after {now - r.draining_since:.2f}s",
                    actor_id=getattr(r.actor_handle, "actor_id", None),
                    deployment=st.name, app=st.app_name,
                    replica_id=r.replica_id,
                    timed_out=bool(timed_out and not done))

    # ---- active health probes ---------------------------------------------
    def _probe_health(self, ray_tpu, st: _DeploymentState) -> None:
        """Periodically probe RUNNING replicas via their health_check
        actor method; RAY_TPU_SERVE_HEALTH_THRESHOLD consecutive
        failures (error, wedged cause, timeout, or actor death) mark
        the replica unhealthy: it is killed and the reconcile pass
        below starts a replacement. Lock held; never blocks (probe
        results are collected with wait(timeout=0))."""
        period = _env_float("RAY_TPU_SERVE_HEALTH_PERIOD_S",
                            st.config.health_check_period_s)
        if period <= 0:
            return
        timeout = _env_float("RAY_TPU_SERVE_HEALTH_TIMEOUT_S",
                             st.config.health_check_timeout_s)
        threshold = max(1, int(_env_float(
            "RAY_TPU_SERVE_HEALTH_THRESHOLD",
            st.config.health_check_failure_threshold)))
        now = time.time()
        for r in list(st.replicas):
            if r.state != "RUNNING":
                continue
            if r.health_ref is not None:
                ready, _ = ray_tpu.wait([r.health_ref], timeout=0)
                if ready:
                    ref, r.health_ref = r.health_ref, None
                    try:
                        ray_tpu.get(ref)
                        r.health_failures = 0
                    except Exception as e:  # noqa: BLE001
                        self._health_failure(ray_tpu, st, r, e, threshold)
                elif now - r.last_probe_ts > timeout:
                    r.health_ref = None
                    self._health_failure(
                        ray_tpu, st, r,
                        TimeoutError(f"health probe timed out after "
                                     f"{timeout}s"), threshold)
            if (r.state == "RUNNING" and r.health_ref is None
                    and now - r.last_probe_ts >= period):
                r.last_probe_ts = now
                try:
                    r.health_ref = r.actor_handle.health_check.remote()
                except Exception as e:  # noqa: BLE001
                    self._health_failure(ray_tpu, st, r, e, threshold)

    def _health_failure(self, ray_tpu, st: _DeploymentState,
                        r: ReplicaInfo, exc: BaseException,
                        threshold: int) -> None:
        from ..exceptions import ActorDiedError
        from ..util import events as events_mod
        r.health_failures += 1
        events_mod.emit_safe(
            counter="ray_tpu_serve_health_probe_failures_total",
            counter_tags={"deployment": st.name})
        # actor death is unambiguous — no flake to tolerate, escalate
        # on the first observation instead of waiting out the threshold
        if (r.health_failures < threshold
                and not isinstance(exc, ActorDiedError)):
            return
        cause = repr(exc)
        if "EngineWedgedError" in cause:
            cause = f"wedged: {cause}"
        _emit_serve_event(
            "serve.replica.unhealthy",
            f"{r.replica_id} failed {r.health_failures} consecutive "
            f"health probes: {cause[:300]}",
            actor_id=getattr(r.actor_handle, "actor_id", None),
            deployment=st.name, app=st.app_name,
            replica_id=r.replica_id, cause=cause[:300],
            failures=r.health_failures)
        st._pending_replacements.append(r.replica_id)
        self._kill_replica(ray_tpu, r)

    def _stop_deployment(self, key: str) -> None:
        import ray_tpu
        st = self._deployments.pop(key, None)
        if st is None:
            return
        # pg bundles reserved by a scale-up whose replicas never
        # started: nothing will consume them now — queue the empty pgs
        # for removal or their reserved capacity leaks forever
        if st._pending_pg_bundles:
            stale = {pg for pg, _i in st._pending_pg_bundles}
            st._pending_pg_bundles.clear()
            for pg in stale:
                if self._pg_refs.get(pg, 0) <= 0:
                    self._pg_refs.pop(pg, None)
                    self._pgs_to_remove.append(pg)
        for r in st.replicas:
            self._stop_replica(ray_tpu, st, r,
                               graceful=r.state == "RUNNING")
        if any(r.state == "STOPPING" for r in st.replicas):
            self._stopping_states.append(st)

    def _collect_autoscale_metrics(self, ray_tpu, key: str) -> None:
        """Harvest + re-dispatch per-replica autoscale metric probes,
        never blocking: outstanding refs are collected with
        wait(timeout=0) and a new probe is dispatched once the previous
        answered and the sampling period elapsed. Runs for EVERY
        deployment (least-busy scale-down victim selection wants a load
        sample) — only autoscaling ones keep the windowed history.

        Settling the probe refs happens OUTSIDE the controller lock:
        wait/get are worker->driver socket round trips even for a
        ready ref, and holding the lock across them stalls every
        handle's routing-table RPC whenever the dispatcher is busy —
        the PR 7 stall class this controller's _autoscale_step already
        phase-locks against (raylint RT001). Only the control loop
        settles probe refs, so the unlocked window cannot race another
        settler."""
        with self._lock:
            st = self._deployments.get(key)
            if st is None:
                return
            pending = [(r, r.metrics_ref) for r in st.replicas
                       if r.state == "RUNNING"
                       and r.metrics_ref is not None]
        settled: Dict[int, Optional[dict]] = {}
        for r, ref in pending:
            ready, _ = ray_tpu.wait([ref], timeout=0)
            if not ready:
                continue
            try:
                settled[id(r)] = ray_tpu.get(ref)
            except Exception:  # noqa: BLE001  dying replica
                settled[id(r)] = None
        with self._lock:
            st = self._deployments.get(key)
            if st is None:
                return
            ac = st.config.autoscaling_config
            now = time.time()
            period = (ac.metrics_interval_s if ac is not None
                      else _METRICS_PERIOD_S)
            total_ongoing = 0.0
            engine_agg: Dict[str, list] = {}
            have_sample = False
            for r in st.replicas:
                if r.state != "RUNNING":
                    continue
                if r.metrics_ref is not None and id(r) in settled:
                    r.metrics_ref = None
                    m = settled[id(r)]
                    if m is not None:
                        r.last_metrics = m
                if (r.metrics_ref is None
                        and now - r.metrics_dispatch_ts >= period):
                    r.metrics_dispatch_ts = now
                    try:
                        r.metrics_ref = \
                            r.actor_handle.get_autoscale_metrics.remote()
                    except Exception:  # noqa: BLE001  dying replica
                        pass
                m = r.last_metrics
                if m is None:
                    continue
                have_sample = True
                load = float(m.get("ongoing", 0)) + float(
                    m.get("streams", 0))
                eng = m.get("engine") or {}
                load += float(eng.get("queue_depth", 0) or 0)
                total_ongoing += load
                for k in ("queue_depth", "kv_util", "ttft_p50_ms",
                          "tpot_ms"):
                    v = eng.get(k)
                    if v is not None:
                        engine_agg.setdefault(k, []).append(float(v))
            if ac is None or not have_sample:
                return
            st._ongoing_history.append((now, total_ongoing))
            cutoff = now - ac.look_back_period_s
            st._ongoing_history = [(t, v) for t, v in st._ongoing_history
                                   if t >= cutoff]
            # engine SLO signals: queue depth sums across replicas, the
            # latency/utilization signals take the worst replica
            st._last_metrics = {
                "queue_depth": sum(engine_agg.get("queue_depth", [])),
            }
            for k in ("kv_util", "ttft_p50_ms", "tpot_ms"):
                if engine_agg.get(k):
                    st._last_metrics[k] = max(engine_agg[k])

    @staticmethod
    def _replica_load(r: ReplicaInfo) -> float:
        m = r.last_metrics or {}
        return (float(m.get("ongoing", 0)) + float(m.get("streams", 0)))

    def _autoscale_step(self, key: str) -> None:
        """Feed the metric window into the deployment's autoscaler
        policy (serve/autoscaler.py -> core/autoscaler.py) and apply
        the returned target: scale-up reserves placement-group bundles
        when configured, scale-down lets _reconcile drain the
        least-busy replicas.

        Three phases so the controller lock is NEVER held across a
        driver round trip (feasibility view, pg create — each a
        report_sync with a seconds-scale timeout; pinning the lock
        would stall every handle's routing-table RPC during the exact
        load spike that triggered the scale-up): decide under the
        lock, do driver I/O unlocked, re-validate and apply under the
        lock."""
        # ---- phase 1 (lock): decide ----
        with self._lock:
            st = self._deployments.get(key)
            if st is None:
                return
            ac = st.config.autoscaling_config
            if ac is None or not st._ongoing_history:
                return
            running = [r for r in st.replicas if r.state == "RUNNING"]
            if not running:
                return
            now = time.time()
            avg = (sum(v for _, v in st._ongoing_history)
                   / max(len(st._ongoing_history), 1))
            policy = self._autoscaler.policy_for(key, ac)
            busy = {r.replica_id: self._replica_load(r) for r in running}
            target, reason = policy.decide(
                now, st.target_num, avg, engine=st._last_metrics,
                per_replica_busy=busy)
            try:
                from ..util import metrics_catalog as mcat
                mcat.get("ray_tpu_serve_autoscaler_target_replicas").set(
                    float(target), tags={"deployment": st.name})
            except Exception:  # noqa: BLE001
                pass
            if target == st.target_num:
                return
            old_target = st.target_num
            direction = ("scale_up" if target > old_target
                         else "scale_down")
            if direction == "scale_down" and st._pending_pg_bundles:
                # bundles reserved by a scale-up that never started its
                # replicas: drop them so a LATER unrelated start isn't
                # pinned to a stale reservation; empty pgs queue for
                # removal (drained outside the lock)
                stale = {pg for pg, _i in st._pending_pg_bundles}
                st._pending_pg_bundles.clear()
                for pg in stale:
                    if self._pg_refs.get(pg, 0) <= 0:
                        self._pg_refs.pop(pg, None)
                        self._pgs_to_remove.append(pg)
            resources = dict(
                st.config.ray_actor_options.get("resources") or {})
            resources.setdefault(
                "CPU",
                st.config.ray_actor_options.get("num_cpus", 1) or 1)
            pg_strategy = st.config.placement_group_strategy
            dep_name, app_name = st.name, st.app_name

        # ---- phase 2 (no lock): driver round trips ----
        feasible = None
        pg = None
        if direction == "scale_up":
            from .autoscaler import create_placement_group
            deficit = target - old_target
            feasible = self._autoscaler.feasible_now(resources, deficit)
            if pg_strategy:
                pg = create_placement_group(
                    [dict(resources) for _ in range(deficit)],
                    strategy=pg_strategy,
                    name=f"serve-{app_name}-{dep_name}-{int(time.time())}")

        # ---- phase 3 (lock): re-validate and apply ----
        aborted = False
        with self._lock:
            st = self._deployments.get(key)
            if st is None or st.target_num != old_target:
                # deleted or retargeted (redeploy) while unlocked:
                # drop this decision; an unconsumed reservation frees
                aborted = True
                if pg is not None:
                    self._pgs_to_remove.append(pg.pg_id)
            else:
                if pg is not None:
                    self._pg_refs.setdefault(pg.pg_id, 0)
                    st._pending_pg_bundles.extend(
                        (pg.pg_id, i) for i in range(deficit))
                self._autoscaler.record(
                    key=key, deployment=dep_name, app=app_name,
                    direction=direction, from_num=old_target,
                    to_num=target, reason=reason, feasible=feasible,
                    pg_id=pg.pg_id if pg is not None else None)
                st.target_num = target
                st._last_scale_ts = now
        if not aborted:
            _emit_serve_event(
                f"serve.autoscaler.{direction}",
                f"{key}: {old_target} -> {target} ({reason})",
                counter="ray_tpu_serve_autoscaler_scale_events_total",
                counter_tags={"deployment": dep_name,
                              "direction": direction},
                deployment=dep_name, app=app_name,
                from_replicas=old_target, to_replicas=target,
                reason=reason[:200], feasible_now=feasible,
                placement_group=pg.pg_id if pg is not None else None)

    def _release_pg(self, pg_id: Optional[str]) -> None:
        """Drop one replica's claim; the last claim queues the pg for
        removal. Lock-safe: the actual driver RPC happens when the
        control loop drains _pgs_to_remove outside the lock."""
        if not pg_id:
            return
        n = self._pg_refs.get(pg_id)
        if n is None:
            return
        n -= 1
        if n <= 0:
            self._pg_refs.pop(pg_id, None)
            self._pgs_to_remove.append(pg_id)
        else:
            self._pg_refs[pg_id] = n

    def _drain_pg_removals(self) -> None:
        """Remove released placement groups; control loop, no lock."""
        from .autoscaler import remove_placement_group
        while True:
            with self._lock:
                if not self._pgs_to_remove:
                    return
                pg_id = self._pgs_to_remove.pop(0)
            remove_placement_group(pg_id)
