"""Shared ingress-proxy plumbing (HTTP + gRPC proxies).

One implementation of the controller-polling route refresh and the
get-or-create-named-actor pattern, so fixes (backoff, handle reuse)
land in both proxies at once.
"""
from __future__ import annotations

import time
from typing import Callable, Dict

from .handle import DeploymentHandle


def refresh_routes_forever(fetch: Callable, apply: Callable,
                           period_s: float = 0.5) -> None:
    """Poll the controller forever. fetch(ctrl) returns an ObjectRef of
    the raw route table; apply(raw) runs ONLY when the table changed —
    steady state does no handle rebuilding (each DeploymentHandle keeps
    its replica cache + load-tracker state between refreshes)."""
    import ray_tpu
    from .controller import CONTROLLER_NAME
    last = None
    while True:
        try:
            ctrl = ray_tpu.get_actor(CONTROLLER_NAME)
            raw = ray_tpu.get(fetch(ctrl))
            if raw != last:
                apply(raw)
                last = raw
        except Exception:  # noqa: BLE001  controller not up yet
            pass
        time.sleep(period_s)


def rebuild_handles(old: Dict[str, DeploymentHandle],
                    wanted: Dict[str, tuple]
                    ) -> Dict[str, DeploymentHandle]:
    """wanted: key -> (app_name, deployment_name[, extra...]). Reuses
    existing handles whose target is unchanged; builds fresh ones only
    for added/retargeted keys."""
    new = {}
    for key, target in wanted.items():
        app, dep = target[0], target[1]
        cur = old.get(key)
        if (cur is not None and cur._deployment == dep
                and cur._app == app):
            new[key] = cur
        else:
            new[key] = DeploymentHandle(dep, app)
    return new


def get_or_create_proxy(name: str, cls, host: str, port: int,
                        max_concurrency: int = 8):
    """Fetch the named proxy actor or create it; returns
    (handle, bound_port)."""
    import ray_tpu
    try:
        proxy = ray_tpu.get_actor(name)
    except Exception:  # noqa: BLE001
        proxy = ray_tpu.remote(cls).options(
            name=name, max_concurrency=max_concurrency).remote(host, port)
    return proxy, ray_tpu.get(proxy.ready.remote())


__all__ = ["refresh_routes_forever", "rebuild_handles",
           "get_or_create_proxy"]
