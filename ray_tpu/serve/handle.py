"""DeploymentHandle: the client-side request path.

Reference parity: python/ray/serve/handle.py (DeploymentHandle,
DeploymentResponse) + _private/replica_scheduler/pow_2_scheduler.py.
Routing is client-side power-of-two-choices over in-flight counts the
handle tracks locally, with the replica set refreshed from the controller.
"""
from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional

from ..exceptions import (ActorDiedError, ActorUnavailableError,
                          EngineWedgedError, NoCapacityError, RayTpuError,
                          ReplicaDrainingError, StreamInterruptedError,
                          TaskError, error_cause_is)
from .router import (AffinityRouter, extract_affinity_key,
                     pick_least_loaded)

_REPLICA_REFRESH_S = 1.0
# a replica that just failed a request is skipped by routing for this
# long (the controller usually replaces it well within the window)
_SUSPECT_TTL_S = 10.0

# Replica-side raises cross the actor boundary wrapped in TaskError
# (repr string, original type lost) — match retriable causes by name.
_RETRIABLE_CAUSE_NAMES = ("EngineWedgedError", "ReplicaDrainingError",
                          "ActorDiedError", "ActorUnavailableError")


def _retriable_failure(exc: BaseException) -> bool:
    """True when resubmitting to a DIFFERENT replica can succeed: the
    serving replica died, its engine wedged, or it started draining."""
    if isinstance(exc, (ActorDiedError, ActorUnavailableError,
                        EngineWedgedError, ReplicaDrainingError)):
        return True
    return isinstance(exc, TaskError) and error_cause_is(
        exc, *_RETRIABLE_CAUSE_NAMES)


def _note_failover(kind: str, deployment: str, replica_id: str,
                   exc: BaseException) -> None:
    """serve.request.failover event + counter; never fails the retry."""
    from ..util import events as events_mod
    events_mod.emit_safe("serve.request.failover",
                         f"resubmitting after {type(exc).__name__} "
                         f"on {replica_id}",
                         counter="ray_tpu_serve_failovers_total",
                         counter_tags={"kind": kind},
                         deployment=deployment, replica_id=replica_id,
                         cause=repr(exc)[:200], kind=kind)


class BackPressureError(RayTpuError):
    """Raised when max_queued_requests would be exceeded."""


class DeploymentResponse:
    """Future for one request. `.result()` blocks; awaitable in async code;
    passable to another `.remote()` call (resolves to the ObjectRef).

    If the serving replica died (e.g. killed during a rolling update), the
    request is transparently re-routed to a live replica, up to
    `max_retries` times (reference: serve retries replica-death failures
    at the router).
    """

    def __init__(self, ref, on_done=None, resubmit=None, max_retries=3):
        self._ref = ref
        self._on_done = on_done
        self._resubmit = resubmit
        self._max_retries = max_retries
        self._done = False

    def _settle(self):
        if not self._done:
            self._done = True
            if self._on_done is not None:
                self._on_done()

    def result(self, timeout_s: Optional[float] = None) -> Any:
        import ray_tpu
        deadline = (None if timeout_s is None
                    else time.time() + timeout_s)
        try:
            return ray_tpu.get(self._ref, timeout=timeout_s)
        except Exception as e:  # noqa: BLE001  typed check below
            if (self._resubmit is None or self._max_retries <= 0
                    or not _retriable_failure(e)):
                raise
            # retries share the ORIGINAL wait budget — restarting
            # timeout_s per attempt would stretch the caller's bound
            # to retries x budget. The deadline also rides into the
            # resubmit so the retry's replica-pick wait is bounded too.
            retry = self._resubmit(e, deadline_override=deadline)
            retry._max_retries = self._max_retries - 1
            self._ref = retry._ref
            return retry.result(timeout_s=(
                None if deadline is None
                else max(0.1, deadline - time.time())))
        finally:
            self._settle()

    # Bound on the SYNCHRONOUS replica-pick wait a failover retry may
    # spend inside __await__: the pick loop's sleeps run on the event
    # loop thread (this runtime's handle is poll-based), so an open-
    # ended 30s wait would freeze every other coroutine and defeat
    # asyncio.wait_for. Requests that carry a propagated deadline are
    # bounded by it instead.
    _AWAIT_RETRY_PICK_BUDGET_S = 5.0

    def __await__(self):
        # same failover contract as result(): async callers get the
        # transparent re-route too
        while True:
            try:
                v = yield from self._ref.__await__()
                self._settle()
                return v
            except Exception as e:  # noqa: PERF203  typed check below
                if (self._resubmit is None or self._max_retries <= 0
                        or not _retriable_failure(e)):
                    self._settle()
                    raise
                retry = self._resubmit(
                    e, deadline_override=(
                        time.time() + self._AWAIT_RETRY_PICK_BUDGET_S))
                self._max_retries -= 1
                self._ref = retry._ref
                # adopt the retry's resubmit closure (it captured the
                # NEW replica id) — keeping ours would suspect the
                # ORIGINAL replica again on a second failover, same as
                # the stream-adoption fix
                self._resubmit = retry._resubmit

    @property
    def object_ref(self):
        return self._ref

    def _to_object_ref(self):
        return self._ref


class DeploymentResponseGenerator:
    """Streaming response: iterate to pull chunks from the replica.

    Failover contract: if the serving replica dies/wedges/drains BEFORE
    this consumer has received any chunk, the stream is transparently
    resubmitted to a healthy replica (up to `max_retries` times). Once
    a chunk has been received, resubmission would replay delivered
    tokens, so the failure surfaces as the typed, retriable
    StreamInterruptedError instead.
    """

    def __init__(self, replica_handle, stream_id_ref, on_done=None,
                 resubmit=None, max_retries=3):
        self._replica = replica_handle
        self._stream_id_ref = stream_id_ref
        self._stream_id = None
        self._buffer: List[Any] = []
        self._finished = False
        self._on_done = on_done
        self._resubmit = resubmit
        self._max_retries = max_retries
        self._got_first = False   # any chunk received from the replica

    def __iter__(self):
        return self

    def _pull(self):
        import ray_tpu
        if self._stream_id is None:
            self._stream_id = ray_tpu.get(self._stream_id_ref)
        while not self._buffer:
            chunks, done = ray_tpu.get(
                self._replica.stream_next.remote(self._stream_id))
            self._buffer.extend(chunks)
            if chunks:
                self._got_first = True
            if done:
                self._finished = True
                if self._on_done is not None:
                    self._on_done()
                break

    def __next__(self):
        if self._buffer:
            return self._buffer.pop(0)
        if self._finished:
            raise StopIteration
        try:
            self._pull()
        except Exception as e:  # noqa: BLE001  typed check below
            if (self._resubmit is None or self._max_retries <= 0
                    or not _retriable_failure(e)):
                raise
            if self._got_first:
                # post-first-token: surface a typed retriable error —
                # the caller decides whether replaying is acceptable
                self._finished = True
                if self._on_done is not None:
                    self._on_done()
                raise StreamInterruptedError(
                    f"stream lost its replica after first token: "
                    f"{e!r}", cause_repr=repr(e)) from e
            fresh = self._resubmit(e)
            # release the dead replica's in-flight count, then adopt
            # the fresh generator's replica/stream/accounting wholesale
            # — INCLUDING its resubmit closure, which captured the NEW
            # replica id (keeping ours would suspect the original
            # replica again on a second failover and leave the one
            # that just died routable)
            if self._on_done is not None:
                self._on_done()
            self._replica = fresh._replica
            self._stream_id_ref = fresh._stream_id_ref
            self._stream_id = None
            self._on_done = fresh._on_done
            self._resubmit = fresh._resubmit
            self._max_retries -= 1
            return next(self)
        if self._buffer:
            return self._buffer.pop(0)
        raise StopIteration

    def close(self):
        """Abandoned stream (client cancelled before draining): release
        the router's manual in-flight count AND cancel the replica-side
        drain task — otherwise the replica keeps pumping until its
        bounded buffer fills, parks forever, and its _ongoing count
        stays elevated (hanging graceful shutdown). Idempotent; a
        fully-drained stream already fired on_done."""
        if self._finished:
            return
        self._finished = True
        try:
            import ray_tpu
            if self._stream_id is None:
                self._stream_id = ray_tpu.get(self._stream_id_ref)
            self._replica.stream_cancel.remote(self._stream_id)
        except Exception:  # noqa: BLE001  replica already gone
            pass
        if self._on_done is not None:
            self._on_done()

    def __aiter__(self):
        return self

    async def __anext__(self):
        try:
            return next(self)
        except StopIteration:
            raise StopAsyncIteration from None


class _RouterState:
    """Shared per-(app, deployment) routing state.

    In-flight accounting is by pending ObjectRef: a request stops counting
    against its replica the moment the replica finishes it (pruned via
    wait(timeout=0)), NOT when the caller reads the result — so issuing
    many .remote() calls before consuming any cannot deadlock routing.
    Streams (no single completion ref) use a manual count released when
    the generator finishes.
    """

    def __init__(self, deployment: str = "", app: str = "default"):
        self.replicas: List[tuple] = []  # (replica_id, actor_handle)
        self.pending: Dict[str, list] = {}   # replica_id -> [ObjectRef]
        self.manual: Dict[str, int] = {}     # replica_id -> stream count
        self.suspects: Dict[str, float] = {}  # replica_id -> marked ts
        self.last_refresh = 0.0
        self.lock = threading.Lock()
        self.max_ongoing = 5
        self.max_queued = -1
        self.queued = 0
        # scale-out router state (serve/router.py): sticky
        # session/prefix bindings + the deployment's registered
        # prefixes (refreshed from the controller with the replica set)
        self.affinity = AffinityRouter(deployment, app)
        self.registered_prefixes: List[dict] = []
        self.last_prune = 0.0

    def mark_suspect(self, replica_id: str) -> None:
        """A request just failed on this replica (death/wedge/drain):
        skip it in routing for _SUSPECT_TTL_S and drop its in-flight
        accounting so p2c doesn't keep favoring/avoiding a ghost."""
        with self.lock:
            self.suspects[replica_id] = time.time()
            self.pending.pop(replica_id, None)
            self.manual.pop(replica_id, None)
            # affinity keys bound to the dead replica re-bind (and
            # re-warm) on their next request instead of chasing a ghost
            self.affinity.forget(replica_id)

    def live_candidates(self) -> List[tuple]:
        """Routing candidates minus recently-failed replicas. Caller
        must hold lock. When EVERY replica is suspect the result is
        empty and the pick loop keeps waiting — the controller is
        usually seconds from delivering a replacement, and routing
        straight back to the replica that just failed (the old
        _resubmit bug) only burns the retry budget. Suspicion expires
        after _SUSPECT_TTL_S in case the controller disagrees."""
        if not self.suspects:          # hot path: nothing ever failed
            return self.replicas
        now = time.time()
        for rid in [rid for rid, ts in self.suspects.items()
                    if now - ts > _SUSPECT_TTL_S]:
            del self.suspects[rid]
        return [c for c in self.replicas if c[0] not in self.suspects]

    _PRUNE_INTERVAL_S = 0.02

    def prune(self, force: bool = False):
        """Drop refs whose tasks completed. Caller must NOT hold lock.

        Throttled: the wait(timeout=0) completion scan is a runtime
        round trip, and paying it on EVERY request put ~20% on the
        router's happy path. Between scans the in-flight counts can
        only over-estimate (finished-but-unpruned refs), which at worst
        biases p2c — correctness never depends on them. Saturation
        paths pass force=True so a full replica never looks full a
        moment longer than real."""
        import ray_tpu
        now = time.time()
        # unlocked pre-check: a stale read just delays one scan by an
        # interval; the locked re-check below keeps the scan single
        if not force and now - self.last_prune < self._PRUNE_INTERVAL_S:
            return
        with self.lock:
            if not force and now - self.last_prune < \
                    self._PRUNE_INTERVAL_S:
                return
            self.last_prune = now
            all_refs = [ref for refs in self.pending.values()
                        for ref in refs]
        if not all_refs:
            return
        ready, _ = ray_tpu.wait(all_refs, num_returns=len(all_refs),
                                timeout=0)
        done = {r.id for r in ready}
        with self.lock:
            for rid in self.pending:
                self.pending[rid] = [r for r in self.pending[rid]
                                     if r.id not in done]

    def load(self, replica_id: str) -> int:
        return (len(self.pending.get(replica_id, ()))
                + self.manual.get(replica_id, 0))


class DeploymentHandle:
    """Serializable handle for calling a deployment from anywhere."""

    def __init__(self, deployment_name: str, app_name: str = "default",
                 method_name: str = "__call__", stream: bool = False,
                 multiplexed_model_id: str = "",
                 deadline_s: Optional[float] = None):
        self._deployment = deployment_name
        self._app = app_name
        self._method = method_name
        self._stream = stream
        self._multiplexed_model_id = multiplexed_model_id
        self._deadline_s = deadline_s
        self._router = _RouterState(deployment_name, app_name)

    def __reduce__(self):
        return (DeploymentHandle,
                (self._deployment, self._app, self._method, self._stream,
                 self._multiplexed_model_id, self._deadline_s))

    def options(self, *, method_name: Optional[str] = None,
                stream: Optional[bool] = None,
                multiplexed_model_id: Optional[str] = None,
                deadline_s: Optional[float] = None,
                ) -> "DeploymentHandle":
        h = DeploymentHandle(
            self._deployment, self._app,
            method_name if method_name is not None else self._method,
            stream if stream is not None else self._stream,
            multiplexed_model_id if multiplexed_model_id is not None
            else self._multiplexed_model_id,
            deadline_s if deadline_s is not None else self._deadline_s)
        h._router = self._router  # share in-flight accounting
        return h

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return _BoundMethod(self, name)

    # ---- routing ----------------------------------------------------------
    def _controller(self):
        import ray_tpu
        return ray_tpu.get_actor("_SERVE_CONTROLLER")

    def _refresh_replicas(self, force: bool = False):
        import ray_tpu
        r = self._router
        now = time.time()
        if not force and r.replicas and \
                now - r.last_refresh < _REPLICA_REFRESH_S:
            return
        ctrl = self._controller()
        replicas = ray_tpu.get(
            ctrl.get_replicas.remote(self._app, self._deployment))
        info = ray_tpu.get(
            ctrl.get_deployment_info.remote(self._app, self._deployment))
        with r.lock:
            r.replicas = replicas
            r.last_refresh = now
            if info:
                r.max_ongoing = info["max_ongoing_requests"]
                r.max_queued = info["max_queued_requests"]
                r.registered_prefixes = list(
                    info.get("registered_prefixes") or [])

    def _pick_replica(self, deadline_ts: Optional[float] = None,
                      affinity_key: Optional[str] = None):
        """Least-loaded power-of-two-choices over live (non-suspect)
        replicas that still have request slots (serve/router.py);
        requests carrying an affinity key are sticky-routed first
        (consistent hash with bounded load) and only fall back to p2c
        when every preferred replica is over the load bound. Waits with
        exponential backoff + jitter (not a hot loop) when every
        replica is at max_ongoing_requests. The wait is bounded by the
        request's propagated deadline when one is set, else 30s;
        exhaustion raises the typed NoCapacityError the proxy maps to
        503."""
        r = self._router
        start = time.time()
        budget = (30.0 if deadline_ts is None
                  else max(0.0, deadline_ts - start))
        sleep_s = 0.002
        first_pass = True
        while True:
            self._refresh_replicas(force=not r.replicas)
            # retries after a full pass must see completions instantly
            # (a saturated replica may have just freed a slot)
            r.prune(force=not first_pass)
            first_pass = False
            with r.lock:
                candidates = r.live_candidates()
                total = len(r.replicas)
                if candidates:
                    if affinity_key is not None:
                        chosen = r.affinity.pick(
                            affinity_key, candidates, r.load,
                            r.max_ongoing)
                        if chosen is not None:
                            return chosen
                    chosen = pick_least_loaded(candidates, r.load,
                                               r.max_ongoing)
                    if chosen is not None:
                        return chosen
            if time.time() - start > budget:
                # name the REAL cause: "saturated" vs "all replicas just
                # failed" point an operator at opposite remediations
                if total == 0:
                    why = "no replicas in the routing table"
                elif not candidates:
                    why = (f"all {total} replicas recently failed "
                           "(suspect-listed) and no replacement became "
                           "available in time")
                else:
                    why = (f"every replica at max_ongoing_requests="
                           f"{r.max_ongoing}")
                raise NoCapacityError(
                    f"no capacity on {self._deployment} after "
                    f"{budget:.1f}s: {why}")
            # backoff with jitter: spinning at a fixed 20ms hammered the
            # router lock and the refresh path under saturation
            time.sleep(sleep_s * (0.5 + random.random()))
            sleep_s = min(sleep_s * 2, 0.05)

    def _flush_binding_notes(self) -> None:
        """Deliver queued binding transitions to the controller's
        router table (state API / dashboard surface). Fire-and-forget,
        best-effort, and ALWAYS outside the router lock — resolving the
        controller is a driver round trip from proxy processes."""
        r = self._router
        with r.lock:
            notes = r.affinity.take_notes()
        if not notes:
            return
        try:
            ctrl = self._controller()
            for key, replica_id, outcome in notes:
                ctrl.note_session_binding.remote(
                    self._app, self._deployment, key, replica_id,
                    outcome)
        except Exception:  # noqa: BLE001
            pass

    def remote(self, *args, **kwargs):
        r = self._router
        # absolute deadline: explicit kwarg (proxy-stamped; retries keep
        # the ORIGINAL deadline) or this handle's relative deadline_s
        deadline_ts = kwargs.get("__serve_deadline_ts")
        if deadline_ts is None and self._deadline_s is not None:
            deadline_ts = time.time() + self._deadline_s
            kwargs["__serve_deadline_ts"] = deadline_ts
        # affinity key: explicit kwarg (proxy session header / caller),
        # else a session id or registered-prefix match in a dict body.
        # Popped here — replicas never see the routing hint.
        affinity_key = kwargs.pop("__serve_affinity_key", None)
        if affinity_key is None:
            if not r.replicas:
                # cold handle: fetch the routing table (and with it the
                # registered-prefix list) BEFORE key extraction, so the
                # very first prefix-keyed request routes warm
                try:
                    self._refresh_replicas(force=True)
                except Exception:  # noqa: BLE001  pick loop will retry
                    pass
            affinity_key = extract_affinity_key(
                args, r.registered_prefixes)
        if affinity_key is not None:
            affinity_key = str(affinity_key)
        # the queued counter only backs max_queued_requests enforcement;
        # with the unbounded default (-1) skip both lock rounds
        track_queue = r.max_queued >= 0
        if track_queue:
            with r.lock:
                if r.queued >= r.max_queued:
                    raise BackPressureError(
                        f"{self._deployment}: max_queued_requests "
                        f"({r.max_queued}) exceeded")
                r.queued += 1
        try:
            replica_id, handle = self._pick_replica(deadline_ts,
                                                    affinity_key)
        finally:
            if track_queue:
                with r.lock:
                    r.queued -= 1
        if affinity_key is not None:
            self._flush_binding_notes()
        args = tuple(a._to_object_ref() if isinstance(a, DeploymentResponse)
                     else a for a in args)
        if self._multiplexed_model_id:
            kwargs["__serve_multiplexed_model_id"] = \
                self._multiplexed_model_id

        def resubmit(exc, kind, a=args, kw=dict(kwargs),
                     failed=replica_id, deadline_override=None):
            # the fix for routing straight back to the dead replica:
            # suspect-list it AND force the routing table to re-resolve
            # from the controller before the retry picks a target
            r.mark_suspect(failed)
            r.last_refresh = 0.0
            _note_failover(kind, self._deployment, failed, exc)
            if affinity_key is not None:
                # keep the session key on the retry: the failed replica
                # is suspect, so the key re-binds to a live one instead
                # of degrading to keyless routing
                kw = {**kw, "__serve_affinity_key": affinity_key}
            if (deadline_override is not None
                    and "__serve_deadline_ts" not in kw):
                # a deadline-less request retried from result(timeout_s=)
                # inherits the caller's remaining budget, so the retry's
                # replica-pick wait can't exceed the original bound
                kw = {**kw, "__serve_deadline_ts": deadline_override}
            return self.remote(*a, **kw)

        if self._stream:
            with r.lock:
                r.manual[replica_id] = r.manual.get(replica_id, 0) + 1

            def done():
                with r.lock:
                    # decrement only while the key exists: after
                    # mark_suspect popped a dead replica's count, a
                    # late done() must not resurrect a ghost entry
                    if replica_id in r.manual:
                        r.manual[replica_id] = max(
                            0, r.manual[replica_id] - 1)
            sid_ref = handle.stream_start.remote(self._method, args, kwargs)
            return DeploymentResponseGenerator(
                handle, sid_ref, on_done=done,
                resubmit=lambda exc: resubmit(exc, "stream"))
        ref = handle.handle_request.remote(self._method, args, kwargs)
        with r.lock:
            r.pending.setdefault(replica_id, []).append(ref)

        def unary_done(ref=ref, rid=replica_id):
            # consuming the response releases its in-flight count right
            # away — the prune() completion scan (a runtime round trip)
            # is then only a backstop for responses nobody reads
            with r.lock:
                refs = r.pending.get(rid)
                if refs is not None:
                    try:
                        refs.remove(ref)
                    except ValueError:
                        pass    # prune() already dropped it
        return DeploymentResponse(
            ref, on_done=unary_done,
            resubmit=lambda exc, deadline_override=None: resubmit(
                exc, "unary", deadline_override=deadline_override))


class _BoundMethod:
    def __init__(self, handle: DeploymentHandle, method_name: str):
        self._handle = handle
        self._method = method_name

    def remote(self, *args, **kwargs):
        return self._handle.options(method_name=self._method).remote(
            *args, **kwargs)
