"""DeploymentHandle: the client-side request path.

Reference parity: python/ray/serve/handle.py (DeploymentHandle,
DeploymentResponse) + _private/replica_scheduler/pow_2_scheduler.py.
Routing is client-side power-of-two-choices over in-flight counts the
handle tracks locally, with the replica set refreshed from the controller.
"""
from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional

from ..exceptions import RayTpuError

_REPLICA_REFRESH_S = 1.0


class BackPressureError(RayTpuError):
    """Raised when max_queued_requests would be exceeded."""


class DeploymentResponse:
    """Future for one request. `.result()` blocks; awaitable in async code;
    passable to another `.remote()` call (resolves to the ObjectRef).

    If the serving replica died (e.g. killed during a rolling update), the
    request is transparently re-routed to a live replica, up to
    `max_retries` times (reference: serve retries replica-death failures
    at the router).
    """

    def __init__(self, ref, on_done=None, resubmit=None, max_retries=3):
        self._ref = ref
        self._on_done = on_done
        self._resubmit = resubmit
        self._max_retries = max_retries
        self._done = False

    def _settle(self):
        if not self._done:
            self._done = True
            if self._on_done is not None:
                self._on_done()

    def result(self, timeout_s: Optional[float] = None) -> Any:
        import ray_tpu
        from ..exceptions import ActorDiedError
        try:
            return ray_tpu.get(self._ref, timeout=timeout_s)
        except ActorDiedError:
            if self._resubmit is None or self._max_retries <= 0:
                raise
            retry = self._resubmit()
            retry._max_retries = self._max_retries - 1
            self._ref = retry._ref
            return retry.result(timeout_s=timeout_s)
        finally:
            self._settle()

    def __await__(self):
        def _done(v):
            self._settle()
            return v
        return (yield from self._ref.__await__())

    @property
    def object_ref(self):
        return self._ref

    def _to_object_ref(self):
        return self._ref


class DeploymentResponseGenerator:
    """Streaming response: iterate to pull chunks from the replica."""

    def __init__(self, replica_handle, stream_id_ref, on_done=None):
        self._replica = replica_handle
        self._stream_id_ref = stream_id_ref
        self._stream_id = None
        self._buffer: List[Any] = []
        self._finished = False
        self._on_done = on_done

    def __iter__(self):
        return self

    def __next__(self):
        import ray_tpu
        if self._buffer:
            return self._buffer.pop(0)
        if self._finished:
            raise StopIteration
        if self._stream_id is None:
            self._stream_id = ray_tpu.get(self._stream_id_ref)
        while not self._buffer:
            chunks, done = ray_tpu.get(
                self._replica.stream_next.remote(self._stream_id))
            self._buffer.extend(chunks)
            if done:
                self._finished = True
                if self._on_done is not None:
                    self._on_done()
                break
        if self._buffer:
            return self._buffer.pop(0)
        raise StopIteration

    def close(self):
        """Abandoned stream (client cancelled before draining): release
        the router's manual in-flight count AND cancel the replica-side
        drain task — otherwise the replica keeps pumping until its
        bounded buffer fills, parks forever, and its _ongoing count
        stays elevated (hanging graceful shutdown). Idempotent; a
        fully-drained stream already fired on_done."""
        if self._finished:
            return
        self._finished = True
        try:
            import ray_tpu
            if self._stream_id is None:
                self._stream_id = ray_tpu.get(self._stream_id_ref)
            self._replica.stream_cancel.remote(self._stream_id)
        except Exception:  # noqa: BLE001  replica already gone
            pass
        if self._on_done is not None:
            self._on_done()

    def __aiter__(self):
        return self

    async def __anext__(self):
        try:
            return next(self)
        except StopIteration:
            raise StopAsyncIteration from None


class _RouterState:
    """Shared per-(app, deployment) routing state.

    In-flight accounting is by pending ObjectRef: a request stops counting
    against its replica the moment the replica finishes it (pruned via
    wait(timeout=0)), NOT when the caller reads the result — so issuing
    many .remote() calls before consuming any cannot deadlock routing.
    Streams (no single completion ref) use a manual count released when
    the generator finishes.
    """

    def __init__(self):
        self.replicas: List[tuple] = []  # (replica_id, actor_handle)
        self.pending: Dict[str, list] = {}   # replica_id -> [ObjectRef]
        self.manual: Dict[str, int] = {}     # replica_id -> stream count
        self.last_refresh = 0.0
        self.lock = threading.Lock()
        self.max_ongoing = 5
        self.max_queued = -1
        self.queued = 0

    def prune(self):
        """Drop refs whose tasks completed. Caller must NOT hold lock."""
        import ray_tpu
        with self.lock:
            all_refs = [ref for refs in self.pending.values()
                        for ref in refs]
        if not all_refs:
            return
        ready, _ = ray_tpu.wait(all_refs, num_returns=len(all_refs),
                                timeout=0)
        done = {r.id for r in ready}
        with self.lock:
            for rid in self.pending:
                self.pending[rid] = [r for r in self.pending[rid]
                                     if r.id not in done]

    def load(self, replica_id: str) -> int:
        return (len(self.pending.get(replica_id, ()))
                + self.manual.get(replica_id, 0))


class DeploymentHandle:
    """Serializable handle for calling a deployment from anywhere."""

    def __init__(self, deployment_name: str, app_name: str = "default",
                 method_name: str = "__call__", stream: bool = False,
                 multiplexed_model_id: str = ""):
        self._deployment = deployment_name
        self._app = app_name
        self._method = method_name
        self._stream = stream
        self._multiplexed_model_id = multiplexed_model_id
        self._router = _RouterState()

    def __reduce__(self):
        return (DeploymentHandle,
                (self._deployment, self._app, self._method, self._stream,
                 self._multiplexed_model_id))

    def options(self, *, method_name: Optional[str] = None,
                stream: Optional[bool] = None,
                multiplexed_model_id: Optional[str] = None,
                ) -> "DeploymentHandle":
        h = DeploymentHandle(
            self._deployment, self._app,
            method_name if method_name is not None else self._method,
            stream if stream is not None else self._stream,
            multiplexed_model_id if multiplexed_model_id is not None
            else self._multiplexed_model_id)
        h._router = self._router  # share in-flight accounting
        return h

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return _BoundMethod(self, name)

    # ---- routing ----------------------------------------------------------
    def _controller(self):
        import ray_tpu
        return ray_tpu.get_actor("_SERVE_CONTROLLER")

    def _refresh_replicas(self, force: bool = False):
        import ray_tpu
        r = self._router
        now = time.time()
        if not force and r.replicas and \
                now - r.last_refresh < _REPLICA_REFRESH_S:
            return
        ctrl = self._controller()
        replicas = ray_tpu.get(
            ctrl.get_replicas.remote(self._app, self._deployment))
        info = ray_tpu.get(
            ctrl.get_deployment_info.remote(self._app, self._deployment))
        with r.lock:
            r.replicas = replicas
            r.last_refresh = now
            if info:
                r.max_ongoing = info["max_ongoing_requests"]
                r.max_queued = info["max_queued_requests"]

    def _pick_replica(self, deadline_s: float = 30.0):
        """Power-of-two-choices on pending-request counts; blocks
        (bounded) when every replica is at max_ongoing_requests."""
        r = self._router
        start = time.time()
        while True:
            self._refresh_replicas(force=not r.replicas)
            r.prune()
            with r.lock:
                candidates = r.replicas
                if candidates:
                    if len(candidates) == 1:
                        chosen = candidates[0]
                    else:
                        a, b = random.sample(candidates, 2)
                        chosen = a if r.load(a[0]) <= r.load(b[0]) else b
                    if r.load(chosen[0]) < r.max_ongoing:
                        return chosen
            if time.time() - start > deadline_s:
                raise TimeoutError(
                    f"no capacity on {self._deployment} after {deadline_s}s")
            time.sleep(0.02)

    def remote(self, *args, **kwargs):
        r = self._router
        with r.lock:
            if r.max_queued >= 0 and r.queued >= r.max_queued:
                raise BackPressureError(
                    f"{self._deployment}: max_queued_requests "
                    f"({r.max_queued}) exceeded")
            r.queued += 1
        try:
            replica_id, handle = self._pick_replica()
        finally:
            with r.lock:
                r.queued -= 1
        args = tuple(a._to_object_ref() if isinstance(a, DeploymentResponse)
                     else a for a in args)
        if self._multiplexed_model_id:
            kwargs["__serve_multiplexed_model_id"] = \
                self._multiplexed_model_id
        if self._stream:
            with r.lock:
                r.manual[replica_id] = r.manual.get(replica_id, 0) + 1

            def done():
                with r.lock:
                    r.manual[replica_id] = max(
                        0, r.manual.get(replica_id, 1) - 1)
            sid_ref = handle.stream_start.remote(self._method, args, kwargs)
            return DeploymentResponseGenerator(handle, sid_ref, on_done=done)
        ref = handle.handle_request.remote(self._method, args, kwargs)
        with r.lock:
            r.pending.setdefault(replica_id, []).append(ref)

        def resubmit(a=args, kw=dict(kwargs)):
            r.last_refresh = 0.0  # force a routing-table refresh
            return self.remote(*a, **kw)
        return DeploymentResponse(ref, resubmit=resubmit)


class _BoundMethod:
    def __init__(self, handle: DeploymentHandle, method_name: str):
        self._handle = handle
        self._method = method_name

    def remote(self, *args, **kwargs):
        return self._handle.options(method_name=self._method).remote(
            *args, **kwargs)
