"""SLO-driven replica autoscaling for the serve plane.

Reference counterpart: python/ray/serve/_private/autoscaling_state.py
(metrics-driven replica targets) — but the scaling *policy* is
`core/autoscaler.py`'s: each replica is modeled as one node of a
per-deployment NodeType, so min/max replicas, the upscaling_speed step
clamp, and idle-timeout downscale all come from the same
first-fit-decreasing bin-pack policy that scales cluster hosts.

The controller feeds each deployment's live engine metrics — in-flight
requests, engine queue depth, TTFT/TPOT, KV-page utilization — into a
`DeploymentAutoscaler`, which returns a new replica target plus the
reason. Hysteresis lives here: upscale needs the breach to persist for
`upscale_delay_s`, downscale needs `downscale_delay_s` of slack, and a
change in either direction opens a cooldown before the opposite one,
so a sawtooth load cannot flap the replica set.

Placement: scale-ups can reserve a placement group (one bundle per new
replica, deployment-configurable strategy, multi-host capable) through
the driver's `sys.pg` channel; the bin-packed cluster view comes from
`sys.cluster_view`. Both work from the controller actor's worker
process — the tables themselves live only in the driver.
"""
from __future__ import annotations

import collections
import time
from typing import Any, Dict, List, Optional, Tuple

from ..core.autoscaler import Autoscaler, AutoscalerConfig, NodeType
from .config import AutoscalingConfig

_SLOT = "__replica_slot__"


# ---------------------------------------------------------------------------
# Driver-table access from the controller's worker process
# ---------------------------------------------------------------------------

def cluster_view() -> List[Dict[str, Any]]:
    """[{id, total, avail, labels, is_driver}] for live nodes — direct
    when running in the driver, via the sys.cluster_view report channel
    from a worker (the controller actor)."""
    from ..core.runtime import get_runtime
    rt = get_runtime()
    if hasattr(rt, "cluster_nodes"):          # driver process
        views = []
        for ns in list(rt.cluster_nodes.values()):
            if not ns.alive:
                continue
            views.append({"id": ns.node_id, "total": dict(ns.total),
                          "avail": dict(ns.avail),
                          "labels": dict(getattr(ns, "labels", {}) or {}),
                          "is_driver": ns.node_id == rt.node_id})
        return views
    try:
        return rt.report_sync("sys.cluster_view", None, timeout=5.0) or []
    except Exception:  # noqa: BLE001  view is advisory, never fatal
        return []


class PlacementGroupRef:
    """Worker-safe stand-in for a PlacementGroup: actor options only
    read `.pg_id` off the object they are given."""

    def __init__(self, pg_id: str):
        self.pg_id = pg_id

    def __repr__(self):
        return f"PlacementGroupRef({self.pg_id})"


def create_placement_group(bundles: List[Dict[str, float]],
                           strategy: str = "SPREAD",
                           name: str = "") -> Optional[PlacementGroupRef]:
    """Reserve bundles for a scale-up batch; driver-direct or via the
    sys.pg channel from a worker. Returns None when the driver is not
    reachable (callers then place without a reservation)."""
    from ..core.runtime import get_runtime
    rt = get_runtime()
    try:
        if hasattr(rt, "cluster_nodes"):
            state = rt.placement_group(bundles, strategy, name)
            return PlacementGroupRef(state.pg_id)
        out = rt.report_sync("sys.pg", ("create", bundles, strategy, name),
                             timeout=5.0)
        return PlacementGroupRef(out["pg_id"]) if out else None
    except Exception:  # noqa: BLE001
        return None


def remove_placement_group(pg_id: str) -> None:
    from ..core.runtime import get_runtime
    rt = get_runtime()
    try:
        if hasattr(rt, "cluster_nodes"):
            rt.remove_placement_group(pg_id)
        else:
            rt.report_sync("sys.pg", ("remove", pg_id), timeout=5.0)
    except Exception:  # noqa: BLE001
        pass


# ---------------------------------------------------------------------------
# Per-deployment policy
# ---------------------------------------------------------------------------

class DeploymentAutoscaler:
    """Turns a metric window into a replica target, with hysteresis.

    The desired count starts from the reference load formula
    (`AutoscalingConfig.desired_replicas` over average in-flight +
    engine queue depth), then SLO terms can only *raise* it: engine
    queue depth per replica above `target_queue_depth`, TTFT p50 above
    `ttft_slo_ms`, TPOT above `tpot_slo_ms`, or KV-page utilization
    above `kv_util_target` each ask for one more replica. The step
    toward the target is clamped by `core/autoscaler.py` — replicas are
    nodes of a synthetic NodeType whose min/max/upscaling_speed mirror
    the deployment's AutoscalingConfig.
    """

    def __init__(self, key: str, cfg: AutoscalingConfig):
        self.key = key
        self.cfg = cfg
        self._policy = Autoscaler(AutoscalerConfig(
            node_types=[NodeType(key, {_SLOT: 1.0},
                                 min_workers=cfg.min_replicas,
                                 max_workers=cfg.max_replicas)],
            upscaling_speed=cfg.upscaling_speed,
            idle_timeout_s=cfg.downscale_delay_s))
        self._above_since: Optional[float] = None
        self._below_since: Optional[float] = None
        self._last_change_ts = 0.0

    # -- desired count before hysteresis/step clamps ------------------------
    def _raw_desired(self, current: int, avg_load: float,
                     engine: Dict[str, float]) -> Tuple[int, str]:
        cfg = self.cfg
        desired = cfg.desired_replicas(avg_load, current)
        reason = (f"load {avg_load:.2f} vs target "
                  f"{cfg.target_ongoing_requests}/replica")
        bumps = []
        per = max(current, 1)
        q = engine.get("queue_depth", 0.0) / per
        if cfg.target_queue_depth is not None and \
                q > cfg.target_queue_depth:
            bumps.append(f"engine queue {q:.1f}/replica")
        ttft = engine.get("ttft_p50_ms")
        if cfg.ttft_slo_ms is not None and ttft is not None \
                and ttft > cfg.ttft_slo_ms:
            bumps.append(f"ttft p50 {ttft:.0f}ms > slo {cfg.ttft_slo_ms}")
        tpot = engine.get("tpot_ms")
        if cfg.tpot_slo_ms is not None and tpot is not None \
                and tpot > cfg.tpot_slo_ms:
            bumps.append(f"tpot {tpot:.1f}ms > slo {cfg.tpot_slo_ms}")
        kv = engine.get("kv_util")
        if cfg.kv_util_target is not None and kv is not None \
                and kv > cfg.kv_util_target:
            bumps.append(f"kv util {kv:.2f} > {cfg.kv_util_target}")
        if bumps:
            desired = max(desired, current + 1)
            reason = "; ".join(bumps)
        desired = int(min(max(desired, cfg.min_replicas),
                          cfg.max_replicas))
        return desired, reason

    def decide(self, now: float, current: int, avg_load: float,
               engine: Optional[Dict[str, float]] = None,
               per_replica_busy: Optional[Dict[str, float]] = None
               ) -> Tuple[int, str]:
        """(new_target, reason). new_target == current means hold.

        `per_replica_busy` maps replica_id -> in-flight count; it feeds
        the core policy's idle tracking so a replica only counts toward
        idle-timeout downscale once it has been empty for
        downscale_delay_s (and the load formula must agree).
        """
        cfg = self.cfg
        engine = engine or {}
        desired, reason = self._raw_desired(current, avg_load, engine)

        if desired > current:
            self._below_since = None
            if self._above_since is None:
                self._above_since = now
            if (now - self._above_since < cfg.upscale_delay_s
                    or now - self._last_change_ts < cfg.upscale_delay_s):
                return current, "upscale pending delay"
            # step clamp through the core policy: one synthetic demand
            # per missing replica, one synthetic busy node per current
            # replica; plan() applies max_workers AND upscaling_speed
            nodes = [{"id": rid, "type": self.key, "avail": {_SLOT: 0.0},
                      "used": {_SLOT: 1.0}}
                     for rid in (per_replica_busy or
                                 {f"r{i}": 1.0 for i in range(current)})]
            plan = self._policy.plan(
                demands=[{_SLOT: 1.0}] * (desired - current),
                nodes=nodes, now=now)
            step = plan["launch"].get(self.key, 0)
            if step <= 0:
                return current, "upscale clamped to zero"
            self._above_since = None
            self._last_change_ts = now
            return current + step, reason

        if desired < current:
            self._above_since = None
            if self._below_since is None:
                self._below_since = now
            if (now - self._below_since < cfg.downscale_delay_s
                    or now - self._last_change_ts < cfg.downscale_delay_s):
                return current, "downscale pending delay"
            self._below_since = None
            self._last_change_ts = now
            return desired, reason

        self._above_since = self._below_since = None
        return current, "steady"


# ---------------------------------------------------------------------------
# Controller-side coordinator
# ---------------------------------------------------------------------------

class ServeAutoscaler:
    """One per controller: per-deployment policies, a bounded decision
    log (surfaced by the state API / `/api/serve/autoscaler` / CLI),
    and bin-packed placement annotations for scale-ups."""

    _LOG_CAP = 256

    def __init__(self):
        self._by_key: Dict[str, DeploymentAutoscaler] = {}
        self.decisions: "collections.deque" = collections.deque(
            maxlen=self._LOG_CAP)

    def policy_for(self, key: str,
                   cfg: AutoscalingConfig) -> DeploymentAutoscaler:
        pol = self._by_key.get(key)
        if pol is None or pol.cfg is not cfg:
            pol = DeploymentAutoscaler(key, cfg)
            self._by_key[key] = pol
        return pol

    def feasible_now(self, resources: Dict[str, float],
                     count: int) -> int:
        """How many of `count` replicas (each needing `resources`) fit
        on the cluster's free capacity right now — first-fit-decreasing
        bin-pack over the live node views. Advisory: an infeasible
        replica still becomes a pending actor, which is exactly the
        demand signal the cluster-level StandardAutoscaler launches
        nodes for."""
        if count <= 0:
            return 0
        need = dict(resources) or {"CPU": 1.0}
        policy = Autoscaler(AutoscalerConfig(node_types=[]))
        unmet, _launch = policy.bin_pack(
            [dict(need)] * count,
            [(v["id"], dict(v["avail"])) for v in cluster_view()])
        return count - len(unmet)

    def record(self, *, key: str, deployment: str, app: str,
               direction: str, from_num: int, to_num: int, reason: str,
               feasible: Optional[int] = None,
               pg_id: Optional[str] = None) -> Dict[str, Any]:
        row = {"ts": time.time(), "key": key, "deployment": deployment,
               "app": app, "direction": direction, "from": from_num,
               "to": to_num, "reason": reason}
        if feasible is not None:
            row["feasible_now"] = feasible
        if pg_id:
            row["placement_group"] = pg_id
        self.decisions.append(row)
        return row

    def snapshot(self) -> List[Dict[str, Any]]:
        return list(self.decisions)


__all__ = ["DeploymentAutoscaler", "ServeAutoscaler", "cluster_view",
           "create_placement_group", "remove_placement_group",
           "PlacementGroupRef"]
