"""Generic ASGI ingress for serve deployments.

Reference parity: python/ray/serve/api.py:168 `@serve.ingress(app)` —
the reference mounts an ASGI app (typically FastAPI) on the proxy so a
deployment serves arbitrary routes/middleware. fastapi isn't in this
image, so `ray_tpu.serve.ingress` mounts ANY ASGI-3 callable (a
hand-rolled app, starlette-style framework, etc.):

    app = my_asgi_app           # async def app(scope, receive, send)

    @serve.deployment
    @serve.ingress(app)
    class Api:
        pass

    serve.run(Api.bind(), route_prefix="/api")

Requests under the route prefix reach the replica as a raw request dict
(method/path/query/headers/body); the wrapper drives the ASGI app on
the replica's event loop and streams the response back through the
deployment's streaming path — response start first, then raw body
chunks — so plain responses, chunked streaming, and SSE all flow
through one mechanism, with replica routing/autoscaling/batching
unchanged underneath.
"""
from __future__ import annotations

from typing import Any, Callable, Dict

ASGI_ATTR = "__ray_tpu_asgi__"
START_KEY = "__asgi_start__"


def ingress(asgi_app: Callable):
    """Class decorator: route HTTP requests for this deployment through
    `asgi_app` (an ASGI-3 callable). Apply UNDER @serve.deployment."""

    def decorator(cls):
        class ASGIIngress(cls):
            async def __call__(self, request: Dict[str, Any]):
                import asyncio

                scope = {
                    "type": "http",
                    "asgi": {"version": "3.0", "spec_version": "2.3"},
                    "http_version": "1.1",
                    "method": request["method"],
                    "scheme": "http",
                    "path": request["path"],
                    "raw_path": request["path"].encode(),
                    "query_string": (request.get("query") or "").encode(),
                    "root_path": request.get("root_path", ""),
                    "headers": [(str(k).lower().encode("latin-1"),
                                 str(v).encode("latin-1"))
                                for k, v in request.get("headers", [])],
                    "client": ("127.0.0.1", 0),
                    "server": ("127.0.0.1", 0),
                }
                body = request.get("body") or b""
                delivered = False

                async def receive():
                    nonlocal delivered
                    if not delivered:
                        delivered = True
                        return {"type": "http.request", "body": body,
                                "more_body": False}
                    return {"type": "http.disconnect"}

                q: "asyncio.Queue" = asyncio.Queue()

                async def send(msg):
                    await q.put(msg)

                app_err: list = []

                async def run():
                    try:
                        await asgi_app(scope, receive, send)
                    except BaseException as e:  # noqa: BLE001
                        app_err.append(e)
                    finally:
                        await q.put(None)

                task = asyncio.get_running_loop().create_task(run())
                started = False
                try:
                    while True:
                        msg = await q.get()
                        if msg is None:
                            break
                        if msg["type"] == "http.response.start":
                            started = True
                            yield {START_KEY: True,
                                   "status": int(msg["status"]),
                                   "headers": [
                                       (k.decode("latin-1"),
                                        v.decode("latin-1"))
                                       for k, v in msg.get("headers",
                                                           [])]}
                        elif msg["type"] == "http.response.body":
                            chunk = bytes(msg.get("body", b"") or b"")
                            if chunk:
                                yield chunk
                    if app_err:
                        raise app_err[0]
                    if not started:
                        raise RuntimeError(
                            "ASGI app finished without sending "
                            "http.response.start")
                finally:
                    task.cancel()

        ASGIIngress.__name__ = cls.__name__
        ASGIIngress.__qualname__ = cls.__qualname__
        ASGIIngress.__module__ = cls.__module__
        setattr(ASGIIngress, ASGI_ATTR, True)
        return ASGIIngress

    return decorator
