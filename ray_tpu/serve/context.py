"""Per-request serve context (deadline propagation).

The proxy stamps every request with an ABSOLUTE deadline (epoch
seconds); the handle forwards it as the reserved
`__serve_deadline_ts` kwarg; the replica pops it and exposes it here
for the user callable — the LLM server reads it and threads it into
engine admission, so an expired request is shed instead of executed.

Mirrors multiplex.py's contextvar pattern: sync handlers run in
executor threads that don't inherit the loop's context, so the replica
sets the var inside the thread actually running the handler frames.
"""
from __future__ import annotations

import contextvars
import time
from typing import Optional

_request_deadline: contextvars.ContextVar = contextvars.ContextVar(
    "ray_tpu_serve_request_deadline", default=None)


def _set_request_deadline(deadline_ts: Optional[float]) -> None:
    _request_deadline.set(deadline_ts)


def get_request_deadline() -> Optional[float]:
    """Absolute deadline (epoch seconds) of the serve request being
    handled, or None when the caller set no deadline."""
    return _request_deadline.get()


def remaining_budget() -> Optional[float]:
    """Seconds until the current request's deadline (clamped at 0), or
    None when no deadline was propagated."""
    d = _request_deadline.get()
    if d is None:
        return None
    return max(0.0, d - time.time())
