"""@serve.multiplexed — per-replica LRU cache of loaded models.

Reference parity: python/ray/serve/multiplex.py (_ModelMultiplexWrapper)
+ serve.get_multiplexed_model_id(). One replica serves many fine-tuned
model variants; the decorated async loader is called on cache miss and
the least-recently-used model is evicted (its __del__ / unload hook runs).
"""
from __future__ import annotations

import asyncio
import contextvars
import functools
from collections import OrderedDict
from typing import Callable, Optional

_current_model_id: contextvars.ContextVar[str] = contextvars.ContextVar(
    "ray_tpu_serve_multiplexed_model_id", default="")


def get_multiplexed_model_id() -> str:
    """Inside a handler: the model id of the in-flight request."""
    return _current_model_id.get()


def _set_multiplexed_model_id(model_id: str):
    _current_model_id.set(model_id)


def multiplexed(_fn: Optional[Callable] = None, *,
                max_num_models_per_replica: int = 3):
    def deco(fn):
        if not asyncio.iscoroutinefunction(fn):
            raise TypeError("@serve.multiplexed requires an async loader")
        caches = {}

        @functools.wraps(fn)
        async def wrapper(*args):
            if len(args) == 2:
                self_obj, model_id = args
                call = functools.partial(fn, self_obj)
                key = id(self_obj)
            else:
                (model_id,) = args
                call = fn
                key = None
            cache: OrderedDict = caches.setdefault(key, OrderedDict())
            if model_id in cache:
                cache.move_to_end(model_id)
                return cache[model_id]
            model = await call(model_id)
            cache[model_id] = model
            if len(cache) > max_num_models_per_replica:
                _evicted_id, evicted = cache.popitem(last=False)
                unload = getattr(evicted, "unload", None)
                if unload is not None:
                    r = unload()
                    if asyncio.iscoroutine(r):
                        await r
            return model

        return wrapper

    if _fn is not None:
        return deco(_fn)
    return deco
