"""Exception hierarchy for ray_tpu.

Parity: python/ray/exceptions.py in the reference (RayError, RayTaskError,
RayActorError, GetTimeoutError, ObjectLostError, TaskCancelledError).
"""
from __future__ import annotations


class RayTpuError(Exception):
    """Base class for all ray_tpu errors."""


class TaskError(RayTpuError):
    """Wraps an exception raised inside a remote task.

    Re-raised at the `get()` call site with the worker-side traceback
    attached, mirroring RayTaskError (reference python/ray/exceptions.py).
    """

    def __init__(self, cause_repr: str, traceback_str: str = "",
                 task_name: str = ""):
        self.cause_repr = cause_repr
        self.traceback_str = traceback_str
        self.task_name = task_name
        super().__init__(
            f"task {task_name or '<unknown>'} failed: {cause_repr}\n"
            f"{traceback_str}")


class ActorError(RayTpuError):
    """Base for actor-related failures."""


class ActorDiedError(ActorError):
    """The actor process died (crash or kill) before/while serving a call."""


class ActorUnavailableError(ActorError):
    """The actor is temporarily unreachable (e.g. restarting)."""


class ObjectLostError(RayTpuError):
    """Object was evicted or its producing worker died irrecoverably."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """`get(timeout=...)` expired before the object became available."""


class TaskCancelledError(RayTpuError):
    """The task was cancelled with `ray_tpu.cancel`."""


class WorkerCrashedError(RayTpuError):
    """A worker process died unexpectedly while executing a task."""


class RuntimeNotInitializedError(RayTpuError):
    """An API call was made before `ray_tpu.init()`."""


class ObjectStoreFullError(RayTpuError):
    """The shared-memory object store could not satisfy an allocation."""


class PlacementGroupError(RayTpuError):
    """A placement group cannot be satisfied (e.g. STRICT_SPREAD with more
    bundles than alive nodes)."""


class ActorExitRequest(RayTpuError):
    """Raised by ray_tpu.actor_exit() inside an actor method: the current
    call completes as a normal (None) result and the actor shuts down
    gracefully without restart (reference: ray.actor.exit_actor)."""
