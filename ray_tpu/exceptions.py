"""Exception hierarchy for ray_tpu.

Parity: python/ray/exceptions.py in the reference (RayError, RayTaskError,
RayActorError, GetTimeoutError, ObjectLostError, TaskCancelledError).
"""
from __future__ import annotations


class RayTpuError(Exception):
    """Base class for all ray_tpu errors."""


class TaskError(RayTpuError):
    """Wraps an exception raised inside a remote task.

    Re-raised at the `get()` call site with the worker-side traceback
    attached, mirroring RayTaskError (reference python/ray/exceptions.py).
    """

    def __init__(self, cause_repr: str, traceback_str: str = "",
                 task_name: str = ""):
        self.cause_repr = cause_repr
        self.traceback_str = traceback_str
        self.task_name = task_name
        super().__init__(
            f"task {task_name or '<unknown>'} failed: {cause_repr}\n"
            f"{traceback_str}")


class ActorError(RayTpuError):
    """Base for actor-related failures."""


class ActorDiedError(ActorError):
    """The actor process died (crash or kill) before/while serving a call."""


class ActorUnavailableError(ActorError):
    """The actor is temporarily unreachable (e.g. restarting)."""


class ObjectLostError(RayTpuError):
    """Object was evicted or its producing worker died irrecoverably."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """`get(timeout=...)` expired before the object became available."""


class TaskCancelledError(RayTpuError):
    """The task was cancelled with `ray_tpu.cancel`."""


class WorkerCrashedError(RayTpuError):
    """A worker process died unexpectedly while executing a task."""


class RuntimeNotInitializedError(RayTpuError):
    """An API call was made before `ray_tpu.init()`."""


class ObjectStoreFullError(RayTpuError):
    """The shared-memory object store could not satisfy an allocation."""


class PlacementGroupError(RayTpuError):
    """A placement group cannot be satisfied (e.g. STRICT_SPREAD with more
    bundles than alive nodes)."""


class ActorExitRequest(RayTpuError):
    """Raised by ray_tpu.actor_exit() inside an actor method: the current
    call completes as a normal (None) result and the actor shuts down
    gracefully without restart (reference: ray.actor.exit_actor)."""


# ---- serve-plane fault tolerance -------------------------------------------
# These are RETRIABLE request failures: the serve handle resubmits the
# request to a different replica (after refreshing the routing table)
# when it sees one of them, and the HTTP proxy maps them to retriable
# status codes. Replica-side raises cross process boundaries wrapped in
# TaskError (repr-string), so the handle matches them by cause name —
# keep the class names stable.

class EngineWedgedError(RayTpuError):
    """The LLM engine's generation loop stopped making forward progress
    past RAY_TPU_ENGINE_WATCHDOG_S while requests were admitted (a hung
    device call, a deadlocked control command). The replica fails its
    health check with a `wedged` cause and in-flight requests are
    aborted with this error so the handle can fail over."""


class ReplicaDrainingError(RayTpuError):
    """The replica is gracefully draining (rolling update / scale-down /
    shutdown) and admits no new requests; in-flight work completes.
    Retriable: the handle re-routes to a RUNNING replica."""


class NoCapacityError(RayTpuError, TimeoutError):
    """Every replica of the deployment stayed at max_ongoing_requests
    for the whole routing wait. The proxy maps this to 503 with
    Retry-After. Subclasses TimeoutError for callers of the old
    `_pick_replica` timeout contract."""


class DeadlineExceededError(RayTpuError, TimeoutError):
    """The request's propagated absolute deadline expired before (or
    while) it could be admitted; it was shed rather than executed.
    The proxy maps this to 503 with Retry-After."""


def error_cause_is(exc: BaseException, *names: str) -> bool:
    """True when `exc` is one of the named types, or is a TaskError
    whose cause_repr names one. Replica-side raises cross the actor
    boundary wrapped in TaskError (repr string; the original type is
    lost), so the serve plane matches retriable causes by class name —
    this is the ONE place that encodes that convention."""
    if type(exc).__name__ in names:
        return True
    cause = getattr(exc, "cause_repr", "") or ""
    return any(cause.startswith(name + "(") for name in names)


def classify_request_failure(exc: BaseException) -> str:
    """Symbolic failure class of a serve request, shared by every
    ingress so the retriable/shed/timeout taxonomy can't drift between
    proxies: "backpressure" (client should back off), "no_capacity"
    (all replicas saturated; retriable), "shed" (deadline expired
    before execution; retriable), "timeout" (executed but blew the
    budget), "error" (everything else). Name-based via error_cause_is,
    so TaskError-wrapped replica raises classify identically."""
    if error_cause_is(exc, "BackPressureError"):
        return "backpressure"
    if error_cause_is(exc, "NoCapacityError"):
        return "no_capacity"
    if error_cause_is(exc, "DeadlineExceededError"):
        return "shed"
    if error_cause_is(exc, "StreamInterruptedError"):
        return "interrupted"   # retriable by contract (post-first-token)
    if error_cause_is(exc, "GetTimeoutError"):
        return "timeout"
    return "error"


# ---- elastic training fault tolerance --------------------------------------
# Gang-plane failures cross the actor boundary wrapped in TaskError
# (repr string), so like the serve plane these are matched by class
# name (error_cause_is) — keep the names stable.

class CollectiveRankDiedError(RayTpuError):
    """A member rank of a collective gang died mid-round. Surviving
    ranks parked in `poll` get this immediately (naming the dead rank
    and the round) instead of spinning out the round timeout, so the
    elastic layer can tear the gang down and reform within seconds."""

    def __init__(self, message: str, *, rank: int = -1,
                 round_key=None):
        self.rank = rank
        self.round_key = round_key
        super().__init__(message)


class CollectiveStaleGenerationError(RayTpuError):
    """A contribute/poll arrived stamped with a superseded gang
    generation: the gang reformed while this rank was parked or
    stalled, and its world no longer exists. The rank must exit (the
    elastic layer already replaced it) — mirrors the node-incarnation
    fencing of PR 4."""


class GangReformError(RayTpuError):
    """The elastic gang could not be reformed: no feasible world (not
    even a shrunken one) within RAY_TPU_GANG_REFORM_TIMEOUT_S, or the
    re-gang itself failed."""


class StreamInterruptedError(RayTpuError):
    """A streaming response died AFTER yielding its first chunk (replica
    death or wedged engine mid-stream). Transparent resubmission would
    replay already-delivered tokens, so the caller gets this typed,
    retriable error instead; `cause_repr` names the underlying failure.
    Streams that die before the first chunk fail over transparently and
    never surface this."""

    def __init__(self, message: str, cause_repr: str = ""):
        self.cause_repr = cause_repr
        super().__init__(message)


class CompiledDagError(RayTpuError):
    """A compiled DAG's pipeline infrastructure failed: a pinned
    participant died, a channel peer closed mid-execution, or the
    install handshake broke. In-flight executions fail with this (the
    `cause` names what broke); the channels are torn down and the next
    `execute()` transparently re-compiles. User exceptions raised
    INSIDE a stage do not surface this — they propagate through the
    channels as ordinary TaskErrors without tearing the pipeline
    down."""

    def __init__(self, message: str, cause: str = ""):
        self.cause = cause
        super().__init__(message if not cause
                         else f"{message} (cause: {cause})")
