"""`python -m ray_tpu` — cluster state CLI.

Reference counterpart: the `ray` CLI (`ray status`, `ray summary
tasks|actors|objects`, `ray list actors|tasks|...`, `ray timeline`,
`ray job submit|status|logs`). Single-controller twist: there is no
long-lived head node to dial into from a cold process, so state
subcommands attach to a live driver via its dashboard URL (--address),
while `job` runs locally.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.error
import urllib.request


def _open(address: str, route: str) -> bytes:
    url = address.rstrip("/") + route
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.read()
    except urllib.error.HTTPError as e:       # dashboard is up: show body
        body = e.read().decode(errors="replace")
        sys.stderr.write(f"error: {url} -> HTTP {e.code}: {body}\n")
        sys.exit(2)
    except (urllib.error.URLError, OSError) as e:
        sys.stderr.write(
            f"error: cannot reach dashboard at {address} ({e}).\n"
            "Start one in the driver with "
            "ray_tpu.observability.start_dashboard(port=8265) and pass "
            "--address.\n")
        sys.exit(2)


def _fetch(address: str, route: str):
    return json.loads(_open(address, route))


def _print_table(rows, columns):
    if not rows:
        print("(empty)")
        return
    widths = {c: max(len(c), *(len(str(r.get(c, ""))) for r in rows))
              for c in columns}
    print("  ".join(c.ljust(widths[c]) for c in columns))
    print("  ".join("-" * widths[c] for c in columns))
    for r in rows:
        print("  ".join(str(r.get(c, "")).ljust(widths[c])
                        for c in columns))


def cmd_status(args):
    s = _fetch(args.address, "/api/cluster")
    print(json.dumps(s, indent=2))


def cmd_persistence(args):
    """Control-plane persistence health: driver incarnation, WAL
    length/bytes, last-snapshot age, replayed records after a resume."""
    s = _fetch(args.address, "/api/persistence")
    if args.json:
        print(json.dumps(s, indent=2))
        return
    if not s.get("enabled"):
        print("persistence: disabled (set RAY_TPU_STATE_DIR or "
              "init(state_dir=...) to make driver state durable)")
        print(f"driver incarnation: {s.get('driver_incarnation', 0)}")
        return
    print(f"state dir:           {s.get('state_dir')}")
    print(f"driver incarnation:  {s.get('driver_incarnation')}"
          + ("  (resumed)" if s.get("resumed") else ""))
    print(f"WAL records:         {s.get('wal_records')}"
          f"  ({s.get('wal_bytes')} bytes since last snapshot)")
    print(f"snapshots taken:     {s.get('snapshots_taken')}"
          f"  (last {s.get('last_snapshot_age_s')}s ago)")
    print(f"replayed on resume:  {s.get('replayed_records')}"
          + ("  [torn WAL tail truncated]"
             if s.get("torn_tail_recovered") else ""))
    if s.get("reattach_awaiting_objects"):
        print(f"awaiting reattach:   "
              f"{s['reattach_awaiting_objects']} objects parked for "
              "restored nodes")


def cmd_dispatch(args):
    """Batched-dispatch plane health: submit batch sizes, worker-lease
    grants/revokes, direct actor calls, control messages per direction
    (docs/SCHEDULING.md)."""
    s = _fetch(args.address, "/api/dispatch")
    if args.json:
        print(json.dumps(s, indent=2))
        return
    if not s.get("enabled"):
        print("dispatch stats unavailable on this runtime")
        return
    print(f"batching:            "
          f"{'on' if s.get('batching_enabled') else 'OFF (RAY_TPU_BATCH=0)'}"
          f"  (flush {s.get('flush_max_tasks')} tasks / "
          f"{s.get('flush_window_s')}s window)")
    print(f"binary wire:         "
          f"{'on' if s.get('binary_wire_enabled') else 'OFF'}")
    print(f"submit batches:      {s.get('submit_batches')}"
          f"  ({s.get('batched_submits')} tasks, avg "
          f"{s.get('avg_submit_batch')})")
    print(f"explicit submit_many:{s.get('submit_many_calls')}")
    print(f"leases:              {s.get('lease_grants')} granted / "
          f"{s.get('lease_revokes')} revoked "
          f"(cap {s.get('lease_slots')} slots; actor pipeline "
          f"{s.get('actor_pipeline')})")
    print(f"dispatch frames:     {s.get('dispatch_frames')}"
          f"  ({s.get('dispatched_tasks')} tasks)")
    if s.get("node_leases_enabled"):
        print(f"node leases:         {s.get('node_lease_grants')} "
              f"granted / {s.get('node_lease_extends')} extended / "
              f"{s.get('node_leases_open')} open "
              f"(cap {s.get('node_lease_slots')} slots/worker; "
              f"{s.get('node_lease_tasks')} tasks agent-dispatched)")
        print(f"spillbacks:          {s.get('spillbacks')}")
    else:
        print("node leases:         OFF (RAY_TPU_NODE_LEASES=0)")
    print(f"direct actor calls:  {s.get('direct_actor_calls', 0)}"
          f"  ({s.get('direct_call_fallbacks', 0)} fell back to the "
          f"driver path)")
    print(f"inbound ctrl frames: {s.get('ctrl_frames_in')}")
    msgs = s.get("ctrl_msgs_in") or {}
    top = sorted(msgs.items(), key=lambda kv: -kv[1])[:8]
    if top:
        print("inbound ctrl msgs:   "
              + ", ".join(f"{k}={v}" for k, v in top))


def cmd_lint(args):
    """Run the raylint static-analysis gate (tools/raylint): the
    concurrency/invariant checks RT001-RT005 over the package, exiting
    non-zero on any unsuppressed finding (docs/STATIC_ANALYSIS.md).
    Runs locally against source — no driver needed."""
    try:
        from tools.raylint.__main__ import main as raylint_main
    except ImportError:
        # installed-package invocation: tools/ lives next to the repo's
        # ray_tpu/, so try the checkout root before giving up
        import ray_tpu
        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(ray_tpu.__file__)))
        sys.path.insert(0, repo)
        try:
            from tools.raylint.__main__ import main as raylint_main
        except ImportError:
            sys.stderr.write(
                "error: raylint needs the repo checkout (tools/raylint "
                "is not shipped in the installed package)\n")
            sys.exit(2)
    argv = list(args.raylint_args or [])
    if argv and argv[0] == "--":   # `ray_tpu lint -- -o json`
        argv = argv[1:]
    sys.exit(raylint_main(argv))


def cmd_list(args):
    route = {"actors": "/api/actors", "tasks": "/api/tasks",
             "objects": "/api/objects", "nodes": "/api/nodes",
             "workers": "/api/workers",
             "placement-groups": "/api/placement_groups"}[args.kind]
    data = _fetch(args.address, route + f"?limit={args.limit}")
    if args.kind == "objects":
        data = data["objects"]
    if args.json:
        print(json.dumps(data, indent=2))
        return
    cols = {
        "actors": ["actor_id", "class_name", "state", "name", "worker_id"],
        "tasks": ["task_id", "name", "state", "worker_id", "duration_s"],
        "objects": ["object_id", "state", "size_bytes", "store_kind"],
        "nodes": ["node_id", "hostname", "alive"],
        "workers": ["worker_id", "pid", "state", "actor_id"],
        "placement-groups": ["placement_group_id", "name", "strategy",
                             "state"],
    }[args.kind]
    _print_table(data, cols)


def cmd_summary(args):
    print(json.dumps(_fetch(args.address, f"/api/summary/{args.kind}"),
                     indent=2))


def cmd_timeline(args):
    events = _fetch(args.address, "/api/timeline")
    with open(args.output, "w") as f:
        json.dump(events, f)
    print(f"wrote {len(events)} events to {args.output} "
          "(load in chrome://tracing or Perfetto)")


def _parse_prometheus(text: str):
    """Parse a Prometheus text exposition into
    (meta {name: (kind, help)}, samples [(name, {label: val}, value)]).
    Histogram _bucket/_sum/_count samples keep their suffixed names."""
    import re
    meta = {}
    samples = []
    line_re = re.compile(
        r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})?\s+(\S+)$')
    label_re = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_ = rest.partition(" ")
            kind = meta.get(name, ("untyped", ""))[0]
            meta[name] = (kind, help_)
        elif line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, kind = rest.partition(" ")
            help_ = meta.get(name, ("", ""))[1]
            meta[name] = (kind.strip(), help_)
        elif not line.startswith("#"):
            m = line_re.match(line)
            if not m:
                continue
            labels = {k: v for k, v in
                      label_re.findall(m.group(3) or "")}
            try:
                value = float(m.group(4))
            except ValueError:
                continue
            samples.append((m.group(1), labels, value))
    return meta, samples


def _format_metrics(text: str, needle: str = "") -> str:
    """Pretty-print a merged exposition grouped by metric: counters and
    gauges one line per series; histograms as count/sum/mean."""
    meta, samples = _parse_prometheus(text)
    by_base = {}
    for name, labels, value in samples:
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[:-len(suffix)] in meta \
                    and meta[name[:-len(suffix)]][0] == "histogram":
                base = name[:-len(suffix)]
                break
        by_base.setdefault(base, []).append((name, labels, value))
    out = []
    for base in sorted(by_base):
        if needle and needle not in base:
            continue
        kind, help_ = meta.get(base, ("untyped", ""))
        out.append(f"{base} ({kind})" + (f" — {help_}" if help_ else ""))
        rows = by_base[base]
        if kind == "histogram":
            # one row per tag set: count / sum / mean
            hist = {}
            for name, labels, value in rows:
                key = tuple(sorted((k, v) for k, v in labels.items()
                                   if k != "le"))
                ent = hist.setdefault(key, {"count": 0.0, "sum": 0.0})
                if name.endswith("_count"):
                    ent["count"] = value
                elif name.endswith("_sum"):
                    ent["sum"] = value
            for key in sorted(hist):
                ent = hist[key]
                tags = ",".join(f'{k}="{v}"' for k, v in key)
                mean = (ent["sum"] / ent["count"]) if ent["count"] else 0
                out.append(f"  {{{tags}}}  count={ent['count']:g} "
                           f"sum={ent['sum']:.6g} mean={mean:.6g}")
        else:
            for name, labels, value in sorted(
                    rows, key=lambda r: sorted(r[1].items())):
                tags = ",".join(f'{k}="{v}"'
                                for k, v in sorted(labels.items()))
                out.append(f"  {{{tags}}}  {value:g}")
        out.append("")
    return "\n".join(out)


def cmd_metrics(args):
    text = _open(args.address, "/metrics").decode()
    if args.raw:
        sys.stdout.write(text)
        return
    sys.stdout.write(_format_metrics(text, needle=args.grep or ""))


def _events_query(args, since: int = 0) -> str:
    from urllib.parse import urlencode
    params = [("limit", args.limit)]
    if since:
        params.append(("since", since))
    for key, flag in (("task_id", "task"), ("actor_id", "actor"),
                      ("object_id", "object"), ("node_id", "node"),
                      ("worker_id", "worker")):
        v = getattr(args, flag, None)
        if v:
            params.append((key, v))
    for t in args.type or ():
        params.append(("type", t))
    for s in args.severity or ():
        params.append(("severity", s))
    return "/api/events?" + urlencode(params)


def _print_events(rows) -> None:
    import datetime
    for ev in rows:
        ts = datetime.datetime.fromtimestamp(
            ev.get("ts", 0)).strftime("%H:%M:%S.%f")[:-3]
        ids = " ".join(
            f"{k}={ev[k]}" for k in ("task_id", "actor_id", "object_id",
                                     "node_id", "worker_id")
            if ev.get(k))
        msg = ev.get("message") or ""
        line = (f"{ev.get('seq', '?'):>6} {ts} "
                f"{ev.get('severity', 'info'):<7} "
                f"{ev.get('type', '?'):<26} {ids}")
        print(line + (f"  | {msg}" if msg else ""))


def cmd_events(args):
    """`ray_tpu events` — cluster lifecycle event log, filterable by
    id/type/severity; --follow tails new events; -o exports JSONL."""
    data = _fetch(args.address, _events_query(args))
    rows = data["events"]
    if args.output:
        with open(args.output, "w") as f:
            for ev in rows:
                f.write(json.dumps(ev, default=str) + "\n")
        print(f"wrote {len(rows)} events to {args.output}"
              + (f" (truncated; {data['total']} matched)"
                 if data.get("truncated") else ""))
        return
    if args.json:
        print(json.dumps(data, indent=2, default=str))
        return
    _print_events(rows)
    if data.get("truncated"):
        print(f"... truncated: showing {len(rows)} of {data['total']} "
              f"matching events (raise --limit)")
    if not args.follow:
        return
    last = max((ev.get("seq", 0) for ev in rows), default=0)
    try:
        while True:
            time.sleep(args.interval)
            data = _fetch(args.address, _events_query(args, since=last))
            fresh = data["events"]
            if fresh:
                if data.get("truncated"):
                    # a burst bigger than --limit landed between polls:
                    # the server kept only the newest window — say so
                    # instead of silently skipping the gap
                    print(f"... gap: {data['total'] - len(fresh)} "
                          f"events since seq {last} not shown "
                          f"(raise --limit)")
                _print_events(fresh)
                last = max(ev.get("seq", last) for ev in fresh)
    except KeyboardInterrupt:
        return


def cmd_post_mortem(args):
    """`ray_tpu post-mortem <task_id|actor_id>` — assemble the failure
    bundle (event chain + span subtree + tagged log tail + metrics
    snapshot) from the live driver and write one JSON artifact."""
    from urllib.parse import urlencode
    bundle = _fetch(args.address,
                    "/api/post_mortem?" + urlencode({"id": args.id}))
    out = args.output or f"post-mortem-{args.id}.json"
    with open(out, "w") as f:
        json.dump(bundle, f, indent=1, default=str)
    subj = bundle.get("subject", {})
    logs = bundle.get("log_tail", {}) or {}
    print(f"wrote {out}: kind={subj.get('kind')} "
          f"events={len(bundle.get('events', []))} "
          f"spans={len(bundle.get('spans', []))} "
          f"log_lines={len(logs.get('lines', []))}")
    if subj.get("kind") == "task":
        t = subj["task"]
        print(f"  task {t['name']} state={t['state']} "
              f"worker={t['worker_id']}")
    elif subj.get("kind") == "actor":
        a = subj["actor"]
        print(f"  actor {a['class_name']} state={a['state']} "
              f"death_cause={a['death_cause'] or '-'}")


def _post(address: str, route: str, payload: dict):
    url = address.rstrip("/") + route
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=15) as r:
            return json.loads(r.read())
    except urllib.error.HTTPError as e:
        body = e.read().decode(errors="replace")
        sys.stderr.write(f"error: {url} -> HTTP {e.code}: {body}\n")
        sys.exit(2)
    except (urllib.error.URLError, OSError) as e:
        sys.stderr.write(
            f"error: cannot reach dashboard at {address} ({e})\n")
        sys.exit(2)


def _collapsed_from_payload(payload: dict) -> str:
    """Worker snapshot payload -> collapsed-stack text (same format as
    the driver store's aggregate export)."""
    merged = {}
    for task, stack, n in payload.get("samples") or ():
        line = f"task:{task};{stack}" if task else stack
        merged[line] = merged.get(line, 0) + n
    return "\n".join(f"{s} {n}" for s, n in
                     sorted(merged.items(), key=lambda kv: -kv[1]))


def cmd_profile(args):
    """`ray_tpu profile` — the always-on sampling profiler
    (docs/OBSERVABILITY.md). `show` (default) exports the driver-side
    aggregate; start/stop/snapshot/status drive one worker's sampler
    live over the control plane."""
    action = args.action
    if action in ("start", "stop", "snapshot", "status"):
        if not args.worker:
            sys.stderr.write(f"error: profile {action} needs "
                             "--worker <worker id>\n")
            sys.exit(2)
        payload = {"worker": args.worker, "action": action}
        if action == "start":
            payload["hz"] = args.hz
        reply = _post(args.address, "/api/profile", payload)
        if action == "snapshot" and args.format == "collapsed":
            text = _collapsed_from_payload(reply)
            if args.output:
                with open(args.output, "w") as f:
                    f.write(text + "\n")
                print(f"wrote {args.output}")
            else:
                print(text)
            return
        print(json.dumps(reply, indent=2))
        return
    # show: driver-side aggregate, collapsed / speedscope / summary
    from urllib.parse import urlencode
    params = {"format": args.format}
    if args.worker:
        params["worker"] = args.worker
    if args.task:
        params["task"] = args.task
    route = "/api/profile?" + urlencode(params)
    if args.format == "collapsed":
        text = _open(args.address, route).decode()
        if args.output:
            with open(args.output, "w") as f:
                f.write(text + "\n")
            print(f"wrote {args.output} (flamegraph.pl or paste into "
                  "speedscope.app)")
        else:
            print(text)
        return
    data = _fetch(args.address, route)
    if args.output:
        with open(args.output, "w") as f:
            json.dump(data, f)
        print(f"wrote {args.output}"
              + (" (open at https://www.speedscope.app)"
                 if args.format == "speedscope" else ""))
    else:
        print(json.dumps(data, indent=2))


def cmd_stuck(args):
    """`ray_tpu stuck [id]` — why is the cluster (or one task/actor/
    worker) not making progress: detected deadlock cycles first, then
    every wait chain with its resolved root cause, oldest first."""
    from urllib.parse import urlencode
    graph = _fetch(args.address, "/api/waitgraph")
    params = {"min_age": args.min_age}
    if args.id:
        params["id"] = args.id
    waits = _fetch(args.address,
                   "/api/waits?" + urlencode(params)).get("waits", [])
    if args.json:
        print(json.dumps({"waitgraph": graph, "waits": waits},
                         indent=2, default=str))
        return
    cycles = graph.get("cycles") or []
    probe = graph.get("last_probe") or {}
    if cycles:
        print(f"DEADLOCK: {len(cycles)} waits-on cycle(s) detected")
        labels = {n.get("key"): n for n in graph.get("nodes", [])}
        for cyc in cycles:
            print("  cycle:")
            for k in cyc:
                n = labels.get(k, {})
                extra = ", ".join(str(n[f]) for f in
                                  ("name", "state", "worker_id")
                                  if n.get(f))
                print(f"    {k}" + (f"  ({extra})" if extra else ""))
            edges = [e for e in graph.get("edges", [])
                     if e["src"] in cyc and e["dst"] in cyc]
            for e in edges:
                print(f"      {e['src']} -[{e['why']}]-> {e['dst']}")
    for s in probe.get("stragglers") or []:
        print(f"STRAGGLER: group {s.get('group')!r} seq "
              f"{s.get('seq')} stuck {s.get('stuck_s')}s — missing "
              f"ranks {s.get('missing_ranks')}, behind "
              f"{s.get('behind_ranks')}")
    if not waits:
        if not cycles and not probe.get("stragglers"):
            print("nothing is stuck: no wait records"
                  + (f" touching {args.id!r}" if args.id else ""))
        return
    print(f"{len(waits)} wait(s)"
          + (f" touching {args.id!r}" if args.id else "") + ":")
    for w in waits:
        who = w.get("waiter") or w.get("worker_id")
        print(f"  [{w['age_s']:>7.1f}s] {who} on "
              f"{w['kind']}:{w['rid']}")
        print(f"            {w['root_cause']}")


def cmd_stack(args):
    """`ray_tpu stack` — one-shot stack dump of every live worker (the
    in-process `py-spy dump` across the cluster, with task
    attribution), riding the profile_ctl control plane."""
    workers = _fetch(args.address, "/api/workers")
    wids = [w["worker_id"] for w in workers
            if w.get("state") not in ("dead",)]
    if args.worker:
        wids = [w for w in wids if w == args.worker]
    dumps = []
    for wid in wids:
        try:
            dumps.append(_post(args.address, "/api/profile",
                               {"worker": wid, "action": "stack"}))
        except SystemExit:
            # a worker that died mid-iteration is a skip, not an abort
            dumps.append({"worker_id": wid,
                          "error": "unreachable"})
    if args.format == "speedscope":
        # each thread's current stack becomes one weight-1 sample
        frames, fidx, samples = [], {}, []
        for d in dumps:
            for t in d.get("threads") or ():
                parts = [f"worker:{d.get('worker_id')}",
                         f"thread:{t.get('name')}"]
                if t.get("task_id"):
                    parts.append(f"task:{t['task_id']}")
                parts.extend(p for p in (t.get("stack") or "")
                             .split(";") if p)
                row = []
                for p in parts:
                    if p not in fidx:
                        fidx[p] = len(frames)
                        frames.append({"name": p})
                    row.append(fidx[p])
                samples.append(row)
        out = {"$schema":
               "https://www.speedscope.app/file-format-schema.json",
               "name": "ray_tpu stack",
               "shared": {"frames": frames},
               "profiles": [{"type": "sampled",
                             "name": "ray_tpu stack", "unit": "none",
                             "startValue": 0,
                             "endValue": len(samples),
                             "samples": samples,
                             "weights": [1] * len(samples)}]}
        text = json.dumps(out)
        if args.output:
            with open(args.output, "w") as f:
                f.write(text)
            print(f"wrote {args.output} "
                  "(open at https://www.speedscope.app)")
        else:
            print(text)
        return
    for d in dumps:
        wid = d.get("worker_id", "?")
        if d.get("error"):
            print(f"== {wid}: {d['error']}")
            continue
        print(f"== {wid} ({len(d.get('threads') or [])} threads)")
        for t in d.get("threads") or ():
            task = f"  [task {t['task_id']}]" if t.get("task_id") else ""
            print(f"  -- {t.get('name')}{task}")
            for fr in (t.get("stack") or "").split(";"):
                if fr:
                    print(f"       {fr}")


def cmd_job(args):
    from .core.jobs import JobSubmissionClient
    # submit runs the entrypoint as a local child unless --remote sends
    # it to the --address dashboard's /api/jobs (reference: ray job CLI
    # -> dashboard job head). The query verbs (status/logs/stop/list)
    # ALWAYS go over HTTP: a fresh CLI process has no local job table,
    # so local mode could never find anything.
    remote = (args.job_cmd != "submit"
              or getattr(args, "remote", False))
    client = JobSubmissionClient(address=args.address if remote else None)
    if args.job_cmd == "submit":
        entry = list(args.entrypoint)
        if entry and entry[0] == "--":       # `job submit -- cmd ...`
            entry = entry[1:]
        if not entry:
            sys.stderr.write("error: job submit needs an entrypoint, "
                             "e.g. `ray_tpu job submit -- python x.py`\n")
            sys.exit(2)
        sid = client.submit_job(entrypoint=" ".join(entry))
        if args.no_wait:
            print(sid)
            return
        try:
            status = client.wait_until_finished(sid, timeout=args.timeout)
        except TimeoutError:
            client.stop_job(sid)             # don't orphan the subprocess
            print(client.get_job_logs(sid), end="")
            print(f"job {sid}: TIMEOUT after {args.timeout}s (stopped)")
            sys.exit(1)
        print(client.get_job_logs(sid), end="")
        print(f"job {sid}: {status}")
        sys.exit(0 if status == "SUCCEEDED" else 1)
    elif args.job_cmd == "list":
        try:
            _print_table(client.list_jobs(),
                         ["submission_id", "status", "entrypoint"])
        except ValueError as e:
            sys.stderr.write(f"error: {e}\n")
            sys.exit(1)
        except OSError as e:
            sys.stderr.write(f"error: cannot reach dashboard at "
                             f"{args.address}: {e}\n")
            sys.exit(1)
    else:
        try:
            if args.job_cmd == "status":
                print(client.get_job_status(args.submission_id))
            elif args.job_cmd == "logs":
                if args.follow:
                    for piece in client.tail_job_logs(
                            args.submission_id):
                        sys.stdout.write(piece)
                        sys.stdout.flush()
                else:
                    sys.stdout.write(
                        client.get_job_logs(args.submission_id))
            elif args.job_cmd == "stop":
                stopped = client.stop_job(args.submission_id)
                print(f"job {args.submission_id}: "
                      f"{'stopped' if stopped else 'already finished'}")
        except ValueError as e:
            sys.stderr.write(f"error: {e}\n")
            sys.exit(1)
        except OSError as e:
            sys.stderr.write(f"error: cannot reach dashboard at "
                             f"{args.address}: {e}\n")
            sys.exit(1)


def cmd_serve(args):
    """`ray_tpu serve run module:app` — import an Application and serve
    it, blocking (reference: `serve run` CLI). `serve status` reads the
    live driver's dashboard (--address); there is no remote shutdown —
    Ctrl-C the `serve run` process."""
    import importlib

    import ray_tpu
    from ray_tpu import serve as serve_mod

    if args.serve_cmd == "run":
        if ":" not in args.target:
            sys.stderr.write("error: target must be module:attribute, "
                             "e.g. myapp:app\n")
            sys.exit(2)
        mod_name, attr = args.target.split(":", 1)
        sys.path.insert(0, os.getcwd())
        app = getattr(importlib.import_module(mod_name), attr)
        ray_tpu.init()
        kwargs = {}
        if args.route_prefix is not None:
            kwargs["route_prefix"] = args.route_prefix
        serve_mod.run(app, name=args.name, **kwargs)
        from .serve.http_proxy import start_proxy
        _proxy, port = start_proxy(host=args.host, port=args.port)
        print(f"serving {args.target} on http://{args.host}:{port}",
              flush=True)
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            serve_mod.shutdown()
        return
    if args.serve_cmd == "status":
        # read-only: attach to the LIVE driver via its dashboard (an
        # in-process runtime would report an empty fresh cluster)
        print(json.dumps(_fetch(args.address, "/api/serve"), indent=2))
        return
    if args.serve_cmd == "router":
        # scale-out router table: ring membership, registered prefixes
        # + owners, recent sticky session bindings
        print(json.dumps(_fetch(args.address, "/api/serve/router"),
                         indent=2))
        return
    if args.serve_cmd == "autoscaler":
        # autoscaler targets + the recent scale_up/scale_down decisions
        print(json.dumps(_fetch(args.address, "/api/serve/autoscaler"),
                         indent=2))
        return


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="ray_tpu", description="ray_tpu cluster state CLI")
    p.add_argument("--address", default="http://127.0.0.1:8265",
                   help="dashboard URL of a live driver "
                        "(start one with ray_tpu.observability."
                        "start_dashboard(port=8265))")
    sub = p.add_subparsers(dest="cmd", required=True)

    sub.add_parser("status", help="cluster summary").set_defaults(
        fn=cmd_status)

    pp = sub.add_parser(
        "persistence",
        help="control-plane WAL/snapshot health (driver incarnation, "
             "WAL length, last-snapshot age, resume replay count)")
    pp.add_argument("--json", action="store_true")
    pp.set_defaults(fn=cmd_persistence)

    dpp = sub.add_parser(
        "dispatch",
        help="batched-dispatch plane health (submit batches, worker "
             "leases, direct actor calls, control-message counts)")
    dpp.add_argument("--json", action="store_true")
    dpp.set_defaults(fn=cmd_dispatch)

    ltp = sub.add_parser(
        "lint",
        help="raylint static-analysis gate (RT001-RT005 over ray_tpu/; "
             "docs/STATIC_ANALYSIS.md); extra args pass through, e.g. "
             "`ray_tpu lint -- -o json`")
    ltp.add_argument("raylint_args", nargs=argparse.REMAINDER,
                     help="arguments forwarded to python -m tools.raylint")
    ltp.set_defaults(fn=cmd_lint)

    lp = sub.add_parser("list", help="list cluster entities")
    lp.add_argument("kind", choices=["actors", "tasks", "objects", "nodes",
                                     "workers", "placement-groups"])
    lp.add_argument("--limit", type=int, default=100)
    lp.add_argument("--json", action="store_true")
    lp.set_defaults(fn=cmd_list)

    sp = sub.add_parser("summary", help="rollups by name/state")
    sp.add_argument("kind", choices=["tasks", "actors", "objects"])
    sp.set_defaults(fn=cmd_summary)

    tp = sub.add_parser("timeline", help="export chrome-trace JSON")
    tp.add_argument("-o", "--output", default="timeline.json")
    tp.set_defaults(fn=cmd_timeline)

    ep = sub.add_parser(
        "events", help="cluster lifecycle event log (filter by "
                       "id/type/severity; --follow tails)")
    ep.add_argument("--task", help="filter: events referencing task id")
    ep.add_argument("--actor", help="filter: events referencing actor id")
    ep.add_argument("--object",
                    help="filter: events referencing object id")
    ep.add_argument("--node", help="filter: events referencing node id")
    ep.add_argument("--worker",
                    help="filter: events referencing worker id")
    ep.add_argument("--type", action="append",
                    help="filter: event type (repeatable), e.g. "
                         "task.retry")
    ep.add_argument("--severity", action="append",
                    choices=["info", "warning", "error"],
                    help="filter: severity (repeatable)")
    ep.add_argument("--limit", type=int, default=100)
    ep.add_argument("--json", action="store_true")
    ep.add_argument("--follow", action="store_true",
                    help="keep polling for new events (Ctrl-C stops)")
    ep.add_argument("--interval", type=float, default=1.0,
                    help="--follow poll interval seconds")
    ep.add_argument("-o", "--output", default=None,
                    help="export matching events as JSONL")
    ep.set_defaults(fn=cmd_events)

    pmp = sub.add_parser(
        "post-mortem", help="assemble a failure bundle for a task or "
                            "actor id (events + spans + tagged logs + "
                            "metrics)")
    pmp.add_argument("id", help="task_id (tsk-...) or actor_id (act-...)")
    pmp.add_argument("-o", "--output", default=None,
                     help="bundle path (default post-mortem-<id>.json)")
    pmp.set_defaults(fn=cmd_post_mortem)

    mp = sub.add_parser(
        "metrics", help="merged cluster metrics (pretty-printed; "
                        "--raw for the Prometheus text)")
    mp.add_argument("--raw", action="store_true",
                    help="dump the raw Prometheus exposition")
    mp.add_argument("--grep", default="",
                    help="only show metrics whose name contains this")
    mp.set_defaults(fn=cmd_metrics)

    prp = sub.add_parser(
        "profile", help="sampling profiler: export the cluster "
                        "aggregate or start/stop/snapshot one worker's "
                        "sampler live")
    prp.add_argument("action", nargs="?", default="show",
                     choices=["show", "start", "stop", "snapshot",
                              "status"])
    prp.add_argument("--worker", default=None,
                     help="worker id (required for start/stop/"
                          "snapshot/status; filters `show`)")
    prp.add_argument("--task", default=None,
                     help="filter `show` to one task id")
    prp.add_argument("--hz", type=float, default=100.0,
                     help="sampling rate for `start` (default 100)")
    prp.add_argument("--format", default="collapsed",
                     choices=["collapsed", "speedscope", "summary"],
                     help="`show`/`snapshot` output format")
    prp.add_argument("-o", "--output", default=None)
    prp.set_defaults(fn=cmd_profile)

    stp = sub.add_parser(
        "stuck", help="why is it stuck: deadlock cycles, stragglers, "
                      "and every wait chain with its root cause")
    stp.add_argument("id", nargs="?", default=None,
                     help="restrict to chains touching this task/"
                          "actor/worker/object id (prefix ok)")
    stp.add_argument("--min-age", type=float, default=0.0,
                     help="hide waits younger than this many seconds")
    stp.add_argument("--json", action="store_true",
                     help="raw waitgraph + chains as JSON")
    stp.set_defaults(fn=cmd_stuck)

    skp = sub.add_parser(
        "stack", help="one-shot stack dump of every live worker "
                      "(py-spy-dump equivalent, task-attributed)")
    skp.add_argument("--worker", default=None,
                     help="dump just this worker id")
    skp.add_argument("--format", default="plain",
                     choices=["plain", "speedscope"])
    skp.add_argument("-o", "--output", default=None,
                     help="write speedscope JSON here instead of "
                          "stdout")
    skp.set_defaults(fn=cmd_stack)

    svp = sub.add_parser("serve", help="serve an Application over HTTP")
    svsub = svp.add_subparsers(dest="serve_cmd", required=True)
    svr = svsub.add_parser("run", help="import module:app and serve it")
    svr.add_argument("target")
    svr.add_argument("--name", default="default")
    svr.add_argument("--route-prefix", default=None)
    svr.add_argument("--host", default="127.0.0.1")
    svr.add_argument("--port", type=int, default=8000)
    svr.set_defaults(fn=cmd_serve)
    svst = svsub.add_parser(
        "status", help="serve apps of the live driver (via --address "
                       "dashboard); stop a served app with Ctrl-C on "
                       "its `serve run` process")
    svst.set_defaults(fn=cmd_serve)
    svrt = svsub.add_parser(
        "router", help="scale-out router table: replica ring, "
                       "registered prefixes + owners, sticky bindings")
    svrt.set_defaults(fn=cmd_serve)
    svas = svsub.add_parser(
        "autoscaler", help="serve autoscaler targets + recent "
                           "scale_up/scale_down decisions")
    svas.set_defaults(fn=cmd_serve)

    jp = sub.add_parser("job", help="run a driver script as a job")
    jsub = jp.add_subparsers(dest="job_cmd", required=True)
    jsp = jsub.add_parser("submit")
    jsp.add_argument("--timeout", type=float, default=3600.0)
    jsp.add_argument("--no-wait", action="store_true",
                     help="print the submission id and return")
    jsp.add_argument("--remote", action="store_true",
                     help="submit via --address dashboard /api/jobs")
    jsp.add_argument("entrypoint", nargs=argparse.REMAINDER)
    jsp.set_defaults(fn=cmd_job)
    jls = jsub.add_parser("list", help="jobs on the --address dashboard")
    jls.set_defaults(fn=cmd_job)
    for verb in ("status", "logs", "stop"):
        jv = jsub.add_parser(verb,
                             help=f"{verb} via the --address dashboard")
        jv.add_argument("submission_id")
        if verb == "logs":
            jv.add_argument("--follow", action="store_true")
        jv.set_defaults(fn=cmd_job)

    npp = sub.add_parser(
        "node", help="join this host to a driver as a node agent "
                     "(alias of python -m ray_tpu.core.node)",
        add_help=False)
    del npp  # listed in top-level help; dispatch happens below

    # `node` forwards EVERYTHING after it (flags in any order, --help
    # included) to the agent's own parser; parse_known_args would eat its
    # flags. The only global option (--address) may precede it.
    argv = sys.argv[1:] if argv is None else list(argv)
    i = 0
    while i < len(argv):
        tok = argv[i]
        if tok == "--address":
            i += 2
            continue
        if tok.startswith("--address="):
            i += 1
            continue
        break
    if i < len(argv) and argv[i] == "node":
        from .core import node as node_mod
        sys.argv = ["ray_tpu node", *argv[i + 1:]]
        node_mod.main()
        return

    args = p.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
