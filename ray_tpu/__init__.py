"""ray_tpu — a TPU-native distributed AI framework.

Same capability surface as the reference (ray-project/ray fork at
/root/reference): Core tasks/actors/objects + Data/Train/Tune/Serve/RLlib —
re-designed for TPU: a single-controller runtime orchestrates hosts while
JAX/XLA SPMD over `jax.sharding.Mesh` does all on-chip compute and ICI
collectives.

Subpackages are imported lazily so `import ray_tpu` stays light (no jax
import until the compute path is touched).
"""
from __future__ import annotations

import importlib

from .api import (init, shutdown, is_initialized, remote, get, put, wait,
                  kill, cancel, get_actor, free, cluster_resources,
                  available_resources, get_runtime_context, method, nodes,
                  timeline, get_tpu_ids, actor_exit)
from .core.object_ref import ObjectRef, ObjectRefGenerator
from .core.actor import ActorHandle
from . import exceptions

__version__ = "0.1.0"

_LAZY_SUBMODULES = ("data", "train", "tune", "serve", "rllib", "util",
                    "models", "ops", "parallel", "observability", "dag",
                    "workflow", "job_submission", "experimental")


def __getattr__(name):
    if name in _LAZY_SUBMODULES:
        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'ray_tpu' has no attribute {name!r}")


__all__ = [
    "init", "shutdown", "is_initialized", "remote", "get", "put", "wait",
    "kill", "cancel", "get_actor", "free", "cluster_resources",
    "available_resources", "get_runtime_context", "method", "nodes",
    "timeline", "get_tpu_ids", "actor_exit", "ObjectRef",
    "ObjectRefGenerator",
    "ActorHandle",
    "exceptions", "__version__", *_LAZY_SUBMODULES,
]
