"""Local multi-node test clusters (reference: ray.cluster_utils.Cluster).

Spins up a driver with a TCP listener plus N node agents as local
subprocesses — the same path real multi-host deployments use
(`python -m ray_tpu.core.node`), so tests and demos exercise true
cross-node scheduling and object transfer on one machine.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Dict, List, Optional


class Cluster:
    def __init__(self, *, initialize_head: bool = True, head_cpus: int = 4,
                 connect: bool = True, **_compat):
        self._agents: List[subprocess.Popen] = []
        self._rt = None
        if initialize_head:
            import ray_tpu
            self._rt = ray_tpu.init(num_cpus=head_cpus,
                                    listen="127.0.0.1:0")

    @property
    def address(self) -> Optional[str]:
        return getattr(self._rt, "tcp_address", None)

    def add_node(self, *, num_cpus: int = 2, num_tpus: int = 0,
                 resources: Optional[Dict[str, float]] = None,
                 env: Optional[Dict[str, str]] = None,
                 wait: bool = True, timeout: float = 30.0):
        """Start one node agent joined to the head; returns its node id
        once registered (wait=True)."""
        if self._rt is None:
            raise RuntimeError("cluster has no head (initialize_head=False)")
        before = set(self._rt.cluster_nodes)
        agent_env = dict(os.environ)
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        agent_env["PYTHONPATH"] = os.pathsep.join(
            [repo, *agent_env.get("PYTHONPATH", "").split(os.pathsep)])
        from .util.jaxenv import subprocess_env_cpu
        subprocess_env_cpu(agent_env)
        agent_env.update(env or {})
        cmd = [sys.executable, "-m", "ray_tpu.core.node", self.address,
               "--num-cpus", str(num_cpus)]
        if num_tpus:
            cmd += ["--num-tpus", str(num_tpus)]
        if resources:
            cmd += ["--resources", json.dumps(resources)]
        proc = subprocess.Popen(cmd, env=agent_env, cwd=repo)
        self._agents.append(proc)
        if not wait:
            return None
        deadline = time.time() + timeout
        while time.time() < deadline:
            new = set(self._rt.cluster_nodes) - before
            if new:
                return next(iter(new))
            if proc.poll() is not None:
                raise RuntimeError(
                    f"node agent exited rc={proc.returncode}")
            time.sleep(0.05)
        raise TimeoutError("node agent failed to register")

    def shutdown(self):
        import ray_tpu
        ray_tpu.shutdown()
        for p in self._agents:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        self._agents.clear()
        self._rt = None

    def __enter__(self) -> "Cluster":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()


__all__ = ["Cluster"]
