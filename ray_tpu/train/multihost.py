"""Multi-host SPMD: one JAX process per host, one global device mesh.

Reference counterpart: ray.train.torch's NCCL world
(python/ray/train/torch/config.py:_setup_torch_process_group — each
worker joins a process group keyed by master address / world size /
rank). TPU-first inversion: the world is `jax.distributed` — every host
process sees its local chips, `jax.devices()` is the GLOBAL device
list, and jitted programs span the whole mesh with XLA emitting the
cross-host collectives (ICI within a slice, DCN across slices). No
NCCL, no per-step communication code.

The runtime provides the process fabric: one `_SpmdHost` actor per host
(gang-placed via STRICT_SPREAD when `spread=True`); rank 0 picks the
coordinator endpoint on its own host, every rank joins the world, then
the gang runs the user's SPMD function. On this image the same
machinery is exercised with multiple CPU processes (Gloo collectives) —
the TPU pod deployment only changes the per-host device count.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional


class _SpmdHost:
    """Actor hosting one rank of the jax.distributed world."""

    def __init__(self, rank: int, world: int):
        self.rank = rank
        self.world = world

    def pick_coordinator(self) -> str:
        """Rank 0 chooses the coordinator endpoint ON ITS OWN HOST —
        the jax.distributed coordinator service runs inside rank 0's
        process, which with gang placement is NOT the driver's host."""
        from ..util.netutil import free_port, routable_ip
        return f"{routable_ip()}:{free_port()}"

    def join(self, coordinator: str) -> Dict[str, int]:
        """Blocks until every rank has joined the world. Called on all
        ranks concurrently (each actor has its own process)."""
        import jax
        jax.distributed.initialize(coordinator, num_processes=self.world,
                                   process_id=self.rank)
        return {"rank": self.rank, "world": self.world,
                "local_devices": jax.local_device_count(),
                "global_devices": jax.device_count()}

    def run(self, fn: Callable, *args, **kwargs) -> Any:
        return fn(self.rank, self.world, *args, **kwargs)


class MultiHostSpmd:
    """A gang of per-host JAX processes forming one distributed world.

    num_hosts: processes (= hosts on a pod; may share a host in tests).
    resources_per_host: what each rank's actor reserves (e.g.
        {"TPU": 4} so each rank owns its host's chips).
    env_per_host: env applied before the rank's first jax import —
        platform selection, XLA flags (CPU tests pass JAX_PLATFORMS=cpu
        + --xla_force_host_platform_device_count=N).
    spread: gang the ranks one-per-node via a STRICT_SPREAD placement
        group (requires that many alive nodes).
    """

    def __init__(self, num_hosts: int, *,
                 resources_per_host: Optional[Dict[str, float]] = None,
                 env_per_host: Optional[Dict[str, str]] = None,
                 spread: bool = False):
        import ray_tpu
        from ..api import remote
        self._ray = ray_tpu
        self.num_hosts = num_hosts
        self._pg = None
        if spread:
            from ..util.placement_group import placement_group
            self._pg = placement_group(
                [dict(resources_per_host or {"CPU": 1})] * num_hosts,
                strategy="STRICT_SPREAD")
            if not self._pg.wait(60):
                raise RuntimeError(
                    f"could not gang {num_hosts} hosts (placement group "
                    "not ready)")
        opts: Dict[str, Any] = {}
        res = dict(resources_per_host or {})
        opts["num_cpus"] = res.pop("CPU", 1)
        tpus = res.pop("TPU", 0)
        if tpus:
            opts["num_tpus"] = tpus
        if res:
            opts["resources"] = res
        if env_per_host:
            opts["runtime_env"] = {"env_vars": dict(env_per_host)}
        actor_cls = remote(**opts)(_SpmdHost)
        self.hosts: List[Any] = []
        for rank in range(num_hosts):
            a = actor_cls
            if self._pg is not None:
                a = actor_cls.options(placement_group=self._pg,
                                      bundle_index=rank)
            self.hosts.append(a.remote(rank, num_hosts))
        # Rank 0 picks the coordinator endpoint on its own host, then
        # every rank joins concurrently (the join barrier resolves once
        # all are in). Failures surface through these gets.
        self.coordinator = ray_tpu.get(
            self.hosts[0].pick_coordinator.remote(), timeout=120)
        descs = ray_tpu.get(
            [h.join.remote(self.coordinator) for h in self.hosts],
            timeout=180)
        self.world_devices = descs[0]["global_devices"]

    def run(self, fn: Callable, *args, **kwargs) -> List[Any]:
        """Execute fn(rank, world, *args) on every rank; returns results
        ordered by rank."""
        return self._ray.get(
            [h.run.remote(fn, *args, **kwargs) for h in self.hosts],
            timeout=600)

    def run_sharded(self, fn: Callable, per_rank_args: List[Any],
                    timeout: float = 600.0) -> List[Any]:
        """Execute fn(rank, world, shard) with a DIFFERENT payload per
        rank (multihost data loading: each host gets its batch shard).
        Shards ship as object refs, so each rank's worker pulls its
        share straight from the holding node over the transfer plane
        (core/object_transfer.py) — the driver only brokers locations,
        and per-step input bandwidth scales with the number of hosts
        instead of the single controller socket."""
        if len(per_rank_args) != self.num_hosts:
            raise ValueError(
                f"need one shard per rank: got {len(per_rank_args)} "
                f"for {self.num_hosts} hosts")
        refs = [self._ray.put(a) for a in per_rank_args]
        try:
            return self._ray.get(
                [h.run.remote(fn, r) for h, r in zip(self.hosts, refs)],
                timeout=timeout)
        finally:
            try:
                self._ray.free(refs)
            except Exception:
                pass

    def shutdown(self) -> None:
        for h in self.hosts:
            try:
                self._ray.kill(h)
            except Exception:
                pass
        if self._pg is not None:
            from ..util.placement_group import remove_placement_group
            try:
                remove_placement_group(self._pg)
            except Exception:
                pass
