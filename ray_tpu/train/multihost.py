"""Multi-host SPMD: one JAX process per host, one global device mesh.

Reference counterpart: ray.train.torch's NCCL world
(python/ray/train/torch/config.py:_setup_torch_process_group — each
worker joins a process group keyed by master address / world size /
rank). TPU-first inversion: the world is `jax.distributed` — every host
process sees its local chips, `jax.devices()` is the GLOBAL device
list, and jitted programs span the whole mesh with XLA emitting the
cross-host collectives (ICI within a slice, DCN across slices). No
NCCL, no per-step communication code.

The runtime provides the process fabric: one `_SpmdHost` actor per host
(gang-placed via STRICT_SPREAD when `spread=True`); rank 0 picks the
coordinator endpoint on its own host, every rank joins the world, then
the gang runs the user's SPMD function. On this image the same
machinery is exercised with multiple CPU processes (Gloo collectives) —
the TPU pod deployment only changes the per-host device count.

Elastic mode (`supervised=True`, train/elastic.py): a GangSupervisor
watches every rank's GCS actor state; when a rank dies (preempted host,
OOM-killed worker), `reform()` tears down the doomed jax.distributed
world — killing the remaining rank processes is the clean teardown:
survivors are parked inside collectives that can never complete — and
re-gangs under a bumped GENERATION: at full size when the cluster has
replacement capacity, otherwise resharded onto the surviving world.
Stale ranks of the old generation are fenced out of collectives like
PR-4 node incarnations.
"""
from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence


class _SpmdHost:
    """Actor hosting one rank of the jax.distributed world."""

    def __init__(self, rank: int, world: int, generation: int = 0):
        self.rank = rank
        self.world = world
        self.generation = generation

    def ping(self) -> Dict[str, int]:
        return {"rank": self.rank, "world": self.world,
                "generation": self.generation, "pid": os.getpid()}

    def pick_coordinator(self) -> str:
        """Rank 0 chooses the coordinator endpoint ON ITS OWN HOST —
        the jax.distributed coordinator service runs inside rank 0's
        process, which with gang placement is NOT the driver's host."""
        from ..util.netutil import free_port, routable_ip
        return f"{routable_ip()}:{free_port()}"

    def join(self, coordinator: str) -> Dict[str, int]:
        """Blocks until every rank has joined the world. Called on all
        ranks concurrently (each actor has its own process)."""
        import jax
        if (os.environ.get("JAX_PLATFORMS") or "").startswith("cpu"):
            # CPU cross-process worlds need an explicit collectives
            # implementation or every multi-process computation fails
            # with "Multiprocess computations aren't implemented on the
            # CPU backend"; must be set BEFORE the backend is created
            # (the env var alone is not read by this jax version).
            impl = os.environ.get(
                "JAX_CPU_COLLECTIVES_IMPLEMENTATION", "gloo")
            try:
                jax.config.update(
                    "jax_cpu_collectives_implementation", impl)
            except Exception:  # noqa: BLE001 — older/newer jax: best effort
                pass
        jax.distributed.initialize(coordinator, num_processes=self.world,
                                   process_id=self.rank)
        return {"rank": self.rank, "world": self.world,
                "local_devices": jax.local_device_count(),
                "global_devices": jax.device_count()}

    def run(self, fn: Callable, *args, **kwargs) -> Any:
        return fn(self.rank, self.world, *args, **kwargs)


class MultiHostSpmd:
    """A gang of per-host JAX processes forming one distributed world.

    num_hosts: requested processes (= hosts on a pod; may share a host
        in tests). `world_size` is the CURRENT gang size — it equals
        num_hosts until a supervised gang reforms resharded.
    resources_per_host: what each rank's actor reserves (e.g.
        {"TPU": 4} so each rank owns its host's chips).
    env_per_host: env applied before the rank's first jax import —
        platform selection, XLA flags (CPU tests pass JAX_PLATFORMS=cpu
        + --xla_force_host_platform_device_count=N).
    spread: gang the ranks one-per-node via a STRICT_SPREAD placement
        group (requires that many alive nodes).
    supervised: start a GangSupervisor (train/elastic.py) that detects
        a dead rank within ~RAY_TPU_GANG_PROBE_S and arms `reform()`.
    collective_groups: names of util.collective groups whose rendezvous
        actors should learn about rank deaths (parked rounds then fail
        with CollectiveRankDiedError) and generation bumps.
    """

    def __init__(self, num_hosts: int, *,
                 resources_per_host: Optional[Dict[str, float]] = None,
                 env_per_host: Optional[Dict[str, str]] = None,
                 spread: bool = False,
                 supervised: bool = False,
                 collective_groups: Sequence[str] = (),
                 pg_timeout: float = 60.0,
                 _host_cls: Optional[type] = None):
        import ray_tpu
        self._ray = ray_tpu
        self.num_hosts = num_hosts
        self.world_size = 0
        self.generation = 0
        self._resources_per_host = dict(resources_per_host or {})
        self._env_per_host = dict(env_per_host or {})
        self._spread = spread
        self._supervised = supervised
        self._collective_groups = tuple(collective_groups)
        self._pg_timeout = pg_timeout
        self._host_cls = _host_cls or _SpmdHost
        self._pg = None
        self._supervisor = None
        self.hosts: List[Any] = []
        self._gang_up(num_hosts)
        if supervised:
            self._start_supervisor()

    # ------------------------------------------------------------------
    # construction / teardown
    # ------------------------------------------------------------------
    def _actor_cls(self):
        from ..api import remote
        opts: Dict[str, Any] = {}
        res = dict(self._resources_per_host)
        opts["num_cpus"] = res.pop("CPU", 1)
        tpus = res.pop("TPU", 0)
        if tpus:
            opts["num_tpus"] = tpus
        if res:
            opts["resources"] = res
        if self._env_per_host:
            opts["runtime_env"] = {"env_vars": dict(self._env_per_host)}
        return remote(**opts)(self._host_cls)

    def _gang_up(self, world: int) -> None:
        """Spawn `world` rank actors, gang-place them, and join the
        jax.distributed world. Failure anywhere (placement timeout, a
        rank crashing in join) kills every already-spawned actor and
        removes the placement group — a failed gang must not leak its
        partially-built world."""
        actor_cls = self._actor_cls()
        pg = None
        hosts: List[Any] = []
        try:
            if self._spread:
                from ..util.placement_group import placement_group
                pg = placement_group(
                    [dict(self._resources_per_host or {"CPU": 1})] * world,
                    strategy="STRICT_SPREAD")
                if not pg.wait(self._pg_timeout):
                    raise RuntimeError(
                        f"could not gang {world} hosts (placement group "
                        "not ready)")
            for rank in range(world):
                a = actor_cls
                if pg is not None:
                    a = actor_cls.options(placement_group=pg,
                                          bundle_index=rank)
                hosts.append(a.remote(rank, world, self.generation))
            # Rank 0 picks the coordinator endpoint on its own host, then
            # every rank joins concurrently (the join barrier resolves once
            # all are in). Failures surface through these gets.
            coordinator = self._ray.get(
                hosts[0].pick_coordinator.remote(), timeout=120)
            descs = self._ray.get(
                [h.join.remote(coordinator) for h in hosts],
                timeout=180)
        except BaseException:
            self._teardown_actors(hosts, pg)
            raise
        self.hosts = hosts
        self._pg = pg
        self.coordinator = coordinator
        self.world_size = world
        self.world_devices = descs[0]["global_devices"]

    def _teardown_actors(self, hosts, pg) -> None:
        for h in hosts:
            try:
                self._ray.kill(h)
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
        if pg is not None:
            from ..util.placement_group import remove_placement_group
            try:
                remove_placement_group(pg)
            except Exception:  # noqa: BLE001
                pass

    def _start_supervisor(self) -> None:
        from .elastic import GangSupervisor
        members = {rank: h.actor_id for rank, h in enumerate(self.hosts)}
        self._supervisor = GangSupervisor(
            members, generation=self.generation,
            collective_groups=self._collective_groups)

    # ------------------------------------------------------------------
    # supervision surface
    # ------------------------------------------------------------------
    @property
    def failure(self):
        """First RankDeath seen by the supervisor (None while healthy)."""
        return self._supervisor.first_death if self._supervisor else None

    def wait_failure(self, timeout: Optional[float] = None):
        """Block until a rank dies (or timeout); returns the RankDeath."""
        if self._supervisor is None:
            raise RuntimeError("gang is not supervised "
                               "(pass supervised=True)")
        return self._supervisor.wait(timeout)

    # ------------------------------------------------------------------
    # reform
    # ------------------------------------------------------------------
    def _fits(self, world: int, need: Dict[str, float]) -> bool:
        avail = self._ray.available_resources()
        for r, v in need.items():
            if v and avail.get(r, 0.0) + 1e-9 < v * world:
                return False
        if self._spread:
            alive = sum(1 for n in self._ray.nodes() if n.get("alive"))
            if alive < world:
                return False
        return True

    def _feasible_world(self, target: int, replace_deadline: float,
                        deadline: float) -> int:
        """Largest world the cluster can hold: wait up to the replace
        window for FULL capacity (a replacement host may be seconds from
        freeing/rejoining), then settle for the largest feasible size,
        polling until the reform deadline before giving up."""
        need = dict(self._resources_per_host)
        need.setdefault("CPU", 1)
        while time.monotonic() < replace_deadline:
            if self._fits(target, need):
                return target
            time.sleep(0.1)
        while time.monotonic() < deadline:
            for k in range(target, 0, -1):
                if self._fits(k, need):
                    return k
            time.sleep(0.25)
        return 0

    def reform(self, *, timeout: Optional[float] = None,
               min_hosts: int = 1) -> Dict[str, Any]:
        """Tear down the current (doomed) world and re-gang.

        Killing every rank process IS the clean teardown of the
        jax.distributed world: surviving ranks are parked inside
        collectives that can never complete, and a fresh world needs
        fresh processes anyway (jax.distributed binds once per
        process). The gang comes back at full size when the cluster has
        capacity for `num_hosts` ranks within RAY_TPU_GANG_REPLACE_WAIT_S,
        otherwise RESHARDED onto the largest feasible world (>=
        min_hosts). Collective groups are advanced to the new
        generation first, so zombie ranks of the old world fence out
        instead of corrupting the new world's rounds.

        Returns {"world_size", "generation", "resharded", "deaths"}.
        Raises GangReformError when nothing >= min_hosts fits within
        RAY_TPU_GANG_REFORM_TIMEOUT_S (or `timeout`).
        """
        from ..exceptions import GangReformError
        from ..util import events
        from ..util.collective import advance_group_generation
        from .elastic import reform_timeout_s, replace_wait_s

        t0 = time.monotonic()
        budget = timeout if timeout is not None else reform_timeout_s()
        deadline = t0 + budget
        deaths = []
        if self._supervisor is not None:
            deaths = list(self._supervisor.deaths)
            self._supervisor.stop()
            self._supervisor = None
        old_world = self.world_size
        self._teardown_actors(self.hosts, self._pg)
        self.hosts = []
        self._pg = None
        self.generation += 1

        replace_deadline = min(deadline, t0 + replace_wait_s())
        world = self._feasible_world(self.num_hosts, replace_deadline,
                                     deadline)
        if world < max(min_hosts, 1):
            raise GangReformError(
                f"gang reform failed: no feasible world >= "
                f"{max(min_hosts, 1)} hosts within {budget:.0f}s "
                f"(requested {self.num_hosts}, last world {old_world})")
        resharded = world < self.num_hosts
        for g in self._collective_groups:
            advance_group_generation(g, self.generation, world)
        try:
            self._gang_up(world)
        except BaseException as e:
            raise GangReformError(
                f"gang reform failed re-ganging {world} hosts "
                f"(generation {self.generation}): {e!r}") from e
        if self._supervised:
            self._start_supervisor()
        took = time.monotonic() - t0
        kind = "resharded" if resharded else "replaced"
        events.emit_safe(
            "train.gang.reform",
            f"gang reformed ({kind}) {old_world} -> {world} ranks in "
            f"{took:.2f}s", counter="ray_tpu_train_gang_reforms_total",
            counter_tags={"kind": kind},
            old_world=str(old_world), world=str(world),
            generation=str(self.generation), seconds=f"{took:.3f}")
        if resharded:
            events.emit_safe(
                "train.gang.reshard",
                f"no replacement capacity for {self.num_hosts} ranks; "
                f"gang resharded onto the surviving world ({world} "
                "ranks, dp axis shrunk)",
                world=str(world), requested=str(self.num_hosts),
                generation=str(self.generation))
        return {"world_size": world, "generation": self.generation,
                "resharded": resharded, "seconds": took,
                "deaths": [(d.rank, d.cause) for d in deaths]}

    # ------------------------------------------------------------------
    # execution
    # ------------------------------------------------------------------
    def run(self, fn: Callable, *args, **kwargs) -> List[Any]:
        """Execute fn(rank, world, *args) on every rank; returns results
        ordered by rank."""
        return self._ray.get(self.run_async(fn, *args, **kwargs),
                             timeout=600)

    def run_async(self, fn: Callable, *args, **kwargs) -> List[Any]:
        """Submit fn(rank, world, *args) on every rank; returns the
        per-rank refs (the elastic fit loop waits on these alongside
        the supervisor's failure signal)."""
        return [h.run.remote(fn, *args, **kwargs) for h in self.hosts]

    def run_sharded(self, fn: Callable, per_rank_args: List[Any],
                    timeout: float = 600.0) -> List[Any]:
        """Execute fn(rank, world, shard) with a DIFFERENT payload per
        rank (multihost data loading: each host gets its batch shard).
        Shards ship as object refs, so each rank's worker pulls its
        share straight from the holding node over the transfer plane
        (core/object_transfer.py) — the driver only brokers locations,
        and per-step input bandwidth scales with the number of hosts
        instead of the single controller socket."""
        if len(per_rank_args) != self.world_size:
            raise ValueError(
                f"need one shard per rank: got {len(per_rank_args)} "
                f"for {self.world_size} hosts")
        refs = [self._ray.put(a) for a in per_rank_args]
        try:
            return self._ray.get(
                [h.run.remote(fn, r) for h, r in zip(self.hosts, refs)],
                timeout=timeout)
        finally:
            try:
                self._ray.free(refs)
            except Exception:
                pass

    def shutdown(self) -> None:
        if self._supervisor is not None:
            self._supervisor.stop()
            self._supervisor = None
        self._teardown_actors(self.hosts, self._pg)
        self.hosts = []
        self._pg = None
