"""Per-worker training session.

Reference parity: python/ray/train/_internal/session.py +
python/ray/train/context.py — `train.report(...)`, `train.get_context()`
with rank/world info, checkpoint handoff.

Inside a train worker, `report` ships metrics (and optionally a checkpoint
path) to the trainer supervisor over the runtime's out-of-band report
channel; on the driver (local mode) it appends directly.
"""
from __future__ import annotations

import dataclasses
import threading
from typing import Any, Dict, Optional

_session_lock = threading.Lock()
_session: Optional["TrainSession"] = None


@dataclasses.dataclass
class TrainContext:
    world_size: int = 1
    world_rank: int = 0
    local_rank: int = 0
    trial_name: str = ""
    experiment_name: str = ""

    def get_world_size(self) -> int:
        return self.world_size

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_local_rank(self) -> int:
        return self.local_rank


class TrainSession:
    def __init__(self, context: TrainContext, report_fn):
        self.context = context
        self._report_fn = report_fn
        self.iteration = 0
        self._last_report_t: Optional[float] = None

    def _record_builtin_metrics(self, metrics: Dict[str, Any]) -> None:
        """Mirror the loop's cadence and well-known throughput keys onto
        the registry — these ship to the driver's exposition over the
        worker telemetry channel, giving bench.py a driver-captured
        source for step-time / tokens/s / MFU artifacts. Never raises."""
        import time  # noqa: PLC0415
        try:
            from ..util import metrics_catalog as mcat  # noqa: PLC0415
            now = time.perf_counter()
            if self._last_report_t is not None:
                mcat.get("ray_tpu_train_step_time_s").observe(
                    now - self._last_report_t)
            self._last_report_t = now
            mcat.get("ray_tpu_train_reports_total").inc()
            for key, gauge in (("tokens_per_s",
                                "ray_tpu_train_tokens_per_s"),
                               ("mfu", "ray_tpu_train_mfu")):
                v = metrics.get(key)
                if isinstance(v, (int, float)):
                    mcat.get(gauge).set(float(v))
        except Exception:
            pass

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Any] = None) -> None:
        self.iteration += 1
        self._record_builtin_metrics(metrics)
        payload = {"metrics": dict(metrics), "iteration": self.iteration,
                   "rank": self.context.world_rank}
        if checkpoint is not None:
            payload["checkpoint"] = getattr(checkpoint, "path", checkpoint)
        self._report_fn(payload)


def init_session(context: TrainContext, report_fn) -> TrainSession:
    global _session
    with _session_lock:
        _session = TrainSession(context, report_fn)
    return _session


def clear_session() -> None:
    global _session
    with _session_lock:
        _session = None


def get_session() -> TrainSession:
    if _session is None:
        raise RuntimeError(
            "No active train session — report()/get_context() must run "
            "inside a training function launched by a Trainer")
    return _session


def report(metrics: Dict[str, Any], checkpoint=None) -> None:
    get_session().report(metrics, checkpoint)


def get_context() -> TrainContext:
    return get_session().context
