"""Checkpointing: orbax-backed sharded state + a directory-based Checkpoint
handle.

Reference parity: python/ray/train/_checkpoint.py (Checkpoint.from_directory
/ to_directory / as_directory) and torch state_dict saving; here the heavy
path is orbax — each host writes its own shards of a NamedSharding'd
TrainState, and restore re-shards onto the (possibly different) mesh.
"""
from __future__ import annotations

import json
import os
import shutil
import time
from typing import Any, Dict, Optional


class Checkpoint:
    """A handle to a checkpoint directory (metrics sidecar + orbax state)."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @staticmethod
    def from_directory(path: str) -> "Checkpoint":
        return Checkpoint(path)

    def as_directory(self) -> str:
        return self.path

    def to_directory(self, dest: str) -> str:
        if os.path.abspath(dest) != self.path:
            shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    def metadata(self) -> Dict[str, Any]:
        meta = os.path.join(self.path, META_NAME)
        if os.path.exists(meta):
            with open(meta) as f:
                return json.load(f)
        return {}

    def __repr__(self):
        return f"Checkpoint({self.path})"


#: committed checkpoints carry this meta sidecar; it is written INSIDE
#: the tmp- staging dir before the atomic rename, so its presence in a
#: `checkpoint_*` directory == the save committed. Torn saves leave only
#: an uncommitted `tmp-*` sibling (or a meta-less dir from pre-atomic
#: writers) that latest()/_prune() never select.
META_NAME = "ckpt_meta.json"
_TMP_PREFIX = "tmp-"
_OLD_PREFIX = _TMP_PREFIX + "old-"


def is_committed(path: str) -> bool:
    """True when `path` is a fully committed checkpoint directory."""
    return (os.path.isdir(path)
            and not os.path.basename(path).startswith(_TMP_PREFIX)
            and os.path.exists(os.path.join(path, META_NAME)))


def save_pytree(state: Any, path: str, *, step: Optional[int] = None,
                metadata: Optional[Dict[str, Any]] = None) -> Checkpoint:
    """Save a (possibly sharded) pytree with orbax; blocking.

    Crash-safe commit protocol: the state is written to a `tmp-` sibling
    in the same directory, the meta sidecar is fsynced, and one atomic
    rename publishes the checkpoint. A crash at ANY instant leaves either
    the previous committed checkpoint intact or the new one committed —
    never a torn directory that latest() would select (the old code
    rmtree'd the destination first, so a crash mid-save destroyed the
    checkpoint it was replacing)."""
    import uuid

    path = os.path.abspath(path)
    parent, base = os.path.split(path)
    os.makedirs(parent, exist_ok=True)
    tmp = os.path.join(parent, f"{_TMP_PREFIX}{base}-{uuid.uuid4().hex[:8]}")
    ckptr = _checkpointer()
    ckptr.save(tmp, state)
    meta = dict(metadata or {})
    meta.update({"step": step, "saved_at": time.time()})
    meta_path = os.path.join(tmp, META_NAME)
    with open(meta_path, "w") as f:
        json.dump(meta, f)
        f.flush()
        os.fsync(f.fileno())
    old = None
    if os.path.exists(path):
        # The previous checkpoint at this exact path slides aside first
        # (rename over a non-empty dir is not atomic); it is reclaimed
        # only after the new one is committed. A crash BETWEEN the two
        # renames leaves it under the tmp-old- name with its meta intact
        # — _recover_slide_aside promotes it back on the next latest()/
        # prune, so the "committed checkpoint at any instant" invariant
        # holds across the overwrite window too.
        old = os.path.join(parent,
                           f"{_OLD_PREFIX}{base}-{uuid.uuid4().hex[:8]}")
        os.rename(path, old)
    os.rename(tmp, path)                       # the commit point
    _fsync_dir(parent)
    if old is not None:
        shutil.rmtree(old, ignore_errors=True)
    return Checkpoint(path)


def _checkpointer():
    """A PyTree checkpointer whose barriers never span processes: in a
    multi-process jax world the default orbax Checkpointer.save runs
    `sync_global_processes` barriers that expect EVERY process to call
    save — but the elastic trainer commits from rank 0 only (state is
    replicated), so a cross-process barrier would deadlock the gang
    (observed: 30 s gloo rendezvous timeout killing the whole world).
    Scoping active_processes to the caller keeps the save local."""
    import orbax.checkpoint as ocp
    try:
        import jax
        if jax.process_count() > 1:
            me = jax.process_index()
            return ocp.Checkpointer(
                ocp.PyTreeCheckpointHandler(),
                multiprocessing_options=ocp.options.MultiprocessingOptions(
                    primary_host=me, active_processes={me},
                    barrier_sync_key_prefix=f"rtpu-p{me}"))
    except Exception:  # noqa: BLE001 — orbax/jax API drift: default path
        pass
    return ocp.PyTreeCheckpointer()


def _recover_slide_aside(root: str) -> None:
    """Undo a crash caught between save_pytree's two overwrite renames:
    the previously committed checkpoint sits under tmp-old-<base>-<id>
    (meta intact) with nothing at <base> — promote it back. Only safe
    to run from the committing process or after the saver is known dead
    (the elastic trainer's single-writer rank-0 discipline): promoting
    mid-save would collide with the saver's final rename."""
    try:
        entries = os.listdir(root)
    except OSError:
        return
    for d in entries:
        if not d.startswith(_OLD_PREFIX):
            continue
        base = d[len(_OLD_PREFIX):].rsplit("-", 1)[0]
        target = os.path.join(root, base)
        src = os.path.join(root, d)
        if not os.path.exists(target) \
                and os.path.exists(os.path.join(src, META_NAME)):
            try:
                os.rename(src, target)
            except OSError:
                pass    # a concurrent promote/save won the race


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:
        pass


def restore_pytree(path: str, *, target: Any = None,
                   shardings: Any = None) -> Any:
    """Restore a pytree; with `shardings` (pytree of NamedSharding) leaves
    are placed directly onto the mesh (no host-side full copy)."""
    import orbax.checkpoint as ocp
    ckptr = ocp.PyTreeCheckpointer()
    if shardings is not None:
        import jax
        restore_args = jax.tree_util.tree_map(
            lambda s: ocp.ArrayRestoreArgs(sharding=s), shardings)
        return ckptr.restore(path, item=target, restore_args=restore_args)
    return ckptr.restore(path, item=target)


class CheckpointManager:
    """Rotating checkpoint directory (num_to_keep)."""

    def __init__(self, root: str, num_to_keep: Optional[int] = 2):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.num_to_keep = num_to_keep

    def save(self, state: Any, step: int,
             metadata: Optional[Dict[str, Any]] = None) -> Checkpoint:
        path = os.path.join(self.root, f"checkpoint_{step:09d}")
        ckpt = save_pytree(state, path, step=step, metadata=metadata)
        self._prune()
        return ckpt

    def _committed(self):
        return sorted(d for d in os.listdir(self.root)
                      if d.startswith("checkpoint_")
                      and is_committed(os.path.join(self.root, d)))

    def latest(self) -> Optional[Checkpoint]:
        """Newest COMMITTED checkpoint; torn saves (a crash mid-save
        leaves a tmp- sibling or a meta-less directory) never selected.
        A checkpoint caught mid-overwrite by a crash is promoted back
        from its slide-aside name first."""
        _recover_slide_aside(self.root)
        entries = self._committed()
        if not entries:
            return None
        return Checkpoint(os.path.join(self.root, entries[-1]))

    # staging dirs older than this are crash leftovers; younger ones may
    # be a concurrent save still writing, so they are left alone
    TMP_TTL_S = 3600.0

    def _prune(self):
        _recover_slide_aside(self.root)
        # abandoned tmp- staging dirs from crashed saves are garbage
        now = time.time()
        for d in os.listdir(self.root):
            p = os.path.join(self.root, d)
            if d.startswith(_TMP_PREFIX):
                try:
                    age = now - os.path.getmtime(p)
                except OSError:
                    continue
                if age > self.TMP_TTL_S:
                    shutil.rmtree(p, ignore_errors=True)
        if self.num_to_keep is None:
            return
        for d in self._committed()[:-self.num_to_keep]:
            shutil.rmtree(os.path.join(self.root, d), ignore_errors=True)
