"""Checkpointing: orbax-backed sharded state + a directory-based Checkpoint
handle.

Reference parity: python/ray/train/_checkpoint.py (Checkpoint.from_directory
/ to_directory / as_directory) and torch state_dict saving; here the heavy
path is orbax — each host writes its own shards of a NamedSharding'd
TrainState, and restore re-shards onto the (possibly different) mesh.
"""
from __future__ import annotations

import json
import os
import shutil
import time
from typing import Any, Dict, Optional


class Checkpoint:
    """A handle to a checkpoint directory (metrics sidecar + orbax state)."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @staticmethod
    def from_directory(path: str) -> "Checkpoint":
        return Checkpoint(path)

    def as_directory(self) -> str:
        return self.path

    def to_directory(self, dest: str) -> str:
        if os.path.abspath(dest) != self.path:
            shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    def metadata(self) -> Dict[str, Any]:
        meta = os.path.join(self.path, "ckpt_meta.json")
        if os.path.exists(meta):
            with open(meta) as f:
                return json.load(f)
        return {}

    def __repr__(self):
        return f"Checkpoint({self.path})"


def save_pytree(state: Any, path: str, *, step: Optional[int] = None,
                metadata: Optional[Dict[str, Any]] = None) -> Checkpoint:
    """Save a (possibly sharded) pytree with orbax; blocking."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    if os.path.exists(path):
        shutil.rmtree(path)
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(path, state)
    meta = dict(metadata or {})
    meta.update({"step": step, "saved_at": time.time()})
    with open(os.path.join(path, "ckpt_meta.json"), "w") as f:
        json.dump(meta, f)
    return Checkpoint(path)


def restore_pytree(path: str, *, target: Any = None,
                   shardings: Any = None) -> Any:
    """Restore a pytree; with `shardings` (pytree of NamedSharding) leaves
    are placed directly onto the mesh (no host-side full copy)."""
    import orbax.checkpoint as ocp
    ckptr = ocp.PyTreeCheckpointer()
    if shardings is not None:
        import jax
        restore_args = jax.tree_util.tree_map(
            lambda s: ocp.ArrayRestoreArgs(sharding=s), shardings)
        return ckptr.restore(path, item=target, restore_args=restore_args)
    return ckptr.restore(path, item=target)


class CheckpointManager:
    """Rotating checkpoint directory (num_to_keep)."""

    def __init__(self, root: str, num_to_keep: Optional[int] = 2):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.num_to_keep = num_to_keep

    def save(self, state: Any, step: int,
             metadata: Optional[Dict[str, Any]] = None) -> Checkpoint:
        path = os.path.join(self.root, f"checkpoint_{step:09d}")
        ckpt = save_pytree(state, path, step=step, metadata=metadata)
        self._prune()
        return ckpt

    def latest(self) -> Optional[Checkpoint]:
        entries = sorted(d for d in os.listdir(self.root)
                         if d.startswith("checkpoint_"))
        if not entries:
            return None
        return Checkpoint(os.path.join(self.root, entries[-1]))

    def _prune(self):
        if self.num_to_keep is None:
            return
        entries = sorted(d for d in os.listdir(self.root)
                         if d.startswith("checkpoint_"))
        for d in entries[:-self.num_to_keep]:
            shutil.rmtree(os.path.join(self.root, d), ignore_errors=True)
