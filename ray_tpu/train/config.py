"""Training configuration dataclasses.

Reference parity: python/ray/air/config.py (ScalingConfig, RunConfig,
FailureConfig, CheckpointConfig). TPU-first twist: ScalingConfig speaks in
hosts and a MeshSpec instead of `num_workers` GPU processes — one worker
per host, all chips driven by one SPMD program.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, Optional

from ..parallel.mesh import MeshSpec


@dataclasses.dataclass
class ScalingConfig:
    """How to scale the job across hosts/chips.

    num_workers: worker actors (== participating hosts). On a single host
      this is 1: the SPMD program inside it drives every local chip.
    mesh: MeshSpec for the global device mesh (dp/fsdp/tp/sp/ep/pp).
    use_tpu: claim the TPU in the worker (False -> CPU jax, for tests).
    resources_per_worker: extra custom resources per worker actor.
    """
    num_workers: int = 1
    mesh: Optional[MeshSpec] = None
    use_tpu: bool = True
    resources_per_worker: Optional[Dict[str, float]] = None

    def worker_resources(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker or {})
        res.setdefault("CPU", 1.0)
        if self.use_tpu:
            res.setdefault("TPU", 1.0)
        return res


@dataclasses.dataclass
class FailureConfig:
    max_failures: int = 0          # worker-group restarts before giving up


@dataclasses.dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = 2
    checkpoint_frequency: int = 0  # steps between automatic checkpoints


@dataclasses.dataclass
class RunConfig:
    name: str = "ray_tpu_run"
    storage_path: Optional[str] = None
    failure_config: FailureConfig = dataclasses.field(
        default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = dataclasses.field(
        default_factory=CheckpointConfig)
    verbose: int = 1
    # tune: stop condition (dict | callable | Stopper) and lifecycle
    # callbacks (reference: air.RunConfig(stop=..., callbacks=[...]))
    stop: Optional[Any] = None
    callbacks: Optional[list] = None

    def run_dir(self) -> str:
        base = self.storage_path or os.path.join(
            os.path.expanduser("~"), "ray_tpu_results")
        path = os.path.join(base, self.name)
        os.makedirs(path, exist_ok=True)
        return path
