"""The SPMD train step: one jitted function over the whole mesh.

This is the TPU replacement for the reference's entire DDP/FSDP/NCCL layer
(python/ray/train/torch/config.py:_setup_torch_process_group and the
per-step allreduce hooks): state lives sharded via NamedSharding, the step
is jitted with explicit in/out shardings, and XLA inserts psum over `dp`,
reduce-scatter/all-gather over `fsdp`, and tensor collectives over `tp`.
Nothing in the loop does explicit communication.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import MeshSpec, build_mesh
from ..parallel.sharding import (ShardingRules, sharding_tree, shard_pytree,
                                 batch_sharding, replicated)


@flax.struct.dataclass
class TrainState:
    step: jax.Array
    params: Any
    opt_state: Any

    @staticmethod
    def create(params, tx: optax.GradientTransformation) -> "TrainState":
        return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                          opt_state=tx.init(params))


def next_token_loss(apply_fn: Callable, params, batch: Dict[str, jax.Array]):
    """Causal LM loss. batch: {"tokens": (B,S)} or {"inputs","targets"}.
    Optional "loss_mask" zeroes out padding/prompt positions."""
    if "inputs" in batch:
        inputs, targets = batch["inputs"], batch["targets"]
    else:
        inputs, targets = batch["tokens"][:, :-1], batch["tokens"][:, 1:]
    out = apply_fn({"params": params}, inputs)
    logits = out[0] if isinstance(out, tuple) else out
    logits = logits.astype(jnp.float32)
    # fused cross-entropy: logit[target] - logsumexp instead of a full
    # (B,S,V) fp32 log_softmax + gather — at flagship shapes the logp
    # array alone is ~1 GB of HBM the MXU then waits on
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    ll = jnp.take_along_axis(logits, targets[..., None],
                             axis=-1)[..., 0] - lse
    mask = batch.get("loss_mask")
    if mask is None:
        mask = jnp.ones_like(ll)
    else:
        mask = mask.astype(jnp.float32)
    denom = jnp.maximum(mask.sum(), 1.0)
    loss = -(ll * mask).sum() / denom
    ntokens = denom
    return loss, {"loss": loss, "ntokens": ntokens,
                  "ppl": jnp.exp(jnp.minimum(loss, 20.0))}


@dataclasses.dataclass
class SpmdStep:
    """Compiled train step + the shardings it expects."""
    step_fn: Callable[[TrainState, Dict[str, jax.Array]],
                      Tuple[TrainState, Dict[str, jax.Array]]]
    mesh: Mesh
    state_shardings: Any
    batch_shardings: Any

    def __call__(self, state, batch):
        return self.step_fn(state, batch)


def make_train_step(model, tx: optax.GradientTransformation, mesh: Mesh,
                    *, loss_fn: Optional[Callable] = None,
                    rules: Optional[ShardingRules] = None,
                    donate_state: bool = True,
                    accum_steps: int = 1) -> Callable:
    """Build the jitted SPMD step for `model` on `mesh`.

    Returns init_fn; calling init_fn(rng, example_batch) produces
    (TrainState sharded onto the mesh, SpmdStep compiled step).

    accum_steps > 1 enables gradient accumulation INSIDE the jitted
    step: the batch's leading dim splits into `accum_steps`
    micro-batches run under lax.scan (activation memory scales with the
    micro-batch, the fit-big-models knob on one 16 GB chip); gradients
    accumulate in fp32 and one optimizer update applies at the end —
    numerically a large-batch step, not accum_steps small ones.
    """
    loss_fn = loss_fn or partial(next_token_loss, model.apply)

    def _value_and_grad(params, batch):
        return jax.value_and_grad(
            lambda p: loss_fn(p, batch), has_aux=True)(params)

    def raw_step(state: TrainState, batch):
        from ..parallel.sharding import activation_mesh  # noqa: PLC0415
        with activation_mesh(mesh):
            if accum_steps <= 1:
                (_loss, metrics), grads = _value_and_grad(state.params,
                                                          batch)
            else:
                micro = jax.tree_util.tree_map(
                    lambda x: x.reshape(
                        (accum_steps, x.shape[0] // accum_steps)
                        + x.shape[1:]), batch)
                zeros = jax.tree_util.tree_map(
                    lambda p: jnp.zeros(p.shape, jnp.float32),
                    state.params)

                # Each micro-batch's loss is its own masked mean, so
                # micro-grads are weighted by TOKEN COUNT (ntokens) and
                # normalized once by the total — exactly the full-batch
                # masked mean even when mask counts differ across
                # micro-batches (r4 advice: equal weighting diverges).
                # Custom loss_fns without "ntokens" weight uniformly.
                def body(carry, mb):
                    gsum, toksum = carry
                    (_l, m), g = _value_and_grad(state.params, mb)
                    nt = m.get("ntokens", jnp.float32(1.0)) \
                        if isinstance(m, dict) else jnp.float32(1.0)
                    nt = jnp.asarray(nt, jnp.float32)
                    gsum = jax.tree_util.tree_map(
                        lambda a, b: a + b.astype(jnp.float32) * nt,
                        gsum, g)
                    return (gsum, toksum + nt), m

                (gsum, toksum), ms = jax.lax.scan(
                    body, (zeros, jnp.float32(0.0)), micro)
                grads = jax.tree_util.tree_map(
                    lambda g, p: (g / jnp.maximum(toksum, 1.0)
                                  ).astype(p.dtype),
                    gsum, state.params)
                # metrics: token-weighted means (ntokens itself sums);
                # ppl recomputed from the aggregated loss
                nts = ms.get("ntokens") if isinstance(ms, dict) else None
                w = (nts / jnp.maximum(nts.sum(), 1.0)
                     if nts is not None
                     else jnp.full((accum_steps,), 1.0 / accum_steps))

                def wmean(x):
                    # broadcast w over trailing dims: non-scalar metric
                    # leaves (e.g. a (C,) per-class vector) stack to
                    # (accum_steps, C) and need w as (accum_steps, 1)
                    wb = w.reshape((-1,) + (1,) * (x.ndim - 1))
                    return (x * wb).sum(axis=0)

                metrics = jax.tree_util.tree_map(wmean, ms)
                if isinstance(metrics, dict):
                    if nts is not None:
                        metrics["ntokens"] = nts.sum()
                    if "ppl" in metrics and "loss" in metrics:
                        metrics["ppl"] = jnp.exp(
                            jnp.minimum(metrics["loss"], 20.0))
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        metrics = dict(metrics)
        metrics["grad_norm"] = optax.global_norm(grads)
        return TrainState(step=state.step + 1, params=new_params,
                          opt_state=new_opt), metrics

    def init_fn(rng, example_batch) -> Tuple[TrainState, SpmdStep]:
        tokens = example_batch.get("tokens",
                                   example_batch.get("inputs"))
        # Abstract init -> shardings -> real sharded init (params are born
        # sharded; no host-side full copy of an 8B model).
        def _init(rng):
            params = model.init(rng, tokens[:1, :8])["params"]
            return TrainState.create(params, tx)

        abstract = jax.eval_shape(_init, rng)
        state_sh = jax.tree_util.tree_map_with_path(
            lambda path, leaf: _state_leaf_sharding(path, leaf, mesh, rules),
            abstract)
        # partitionable threefry makes the sharded init draw the SAME
        # bits as an unsharded one: with the legacy (non-partitionable)
        # impl, jit(out_shardings=...) lets the SPMD partitioner shard
        # the RNG computation and every mesh produces different initial
        # params — sharded-vs-single-device parity then fails at step 0
        old_tf = jax.config.jax_threefry_partitionable
        jax.config.update("jax_threefry_partitionable", True)
        try:
            with jax.transfer_guard("allow"):
                state = jax.jit(_init, out_shardings=state_sh)(rng)
        finally:
            jax.config.update("jax_threefry_partitionable", old_tf)

        bshard = jax.tree_util.tree_map(
            lambda x: batch_sharding(mesh), example_batch)
        metric_sh = None  # replicated scalars
        step_fn = jax.jit(
            raw_step,
            in_shardings=(state_sh, bshard),
            out_shardings=(state_sh, metric_sh),
            donate_argnums=(0,) if donate_state else ())
        return state, SpmdStep(step_fn, mesh, state_sh, bshard)

    return init_fn


def _state_leaf_sharding(path, leaf, mesh: Mesh,
                         rules: Optional[ShardingRules]) -> NamedSharding:
    """Shard params AND their optimizer moments identically; scalars
    (step, schedule counters) replicate."""
    from ..parallel.sharding import path_str
    rules = rules or ShardingRules()
    if not getattr(leaf, "shape", ()):
        return replicated(mesh)
    spec = rules.spec_for(path_str(path), leaf.shape, mesh)
    return NamedSharding(mesh, spec)
