"""Framework adapters: HuggingFace weight import + tokenizer/dataset glue.

Reference counterpart: python/ray/train/huggingface (TransformersTrainer,
weight interop) and the torch-module prep in train/torch. TPU-first
inversion: instead of wrapping torch modules, we IMPORT torch weights
into the flax model zoo (GPT-2, Llama) once, then everything downstream
is pure JAX. Gradient-boosting adapters (xgboost/lightgbm) are a
documented scope cut (SURVEY.md §2 known cuts).

All imports of torch/transformers are lazy: nothing here pulls them in
unless an adapter is called.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, Optional

import numpy as np


def torch_state_dict_to_numpy(state_dict) -> Dict[str, np.ndarray]:
    """Detach a torch state_dict to host numpy (fp32)."""
    out = {}
    for k, v in state_dict.items():
        arr = v.detach().cpu().numpy() if hasattr(v, "detach") else np.asarray(v)
        out[k] = np.asarray(arr, dtype=np.float32)
    return out


# ---------------------------------------------------------------- GPT-2 --

def import_hf_gpt2_weights(source, cfg=None):
    """HF GPT-2 (torch) -> ray_tpu.models.gpt2.GPT2 flax params.

    source: a transformers GPT2LMHeadModel / GPT2Model, or a state_dict.
    HF's Conv1D stores weights [in, out] — the same layout as flax Dense
    kernels, so projections map without transposition.
    Returns (params, cfg).
    """
    from ..models.gpt2 import GPT2Config

    if hasattr(source, "state_dict"):
        hf_cfg = getattr(source, "config", None)
        sd = torch_state_dict_to_numpy(source.state_dict())
    else:
        hf_cfg = None
        sd = {k: np.asarray(v, np.float32) for k, v in dict(source).items()}
    # accept both GPT2Model ("h.0...") and GPT2LMHeadModel ("transformer.h.0...")
    if any(k.startswith("transformer.") for k in sd):
        sd = {k[len("transformer."):]: v for k, v in sd.items()
              if k.startswith("transformer.")}

    if cfg is None:
        if hf_cfg is None:
            raise ValueError("pass cfg= when importing from a raw state_dict")
        cfg = GPT2Config(vocab_size=hf_cfg.vocab_size,
                         d_model=hf_cfg.n_embd, n_layers=hf_cfg.n_layer,
                         n_heads=hf_cfg.n_head,
                         max_seq_len=hf_cfg.n_positions)

    p: Dict[str, Any] = {
        "wte": {"embedding": sd["wte.weight"]},
        "wpe": {"embedding": sd["wpe.weight"]},
        "ln_f_scale": sd["ln_f.weight"],
        "ln_f_bias": sd["ln_f.bias"],
    }
    for i in range(cfg.n_layers):
        hf = f"h.{i}."
        p[f"h_{i}"] = {
            "ln_1_scale": sd[hf + "ln_1.weight"],
            "ln_1_bias": sd[hf + "ln_1.bias"],
            "ln_2_scale": sd[hf + "ln_2.weight"],
            "ln_2_bias": sd[hf + "ln_2.bias"],
            "qkv": {"kernel": sd[hf + "attn.c_attn.weight"],
                    "bias": sd[hf + "attn.c_attn.bias"]},
            "attn_out": {"kernel": sd[hf + "attn.c_proj.weight"],
                         "bias": sd[hf + "attn.c_proj.bias"]},
            "fc_in": {"kernel": sd[hf + "mlp.c_fc.weight"],
                      "bias": sd[hf + "mlp.c_fc.bias"]},
            "fc_out": {"kernel": sd[hf + "mlp.c_proj.weight"],
                       "bias": sd[hf + "mlp.c_proj.bias"]},
        }
    return p, cfg


# ---------------------------------------------------------------- Llama --

def import_hf_llama_weights(source, cfg=None):
    """HF LlamaForCausalLM (torch) -> ray_tpu.models.llama.Llama params.

    torch nn.Linear stores [out, in]; flax Dense kernels are [in, out],
    so every projection transposes. Returns (params, cfg).
    """
    from ..models.llama import LlamaConfig

    if hasattr(source, "state_dict"):
        hf_cfg = getattr(source, "config", None)
        sd = torch_state_dict_to_numpy(source.state_dict())
    else:
        hf_cfg = None
        sd = {k: np.asarray(v, np.float32) for k, v in dict(source).items()}

    if cfg is None:
        if hf_cfg is None:
            raise ValueError("pass cfg= when importing from a raw state_dict")
        cfg = LlamaConfig(
            vocab_size=hf_cfg.vocab_size, d_model=hf_cfg.hidden_size,
            n_layers=hf_cfg.num_hidden_layers,
            n_heads=hf_cfg.num_attention_heads,
            n_kv_heads=hf_cfg.num_key_value_heads,
            d_ff=hf_cfg.intermediate_size,
            max_seq_len=hf_cfg.max_position_embeddings,
            rope_theta=getattr(hf_cfg, "rope_theta", 10000.0),
            tie_embeddings="lm_head.weight" not in sd)

    def lin(key):
        return {"kernel": sd[key].T}

    p: Dict[str, Any] = {
        "token_embed": {"embedding": sd["model.embed_tokens.weight"]},
        "final_norm": sd["model.norm.weight"],
    }
    if "lm_head.weight" in sd:
        p["lm_head"] = {"kernel": sd["lm_head.weight"].T}
    for i in range(cfg.n_layers):
        hf = f"model.layers.{i}."
        p[f"layer_{i}"] = {
            "attn_norm": sd[hf + "input_layernorm.weight"],
            "mlp_norm": sd[hf + "post_attention_layernorm.weight"],
            "attention": {
                "q_proj": lin(hf + "self_attn.q_proj.weight"),
                "k_proj": lin(hf + "self_attn.k_proj.weight"),
                "v_proj": lin(hf + "self_attn.v_proj.weight"),
                "o_proj": lin(hf + "self_attn.o_proj.weight"),
            },
            "mlp": {
                "gate_proj": lin(hf + "mlp.gate_proj.weight"),
                "up_proj": lin(hf + "mlp.up_proj.weight"),
                "down_proj": lin(hf + "mlp.down_proj.weight"),
            },
        }
    return p, cfg


# ------------------------------------------------------------ tokenizer --

def load_tokenizer(name_or_path: str, **kwargs):
    """transformers AutoTokenizer (lazy import; needs local files or
    network — callers in air-gapped images pass a local path)."""
    from transformers import AutoTokenizer
    return AutoTokenizer.from_pretrained(name_or_path, **kwargs)


def tokenize_dataset(ds, tokenizer: Callable, *, text_column: str = "text",
                     max_length: int = 512, pad_id: int = 0):
    """Map a ray_tpu.data Dataset of text rows to fixed-length token ids.

    tokenizer: HF tokenizer or any callable str -> list[int] (encode).
    Produces columns input_ids [L] int32 and attention_mask [L] int8.
    """
    def encode_batch(batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        texts = [str(t) for t in batch[text_column]]
        ids_rows, mask_rows = [], []
        for t in texts:
            if hasattr(tokenizer, "encode"):
                ids = tokenizer.encode(t)
            else:
                ids = tokenizer(t)
            ids = list(ids)[:max_length]
            mask = [1] * len(ids) + [0] * (max_length - len(ids))
            ids = ids + [pad_id] * (max_length - len(ids))
            ids_rows.append(ids)
            mask_rows.append(mask)
        return {"input_ids": np.asarray(ids_rows, np.int32),
                "attention_mask": np.asarray(mask_rows, np.int8)}

    return ds.map_batches(encode_batch)


def hf_dataset_to_ray(hf_dataset, columns: Optional[Iterable[str]] = None):
    """`datasets` Dataset -> ray_tpu.data Dataset (columnar numpy)."""
    from ..data import from_items
    cols = list(columns) if columns else hf_dataset.column_names
    rows = [{c: ex[c] for c in cols} for ex in hf_dataset]
    return from_items(rows)
