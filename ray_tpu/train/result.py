"""Result of a training run (reference: python/ray/air/result.py)."""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from .checkpoint import Checkpoint


@dataclasses.dataclass
class Result:
    metrics: Dict[str, Any]
    checkpoint: Optional[Checkpoint]
    error: Optional[BaseException] = None
    metrics_history: List[Dict[str, Any]] = dataclasses.field(
        default_factory=list)
    path: str = ""
    config: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def best_checkpoints(self):
        return [self.checkpoint] if self.checkpoint else []

    def metrics_dataframe(self):
        import pandas as pd
        return pd.DataFrame(self.metrics_history)
