"""LoRA adapters, parameter-functional and model-agnostic.

Reference context: the fork's LLM post-training focus (GRPO/RLHF on the
serve engine). Rather than wrapping module classes (the torch/PEFT
idiom), adapters here are a separate small pytree over the FROZEN base
params: for every targeted 2-D Dense kernel `.../<target>/kernel`
(shape (d_in, d_out)) we hold A:(d_in, r) and B:(r, d_out), and
`merge_lora` produces `kernel + (alpha/r) * A @ B` as a pure function.
Under jit the merge fuses into the forward; grads flow only through the
adapter leaves, so optimizer state is O(adapter), not O(model), and the
base params can stay sharded exactly as the pretrained checkpoint was.

Typical use:
    lora = init_lora(params, rng, rank=8)
    init = make_lora_train_step(model, tx, mesh, params)
    state, step = init(example_batch, lora)
    state, metrics = step(state, batch)
    merged = merge_lora(params, state.params)   # deploy/serve
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import optax

DEFAULT_TARGETS = ("q_proj", "k_proj", "v_proj", "o_proj",
                   "gate_proj", "up_proj", "down_proj",
                   "qkv", "proj", "fc1", "fc2")


@dataclasses.dataclass(frozen=True)
class LoraConfig:
    rank: int = 8
    alpha: float = 16.0
    targets: Sequence[str] = DEFAULT_TARGETS

    @property
    def scaling(self) -> float:
        return self.alpha / self.rank


def _is_target(path: Tuple, leaf, targets: Sequence[str]) -> bool:
    keys = [getattr(k, "key", str(k)) for k in path]
    return (len(keys) >= 2 and keys[-1] == "kernel"
            and keys[-2] in targets and getattr(leaf, "ndim", 0) == 2)


def _is_adapter_node(x) -> bool:
    """Leaf predicate for adapter pytrees: an {"A","B"} pair or an
    untargeted position (None)."""
    return x is None or (isinstance(x, dict) and set(x) == {"A", "B"})


def init_lora(params, rng, rank: int = 8, alpha: float = 16.0,
              targets: Sequence[str] = DEFAULT_TARGETS) -> Dict[str, Any]:
    """Adapter pytree mirroring `params`: an {"A","B"} pair at each
    targeted kernel, None elsewhere. A ~ N(0, 1/rank) fp32, B = 0, so
    the merged model starts exactly at the base model. The returned
    dict also carries the (static) scaling config."""
    cfg = LoraConfig(rank=rank, alpha=alpha, targets=tuple(targets))
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    keys = jax.random.split(rng, max(len(flat), 1))

    def make(path, leaf, key):
        if not _is_target(path, leaf, cfg.targets):
            return None
        d_in, _d_out = leaf.shape
        a = jax.random.normal(key, (d_in, cfg.rank),
                              jnp.float32) / cfg.rank
        b = jnp.zeros((cfg.rank, leaf.shape[1]), jnp.float32)
        return {"A": a, "B": b}

    leaves = [make(path, leaf, keys[i])
              for i, (path, leaf) in enumerate(flat)]
    adapters = jax.tree_util.tree_unflatten(treedef, leaves)
    if all(x is None for x in leaves):
        raise ValueError(f"no LoRA targets matched; targets={cfg.targets}")
    return {"rank": cfg.rank, "alpha": cfg.alpha, "adapters": adapters}


def merge_lora(params, lora) -> Any:
    """params with every adapted kernel replaced by
    kernel + scaling * A @ B (pure; jit/grad-safe)."""
    scaling = lora["alpha"] / lora["rank"]

    def merge(ad, p):
        if ad is None:
            return p
        delta = (ad["A"] @ ad["B"]) * scaling
        return p + delta.astype(p.dtype)

    # walk the ADAPTER tree (its leaves are the {"A","B"}/None markers)
    # and flatten params up to it — params' kernels sit exactly at those
    # positions.
    return jax.tree_util.tree_map(merge, lora["adapters"], params,
                                  is_leaf=_is_adapter_node)


def lora_param_count(lora) -> int:
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(
        lora["adapters"]))


def make_lora_train_step(model, tx, mesh, base_params, *,
                         loss_fn: Optional[Callable] = None):
    """Like train.make_train_step but ONLY the adapter leaves train: the
    base params ride along frozen (closed over, keeping whatever
    shardings they already have) and the TrainState/opt-state hold just
    the adapter pytree.

    Returns init_fn; init_fn(example_batch, lora) ->
    (TrainState over adapters, step(state, batch))."""
    from .spmd import TrainState, next_token_loss
    from ..parallel.sharding import replicated

    loss_fn = loss_fn or partial(next_token_loss, model.apply)

    def init_fn(example_batch, lora):
        del example_batch  # shapes come from the batch at call time
        scaling_cfg = {"rank": lora["rank"], "alpha": lora["alpha"]}

        def raw_step(state: TrainState, batch):
            def lora_loss(adapters):
                merged = merge_lora(base_params,
                                    {**scaling_cfg, "adapters": adapters})
                return loss_fn(merged, batch)

            (_loss, metrics), grads = jax.value_and_grad(
                lora_loss, has_aux=True)(state.params)
            updates, new_opt = tx.update(grads, state.opt_state,
                                         state.params)
            new_params = optax.apply_updates(state.params, updates)
            metrics = dict(metrics)
            metrics["grad_norm"] = optax.global_norm(grads)
            return TrainState(step=state.step + 1, params=new_params,
                              opt_state=new_opt), metrics

        state = TrainState.create(lora["adapters"], tx)
        # adapters are small: replicate them over the mesh; the frozen
        # base keeps its own (fsdp/tp) shardings untouched
        state = jax.device_put(state, replicated(mesh))
        step_fn = jax.jit(raw_step, donate_argnums=(0,))
        return state, step_fn

    return init_fn


__all__ = ["LoraConfig", "init_lora", "merge_lora", "lora_param_count",
           "make_lora_train_step", "DEFAULT_TARGETS"]
