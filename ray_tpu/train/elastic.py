"""Elastic gang layer: supervision, death detection, and mesh resharding.

The training plane's fault-tolerance piece (ROADMAP item 5): PRs 4/6
made objects and the driver survive kills, PR 5 the serve plane — this
module makes a multi-host SPMD GANG survive a preempted host. A
`GangSupervisor` on the driver watches every rank actor's GCS state
(the same actor-death determination the PR-3 heartbeat -> `node.death`
chain feeds), flags a lost rank within ~a poll interval, fails the
gang's parked collective rounds fast (util/collective.py
`mark_rank_dead` -> CollectiveRankDiedError), and hands
`MultiHostSpmd.reform()` the signal to tear down the doomed
`jax.distributed` world and re-gang — with a replacement host when the
cluster has capacity, otherwise RESHARDED onto the surviving world
(`reshard_mesh_spec` shrinks the dp axis). Generations fence zombie
ranks of the old world, mirroring PR-4 node incarnations.

The supervisor runs where the gang handle lives — the driver process —
because the GCS actor/node tables ARE the death signal in this
single-controller design (reference: the Ray paper's lineage/actor
supervision, read through the GCS rather than a side channel).
"""
from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..util import knobs

#: seconds between supervisor scans of the rank actors' GCS state
ENV_PROBE_S = "RAY_TPU_GANG_PROBE_S"
#: total budget for one reform (capacity wait + re-gang + join)
ENV_REFORM_TIMEOUT_S = "RAY_TPU_GANG_REFORM_TIMEOUT_S"
#: how long reform waits for FULL replacement capacity before it
#: settles for a resharded (smaller) world
ENV_REPLACE_WAIT_S = "RAY_TPU_GANG_REPLACE_WAIT_S"


def _probe_s() -> float:
    return knobs.get_float(ENV_PROBE_S)


def reform_timeout_s() -> float:
    return knobs.get_float(ENV_REFORM_TIMEOUT_S)


def replace_wait_s() -> float:
    return knobs.get_float(ENV_REPLACE_WAIT_S)


@dataclasses.dataclass
class RankDeath:
    """One lost gang member, as seen by the supervisor."""
    rank: int
    actor_id: str
    cause: str
    generation: int
    detected_at: float


class GangSupervisor:
    """Driver-side death watch over a gang's rank actors.

    Polls the GCS actor table (every RAY_TPU_GANG_PROBE_S, default
    0.25 s) for each member reaching DEAD — which the runtime already
    determines from worker-socket close, node-socket close, or the
    heartbeat chain — and on the first death:

      * emits `train.gang.rank_death` (cause, rank, generation),
      * calls `mark_rank_dead` on every registered collective group so
        parked rounds fail with CollectiveRankDiedError in seconds,
      * sets `failed` and invokes `on_death` (once per dead rank).

    The supervisor never tears anything down itself — that is
    `MultiHostSpmd.reform()`'s job — so it can also watch bare
    collective gangs that have no MultiHostSpmd around them.
    """

    def __init__(self, members: Dict[int, str], *, generation: int = 0,
                 collective_groups: Sequence[str] = (),
                 on_death: Optional[Callable[[RankDeath], None]] = None,
                 poll_s: Optional[float] = None):
        from ..core import runtime as runtime_mod
        rt = runtime_mod.get_runtime()
        if not getattr(rt, "is_driver", False) \
                or not hasattr(rt, "gcs"):
            raise RuntimeError(
                "gang supervision reads the GCS actor table and must "
                "run in the driver process (where the gang handle "
                "lives)")
        self._rt = rt
        self._members = dict(members)          # rank -> actor_id
        self.generation = generation
        self._groups = tuple(collective_groups)
        self._on_death = on_death
        self._poll_s = poll_s if poll_s is not None else _probe_s()
        self.deaths: List[RankDeath] = []
        self.failed = threading.Event()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._watch, name="gang-supervisor", daemon=True)
        self._thread.start()

    # ---- signal surface -------------------------------------------------
    @property
    def first_death(self) -> Optional[RankDeath]:
        return self.deaths[0] if self.deaths else None

    def wait(self, timeout: Optional[float] = None) -> Optional[RankDeath]:
        """Block until a member dies (or timeout); returns the death."""
        self.failed.wait(timeout)
        return self.first_death

    def survivors(self) -> Dict[int, str]:
        dead = {d.rank for d in self.deaths}
        return {r: a for r, a in self._members.items() if r not in dead}

    def stop(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)

    # ---- watch loop -----------------------------------------------------
    def _watch(self) -> None:
        from ..util import events
        from ..util.collective import notify_rank_death
        seen: set = set()
        while not self._stop.is_set():
            for rank, aid in self._members.items():
                if rank in seen:
                    continue
                ae = self._rt.gcs.actors.get(aid)
                state = ae.state if ae is not None else None
                if state is not None and state != "DEAD":
                    continue
                cause = (ae.death_cause if ae is not None else None) \
                    or "actor entry gone"
                seen.add(rank)
                death = RankDeath(rank=rank, actor_id=aid,
                                  cause=str(cause),
                                  generation=self.generation,
                                  detected_at=time.time())
                self.deaths.append(death)
                events.emit_safe(
                    "train.gang.rank_death",
                    f"gang rank {rank} died: {death.cause}",
                    rank=str(rank), actor_id=aid,
                    generation=str(self.generation))
                for g in self._groups:
                    notify_rank_death(
                        g, rank,
                        f"gang generation {self.generation}: "
                        f"{death.cause}")
                self.failed.set()
                if self._on_death is not None:
                    try:
                        self._on_death(death)
                    except Exception:  # noqa: BLE001 — watch must live on
                        pass
            self._stop.wait(self._poll_s)


def reshard_mesh_spec(spec: Any, n_devices: int) -> Any:
    """Scale a MeshSpec onto a different global device count by scaling
    the dp axis — the premise of the cross-replica-sharding paper in
    PAPERS.md: mesh layout is a re-derivable FUNCTION of the surviving
    world, not fixed job state. Model-parallel axes (tp/sp/fsdp/ep/pp)
    keep their shape; only data parallelism stretches or shrinks."""
    if spec.size == n_devices:
        return spec
    per_dp = spec.size // spec.dp       # devices consumed by other axes
    if per_dp <= 0 or n_devices % per_dp != 0 or n_devices < per_dp:
        raise ValueError(
            f"cannot reshard MeshSpec {spec.axis_sizes()} onto "
            f"{n_devices} devices: non-dp axes need multiples of "
            f"{per_dp} devices")
    return dataclasses.replace(spec, dp=n_devices // per_dp)
