"""Backend setup: per-worker JAX runtime + mesh formation.

Reference counterpart: ray.train backend configs (train/backend.py,
train/torch/config.py — the piece that runs `dist.init_process_group`
on every worker with a rendezvous address). JAX translation: workers
call `jax.distributed.initialize(coordinator, num_processes, process_id)`
and then build one global Mesh; on a single host (or under the test CPU
mesh) initialization is a no-op and the mesh forms over local devices.

Multi-host TPU pods: each host runs one worker process that owns the
host's local chips; the coordinator address is the rank-0 host. All
cross-host tensor traffic happens inside jit via XLA collectives over
ICI/DCN — this backend only forms the mesh, it never moves tensors.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax

from ..parallel.mesh import MeshSpec, build_mesh
from ..util import knobs


@dataclasses.dataclass
class JaxBackendConfig:
    """Reference analogue: TorchConfig(backend='nccl', init_method=...)."""
    coordinator_address: Optional[str] = None   # "host:port" of rank 0
    num_processes: Optional[int] = None
    heartbeat_timeout_s: int = 100


def setup_worker(config: JaxBackendConfig, *, process_id: int,
                 num_processes: Optional[int] = None) -> None:
    """Initialize this worker's JAX distributed runtime (multi-host).

    No-op when single-process: jax.distributed.initialize is only needed
    (and only valid) when several processes form one XLA computation.
    """
    world = num_processes or config.num_processes or 1
    if world <= 1 or config.coordinator_address is None:
        return
    if jax.process_count() > 1:
        return          # already initialized
    jax.distributed.initialize(
        coordinator_address=config.coordinator_address,
        num_processes=world,
        process_id=process_id,
        initialization_timeout=config.heartbeat_timeout_s)


def form_mesh(spec: Optional[MeshSpec] = None) -> jax.sharding.Mesh:
    """Build the global device mesh (all processes' devices). Must be
    called with identical spec on every worker."""
    spec = spec or MeshSpec(dp=len(jax.devices()))
    return build_mesh(spec)


def worker_env(rank: int, world_size: int,
               coordinator_address: Optional[str]) -> dict:
    """Env block a launcher injects into each worker process (reference:
    the env vars torch backend sets: RANK/WORLD_SIZE/MASTER_ADDR)."""
    env = {
        "RAY_TPU_TRAIN_RANK": str(rank),
        "RAY_TPU_TRAIN_WORLD": str(world_size),
    }
    if coordinator_address:
        env["RAY_TPU_COORDINATOR"] = coordinator_address
    return env


def detect_rank() -> int:
    return knobs.get_int("RAY_TPU_TRAIN_RANK")


def detect_world_size() -> int:
    return knobs.get_int("RAY_TPU_TRAIN_WORLD")
