"""ray_tpu.train — distributed training (reference: python/ray/train).

Layer map:
  spmd.py          the jitted SPMD step (replaces DDP/FSDP/NCCL wiring)
  trainer.py       JaxTrainer: actor-per-host function trainer
  spmd_trainer.py  SpmdTrainer: declarative model+mesh trainer
                   + ElasticSpmdTrainer: gang-supervised fit with
                   checkpoint-resume into a (possibly resharded) mesh
  elastic.py       gang supervision, death detection, mesh resharding
  multihost.py     MultiHostSpmd gang (supervised/elastic mode)
  session.py       report()/get_context() worker session
  checkpoint.py    orbax sharded checkpoints
  config.py        ScalingConfig/RunConfig/FailureConfig/CheckpointConfig
  backend.py       per-worker JAX distributed init + mesh formation
  utils.py         prepare_module / prepare_loader
  adapters.py      HF weight import (GPT-2, Llama) + tokenizer glue
"""
from .spmd import TrainState, make_train_step, next_token_loss, SpmdStep
from .optim import make_optimizer, warmup_cosine
from .config import (ScalingConfig, RunConfig, FailureConfig,
                     CheckpointConfig)
from .session import report, get_context, TrainContext
from .checkpoint import (Checkpoint, CheckpointManager, save_pytree,
                         restore_pytree)
from .result import Result
from .trainer import JaxTrainer
from .spmd_trainer import SpmdTrainer, SpmdTrainerConfig
from .backend import (JaxBackendConfig, detect_rank, detect_world_size,
                      form_mesh, setup_worker)
from .utils import prepare_module, prepare_loader

from . import adapters  # noqa: F401  (lazy torch/transformers inside)

from .multihost import MultiHostSpmd
from .elastic import GangSupervisor, RankDeath, reshard_mesh_spec
from .spmd_trainer import ElasticSpmdTrainer
from .lora import (LoraConfig, init_lora, merge_lora, lora_param_count,
                   make_lora_train_step)

__all__ = [
    "MultiHostSpmd", "GangSupervisor", "RankDeath", "reshard_mesh_spec",
    "ElasticSpmdTrainer",
    "JaxBackendConfig", "setup_worker", "form_mesh", "detect_rank",
    "detect_world_size", "prepare_module", "prepare_loader", "adapters",
    "TrainState", "make_train_step", "next_token_loss", "SpmdStep",
    "make_optimizer", "warmup_cosine", "ScalingConfig", "RunConfig",
    "FailureConfig", "CheckpointConfig", "report", "get_context",
    "TrainContext", "Checkpoint", "CheckpointManager", "save_pytree",
    "restore_pytree", "Result", "JaxTrainer", "SpmdTrainer",
    "SpmdTrainerConfig",
    "LoraConfig", "init_lora", "merge_lora", "lora_param_count",
    "make_lora_train_step",
]
