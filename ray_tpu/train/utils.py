"""Training prep utilities: shard modules onto the mesh, split loaders.

Reference counterpart: ray.train.torch prepare_model /
prepare_data_loader (train/torch/train_loop_utils.py). TPU translation:
"prepare" a model by device_put-ing its params with NamedShardings from
the parallel sharding rules; "prepare" a loader by giving each worker
its rank's shard and device-prefetching batches.
"""
from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, Optional

import jax
import numpy as np

from ..parallel.sharding import shard_pytree, replicated


def prepare_module(params: Any, mesh: Optional[jax.sharding.Mesh] = None,
                   *, rules: Optional[Any] = None) -> Any:
    """Place a param pytree onto the mesh per the sharding rules
    (fsdp/tp axes); no mesh -> single-device put."""
    if mesh is None:
        return jax.device_put(params)
    if rules is None:
        return jax.device_put(params, replicated(mesh))
    return shard_pytree(params, mesh, rules)


def prepare_loader(dataset, *, rank: int, world_size: int,
                   batch_size: int, sharding=None,
                   prefetch: int = 2) -> Iterable:
    """Per-worker shard of a ray_tpu.data Dataset as device batches.

    Equivalent altitude to prepare_data_loader: rank-split, batch, then
    double-buffered host->HBM prefetch (ray_tpu.data.device_loader).
    """
    from ..data.device_loader import device_put_iterator
    shard = dataset.split_for_worker(rank, world_size)
    return device_put_iterator(shard.iter_batches(batch_size=batch_size),
                               sharding=sharding, prefetch=prefetch)


def iter_batches_sharded(arrays_iter: Iterator[Any], sharding,
                         prefetch: int = 2) -> Iterator[Any]:
    """Wrap any host-batch iterator with sharded device_put prefetch."""
    from ..data.device_loader import device_put_iterator
    return device_put_iterator(arrays_iter, sharding=sharding,
                               prefetch=prefetch)
