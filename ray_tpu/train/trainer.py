"""JaxTrainer: distributed training driver.

Reference parity: python/ray/train/base_trainer.py +
data_parallel_trainer.py + torch/torch_trainer.py. Differences by design:
  * One worker actor per HOST (not per accelerator): inside each worker a
    single jitted SPMD program drives all local chips; scaling across hosts
    multiplies the mesh, not the worker count per chip.
  * No backend_config/NCCL setup: collective wiring is XLA's job.

Fault tolerance (reference FailureConfig semantics): if a worker dies and
failure budget remains, the whole group restarts from the latest checkpoint
(passed to the loop via session context / `get_checkpoint()`).
"""
from __future__ import annotations

import itertools
import os
import time
from typing import Any, Callable, Dict, List, Optional

from .. import api
from ..core import runtime as runtime_mod
from ..exceptions import ActorDiedError, RayTpuError, WorkerCrashedError
from .checkpoint import Checkpoint, CheckpointManager
from .config import RunConfig, ScalingConfig
from .result import Result
from .session import TrainContext, init_session, clear_session

_trainer_ids = itertools.count()


class _TrainWorker:
    """Actor hosting one training loop (one host's SPMD program)."""

    def __init__(self, ctx: TrainContext, channel: str):
        self.ctx = ctx
        self.channel = channel

    def run(self, fn: Callable, config: Dict[str, Any],
            resume_from: Optional[str]) -> str:
        rt = runtime_mod.get_runtime()

        def report_fn(payload):
            rt.report(self.channel, payload)

        ctx = self.ctx
        session = init_session(ctx, report_fn)
        session.resume_from = resume_from
        try:
            if resume_from is not None:
                config = dict(config or {})
                config.setdefault("resume_from_checkpoint", resume_from)
            fn(config) if config is not None else fn({})
            return "done"
        finally:
            clear_session()

    def ping(self):
        return "pong"


class JaxTrainer:
    def __init__(self, train_loop_per_worker: Callable,
                 *, train_loop_config: Optional[Dict[str, Any]] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        self._fn = train_loop_per_worker
        self._config = train_loop_config or {}
        self.scaling = scaling_config or ScalingConfig(use_tpu=False)
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        self._resume = resume_from_checkpoint
        self._tid = next(_trainer_ids)
        self.channel = f"train:{self._tid}"

    # -- internals ----------------------------------------------------------
    def _spawn_group(self, resume_from: Optional[str]):
        workers = []
        refs = []
        res = self.scaling.worker_resources()
        for rank in range(self.scaling.num_workers):
            ctx = TrainContext(world_size=self.scaling.num_workers,
                               world_rank=rank, local_rank=rank,
                               experiment_name=self.run_config.name)
            actor_cls = api.remote(
                num_cpus=res.get("CPU", 1),
                num_tpus=res.get("TPU", 0),
                resources={k: v for k, v in res.items()
                           if k not in ("CPU", "TPU")},
            )(_TrainWorker)
            w = actor_cls.remote(ctx, self.channel)
            workers.append(w)
        for rank, w in enumerate(workers):
            cfg = dict(self._config)
            if self.datasets:
                cfg["datasets"] = {
                    k: self._shard_dataset(ds, rank)
                    for k, ds in self.datasets.items()}
            refs.append(w.run.remote(self._fn, cfg, resume_from))
        return workers, refs

    def _shard_dataset(self, ds, rank):
        split = getattr(ds, "split_for_worker", None)
        if split is not None:
            return split(rank, self.scaling.num_workers)
        return ds

    def fit(self) -> Result:
        if not api.is_initialized():
            api.init()
        rt = runtime_mod.get_runtime()
        history: List[Dict[str, Any]] = []
        run_dir = self.run_config.run_dir()
        ckpt_root = os.path.join(run_dir, "checkpoints")
        manager = CheckpointManager(
            ckpt_root, self.run_config.checkpoint_config.num_to_keep)

        def on_report(worker_id, payload):
            history.append(payload)

        rt.register_report_handler(self.channel, on_report)

        failures_left = self.run_config.failure_config.max_failures
        resume_from = self._resume.path if self._resume else None
        error: Optional[BaseException] = None

        while True:
            workers, refs = self._spawn_group(resume_from)
            try:
                api.get(refs)
                error = None
                break
            except (ActorDiedError, WorkerCrashedError, RayTpuError) as e:
                error = e
                for w in workers:
                    try:
                        api.kill(w)
                    except Exception:
                        pass
                if failures_left > 0:
                    failures_left -= 1
                    latest = manager.latest()
                    resume_from = latest.path if latest else resume_from
                    continue
                break
            finally:
                for w in workers:
                    try:
                        api.kill(w)
                    except Exception:
                        pass

        final_metrics = history[-1]["metrics"] if history else {}
        ckpt = manager.latest()
        # Also honor checkpoints reported via session.report(path)
        reported = [h.get("checkpoint") for h in history
                    if h.get("checkpoint")]
        if ckpt is None and reported:
            ckpt = Checkpoint(reported[-1])
        return Result(metrics=final_metrics, checkpoint=ckpt, error=error,
                      metrics_history=[h["metrics"] for h in history
                                       if "metrics" in h],
                      path=run_dir)
