"""Optimizers & schedules (optax), replacing torch.optim in the reference's
training path (python/ray/train/examples/*, rllib optimizers)."""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import optax


def warmup_cosine(peak_lr: float, warmup_steps: int, total_steps: int,
                  end_lr_frac: float = 0.1) -> optax.Schedule:
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=peak_lr, warmup_steps=max(1, warmup_steps),
        decay_steps=max(2, total_steps), end_value=peak_lr * end_lr_frac)


def _decay_mask(params):
    """No weight decay on norms/biases/embeddings (standard LLM recipe)."""
    import jax
    from ..parallel.sharding import path_str

    def mask_leaf(path, leaf):
        p = path_str(path).lower()
        return not any(t in p for t in ("norm", "bias", "scale", "embed",
                                        "wpe", "ln_"))
    return jax.tree_util.tree_map_with_path(mask_leaf, params)


def make_optimizer(name: str = "adamw", *, learning_rate=3e-4,
                   weight_decay: float = 0.1, b1=0.9, b2=0.95,
                   grad_clip: Optional[float] = 1.0,
                   schedule: Optional[optax.Schedule] = None
                   ) -> optax.GradientTransformation:
    lr = schedule if schedule is not None else learning_rate
    if name == "adamw":
        core = optax.adamw(lr, b1=b1, b2=b2, weight_decay=weight_decay,
                           mask=_decay_mask)
    elif name == "adam":
        core = optax.adam(lr, b1=b1, b2=b2)
    elif name == "sgd":
        core = optax.sgd(lr, momentum=0.9)
    elif name == "lion":
        core = optax.lion(lr, weight_decay=weight_decay)
    elif name == "adafactor":
        # factored second moments + no first moment: optimizer state is
        # O(rows+cols) per matrix instead of 2x params — the memory
        # budget that lets >=1B-param training fit one 16 GB chip
        core = optax.adafactor(lr)
    else:
        raise ValueError(f"unknown optimizer {name!r}")
    if grad_clip:
        return optax.chain(optax.clip_by_global_norm(grad_clip), core)
    return core
