"""SpmdTrainer: declarative model+mesh trainer (the TorchTrainer analogue
for the common LLM case).

Reference parity: TorchTrainer + its prepare_model/prepare_data_loader
utilities (python/ray/train/torch/). Instead of wrapping user torch code,
the common case is declared: model (name or module), mesh spec, optimizer,
data iterator — the trainer owns the jitted step, logging, checkpointing,
and restore.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, Iterator, Optional

import numpy as np

from ..parallel.mesh import MeshSpec, build_mesh
from .checkpoint import CheckpointManager, restore_pytree
from .config import RunConfig
from .optim import make_optimizer, warmup_cosine
from .spmd import make_train_step
from .result import Result


@dataclasses.dataclass
class SpmdTrainerConfig:
    model: Any                          # nn.Module or registry name
    mesh: MeshSpec = dataclasses.field(default_factory=MeshSpec)
    optimizer: str = "adamw"
    learning_rate: float = 3e-4
    warmup_steps: int = 100
    total_steps: int = 1000
    log_every: int = 10
    checkpoint_every: int = 0
    grad_clip: float = 1.0
    seed: int = 0


class SpmdTrainer:
    def __init__(self, config: SpmdTrainerConfig,
                 data_iter_fn: Callable[[], Iterator[Dict[str, Any]]],
                 run_config: Optional[RunConfig] = None,
                 report_fn: Optional[Callable[[Dict[str, Any]], None]] = None):
        self.cfg = config
        self.data_iter_fn = data_iter_fn
        self.run_config = run_config or RunConfig(name="spmd_trainer")
        self.report_fn = report_fn

    def fit(self, resume_from: Optional[str] = None) -> Result:
        import jax
        import jax.numpy as jnp

        cfg = self.cfg
        model = cfg.model
        if isinstance(model, str):
            from ..models import get_model
            model = get_model(model)
        devices = jax.devices()
        spec = cfg.mesh
        if spec.size != len(devices):
            # single-host convenience: use however many devices exist
            spec = MeshSpec(dp=len(devices)) if len(devices) > 1 else MeshSpec()
        mesh = build_mesh(spec, devices=devices[:spec.size])

        schedule = warmup_cosine(cfg.learning_rate, cfg.warmup_steps,
                                 cfg.total_steps)
        tx = make_optimizer(cfg.optimizer, schedule=schedule,
                            grad_clip=cfg.grad_clip)

        data = self.data_iter_fn()
        first = next(data)
        batch = {k: jnp.asarray(v) for k, v in first.items()}
        init_fn = make_train_step(model, tx, mesh)
        state, step_fn = init_fn(jax.random.PRNGKey(cfg.seed), batch)

        manager = CheckpointManager(
            self.run_config.run_dir() + "/checkpoints",
            self.run_config.checkpoint_config.num_to_keep)
        start_step = 0
        if resume_from:
            state = restore_pytree(resume_from, target=state,
                                   shardings=step_fn.state_shardings)
            start_step = int(state.step)

        history = []
        tokens_acc, t_last = 0, time.time()
        for i in range(start_step, cfg.total_steps):
            state, metrics = step_fn(state, batch)
            tokens_acc += int(np.prod(batch[next(iter(batch))].shape[:2]))
            if (i + 1) % cfg.log_every == 0 or i + 1 == cfg.total_steps:
                now = time.time()
                m = {k: float(v) for k, v in metrics.items()}
                m.update(step=i + 1,
                         tokens_per_s=tokens_acc / max(now - t_last, 1e-9))
                tokens_acc, t_last = 0, now
                history.append(m)
                if self.report_fn:
                    self.report_fn(m)
            if cfg.checkpoint_every and (i + 1) % cfg.checkpoint_every == 0:
                manager.save(jax.device_get(state), i + 1)
            try:
                nxt = next(data)
                batch = {k: jnp.asarray(v) for k, v in nxt.items()}
            except StopIteration:
                data = self.data_iter_fn()
                batch = {k: jnp.asarray(v)
                         for k, v in next(data).items()}

        final_ckpt = None
        if cfg.checkpoint_every:
            final_ckpt = manager.save(jax.device_get(state), cfg.total_steps)
        return Result(metrics=history[-1] if history else {},
                      checkpoint=final_ckpt or manager.latest(),
                      metrics_history=history,
                      path=self.run_config.run_dir())
