"""SpmdTrainer: declarative model+mesh trainer (the TorchTrainer analogue
for the common LLM case).

Reference parity: TorchTrainer + its prepare_model/prepare_data_loader
utilities (python/ray/train/torch/). Instead of wrapping user torch code,
the common case is declared: model (name or module), mesh spec, optimizer,
data iterator — the trainer owns the jitted step, logging, checkpointing,
and restore.

ElasticSpmdTrainer is the multi-host, fault-tolerant variant: it drives
a supervised MultiHostSpmd gang and runs the recover cycle of the other
FT planes (PRs 4/5/6) for training — on a rank death the gang reforms
(replaced or resharded, train/elastic.py), every rank restores the last
COMMITTED checkpoint through `restore_pytree(shardings=...)` onto the
new (possibly smaller) mesh, and the loop continues from `state.step`.
"""
from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

import numpy as np

from ..parallel.mesh import MeshSpec, build_mesh
from ..util import knobs
from .checkpoint import CheckpointManager, restore_pytree
from .config import RunConfig
from .optim import make_optimizer, warmup_cosine
from .spmd import make_train_step
from .result import Result


@dataclasses.dataclass
class SpmdTrainerConfig:
    model: Any                          # nn.Module or registry name
    mesh: MeshSpec = dataclasses.field(default_factory=MeshSpec)
    optimizer: str = "adamw"
    learning_rate: float = 3e-4
    warmup_steps: int = 100
    total_steps: int = 1000
    log_every: int = 10
    checkpoint_every: int = 0
    grad_clip: float = 1.0
    seed: int = 0


class SpmdTrainer:
    def __init__(self, config: SpmdTrainerConfig,
                 data_iter_fn: Callable[[], Iterator[Dict[str, Any]]],
                 run_config: Optional[RunConfig] = None,
                 report_fn: Optional[Callable[[Dict[str, Any]], None]] = None):
        self.cfg = config
        self.data_iter_fn = data_iter_fn
        self.run_config = run_config or RunConfig(name="spmd_trainer")
        self.report_fn = report_fn

    def fit(self, resume_from: Optional[str] = None) -> Result:
        import jax
        import jax.numpy as jnp

        cfg = self.cfg
        model = cfg.model
        if isinstance(model, str):
            from ..models import get_model
            model = get_model(model)
        devices = jax.devices()
        spec = cfg.mesh
        if spec.size != len(devices):
            # single-host convenience: use however many devices exist
            spec = MeshSpec(dp=len(devices)) if len(devices) > 1 else MeshSpec()
        mesh = build_mesh(spec, devices=devices[:spec.size])

        schedule = warmup_cosine(cfg.learning_rate, cfg.warmup_steps,
                                 cfg.total_steps)
        tx = make_optimizer(cfg.optimizer, schedule=schedule,
                            grad_clip=cfg.grad_clip)

        data = self.data_iter_fn()
        first = next(data)
        batch = {k: jnp.asarray(v) for k, v in first.items()}
        init_fn = make_train_step(model, tx, mesh)
        state, step_fn = init_fn(jax.random.PRNGKey(cfg.seed), batch)

        manager = CheckpointManager(
            self.run_config.run_dir() + "/checkpoints",
            self.run_config.checkpoint_config.num_to_keep)
        start_step = 0
        if resume_from:
            state = restore_pytree(resume_from, target=state,
                                   shardings=step_fn.state_shardings)
            start_step = int(state.step)
            bnp, data = _fast_forward_batches(
                data, {k: np.asarray(v) for k, v in first.items()},
                start_step, self.data_iter_fn)
            batch = {k: jnp.asarray(v) for k, v in bnp.items()}

        history = []
        tokens_acc, t_last = 0, time.time()
        for i in range(start_step, cfg.total_steps):
            state, metrics = step_fn(state, batch)
            tokens_acc += int(np.prod(batch[next(iter(batch))].shape[:2]))
            if (i + 1) % cfg.log_every == 0 or i + 1 == cfg.total_steps:
                now = time.time()
                m = {k: float(v) for k, v in metrics.items()}
                m.update(step=i + 1,
                         tokens_per_s=tokens_acc / max(now - t_last, 1e-9))
                tokens_acc, t_last = 0, now
                history.append(m)
                if self.report_fn:
                    self.report_fn(m)
            if cfg.checkpoint_every and (i + 1) % cfg.checkpoint_every == 0:
                manager.save(jax.device_get(state), i + 1)
            # only draw ahead if another step will run: finite streams
            # (e.g. a data-service iterator on its last epoch) end
            # exactly at total_steps and must not be over-drawn
            if i + 1 < cfg.total_steps:
                try:
                    nxt = next(data)
                    batch = {k: jnp.asarray(v) for k, v in nxt.items()}
                except StopIteration:
                    data = self.data_iter_fn()
                    batch = {k: jnp.asarray(v)
                             for k, v in next(data).items()}

        final_ckpt = None
        if cfg.checkpoint_every:
            final_ckpt = manager.save(jax.device_get(state), cfg.total_steps)
        return Result(metrics=history[-1] if history else {},
                      checkpoint=final_ckpt or manager.latest(),
                      metrics_history=history,
                      path=self.run_config.run_dir())


# ---------------------------------------------------------------------------
# Elastic multi-host training
# ---------------------------------------------------------------------------

def _fast_forward_batches(data: Iterator, first_np: Dict[str, Any],
                          start_step: int, data_iter_fn: Callable):
    """Resume semantics shared by SpmdTrainer and the elastic rank fn:
    step i always trains on batch i, so a resumed run SKIPS the
    `start_step` batches the crashed run already consumed instead of
    silently re-training on them. An iterator exposing
    `fast_forward(n)` (stateful loaders: seekable shards, the
    data-service snapshot hook) is asked to seek — absolute: the next
    batch drawn is batch index n. Otherwise batches are drawn and
    discarded, restarting the iterator on exhaustion exactly like the
    training loop's wrap-around (short repeating iterators keep their
    pre-resume alignment only per epoch). `first_np` is batch 0, which
    the caller already drew for init. Returns (batch_for_start_step,
    iterator) — the iterator may have been replaced by a restart."""
    if start_step <= 0:
        return first_np, data
    ff = getattr(data, "fast_forward", None)
    if callable(ff):
        ff(start_step)
        nxt = next(data)
        return {k: np.asarray(v) for k, v in nxt.items()}, data
    out = first_np
    for _ in range(start_step):
        try:
            nxt = next(data)
        except StopIteration:
            data = data_iter_fn()
            nxt = next(data)
        out = {k: np.asarray(v) for k, v in nxt.items()}
    return out, data


def _host_value(leaf):
    """Host copy of one (possibly multi-process) state leaf. Fully
    addressable arrays device_get; fully REPLICATED multi-process
    arrays read their local shard (it holds the whole value). Returns
    None for a leaf that is neither — cross-host sharded state needs a
    coordinated orbax multihost save, which the per-rank checkpoint
    path does not attempt."""
    import jax
    if not isinstance(leaf, jax.Array):
        return np.asarray(leaf)
    if leaf.is_fully_addressable:
        return np.asarray(jax.device_get(leaf))
    if leaf.sharding.is_fully_replicated:
        return np.asarray(leaf.addressable_data(0))
    return None


def _host_state(state):
    """(host_pytree, ok): ok is False when any leaf is cross-host
    sharded (dp/replicated state — the elastic default — is always
    ok)."""
    import jax
    ok = True

    def conv(x):
        nonlocal ok
        v = _host_value(x)
        if v is None:
            ok = False
        return v

    host = jax.tree_util.tree_map(conv, state)
    return host, ok


def _global_batch(batch_np: Dict[str, np.ndarray], bshard,
                  rank: int, world: int):
    """Turn the (identical-on-every-rank) host batch into global device
    arrays sharded per `bshard`: each process uploads only its share of
    the batch dimension (`jax.make_array_from_process_local_data`), so
    per-step input bandwidth scales with hosts. Single-process worlds
    take the plain asarray path."""
    import jax
    import jax.numpy as jnp
    if world <= 1:
        return {k: jnp.asarray(v) for k, v in batch_np.items()}
    out = {}
    for k, v in batch_np.items():
        v = np.asarray(v)
        n = v.shape[0]
        if n % world:
            raise ValueError(
                f"global batch dim {n} of '{k}' must divide the world "
                f"size {world} for per-process sharding")
        share = n // world
        local = v[rank * share:(rank + 1) * share]
        out[k] = jax.make_array_from_process_local_data(bshard[k], local)
    return out


def _sync_world(tag: str, generation: int,
                timeout_ms: int = 180_000) -> None:
    """Rendezvous every rank at the jax coordination service BEFORE the
    first collective computation of a generation. Gloo context init has
    a hard ~30 s store-rendezvous timeout, and ranks reach the first
    collective with wildly different skew (a cold worker pays the full
    flax/optax import + compile while a warm one forked them for free)
    — the coordination-service barrier is plain gRPC with a long
    timeout, so it absorbs the skew and the first collective starts
    aligned on all ranks."""
    try:
        from jax._src import distributed
        client = distributed.global_state.client
        if client is not None:
            client.wait_at_barrier(f"rtpu_{tag}_g{generation}",
                                   timeout_ms)
    except Exception:  # noqa: BLE001 — single-process / API drift: skip
        pass


def _elastic_rank_fn(rank: int, world: int, payload: Dict[str, Any]):
    """One rank's training loop for ElasticSpmdTrainer (runs inside an
    _SpmdHost actor after the jax.distributed join). Restores the last
    committed checkpoint onto THIS world's mesh — which may be smaller
    than the one that wrote it — trains to total_steps, and (rank 0)
    commits checkpoints every checkpoint_every steps."""
    import jax
    from ..util import events
    from ..util import metrics_catalog as mcat
    from .elastic import reshard_mesh_spec

    cfg: Dict[str, Any] = payload
    generation = cfg["generation"]

    trace_path = knobs.get_raw("RAY_TPU_ELASTIC_TRACE")

    def _trace(msg: str) -> None:
        if trace_path:
            with open(f"{trace_path}.r{rank}", "a") as f:
                f.write(f"{time.time():.3f} g{generation} {msg}\n")

    _trace(f"enter world={world} pid={os.getpid()}")
    model = cfg["model"]
    if isinstance(model, str):
        from ..models import get_model
        model = get_model(model)
    devices = jax.devices()
    spec = reshard_mesh_spec(cfg["mesh"], len(devices))
    mesh = build_mesh(spec, devices=devices)

    schedule = warmup_cosine(cfg["learning_rate"], cfg["warmup_steps"],
                             cfg["total_steps"])
    tx = make_optimizer(cfg["optimizer"], schedule=schedule,
                        grad_clip=cfg["grad_clip"])

    data = cfg["data_iter_fn"]()
    first = {k: np.asarray(v) for k, v in next(data).items()}
    init_fn = make_train_step(model, tx, mesh)
    _trace(f"devices={len(devices)} local={jax.local_device_count()} "
           f"sync start")
    if world > 1:
        _sync_world("elastic_warm", generation)
    _trace("init start")
    state, step_fn = init_fn(jax.random.PRNGKey(cfg["seed"]), first)
    _trace("init done")

    manager = CheckpointManager(cfg["ckpt_root"], cfg["num_to_keep"])
    start_step = 0
    latest = manager.latest()
    if latest is not None:
        t0 = time.monotonic()
        state = restore_pytree(latest.path, target=state,
                               shardings=step_fn.state_shardings)
        start_step = int(_host_value(state.step))
        took = time.monotonic() - t0
        if rank == 0:
            events.emit_safe(
                "train.restore",
                f"restored committed checkpoint step {start_step} onto "
                f"a {len(devices)}-device mesh (generation "
                f"{generation}) in {took:.2f}s",
                step=str(start_step), generation=str(generation),
                world=str(world), seconds=f"{took:.3f}")
            try:
                mcat.get("ray_tpu_train_restore_seconds").observe(took)
            except Exception:  # noqa: BLE001 — telemetry never fails work
                pass

    history: List[Dict[str, Any]] = []
    ckpt_every = cfg["checkpoint_every"]
    sharded_save_warned = False
    tokens_acc, t_last = 0, time.time()
    # resume must not re-train on consumed data; skipping is pointless
    # when the restore already reached total_steps (loop won't run)
    batch_np = first
    if start_step < cfg["total_steps"]:
        batch_np, data = _fast_forward_batches(
            data, first, start_step, cfg["data_iter_fn"])
    for i in range(start_step, cfg["total_steps"]):
        _trace(f"step {i}")
        batch = _global_batch(batch_np, step_fn.batch_shardings,
                              rank, world)
        state, metrics = step_fn(state, batch)
        key0 = next(iter(batch_np))
        tokens_acc += int(np.prod(batch_np[key0].shape[:2]))
        if (i + 1) % cfg["log_every"] == 0 or i + 1 == cfg["total_steps"]:
            now = time.time()
            m = {k: float(_host_value(v)) for k, v in metrics.items()}
            m.update(step=i + 1, generation=generation, world=world,
                     tokens_per_s=tokens_acc / max(now - t_last, 1e-9))
            tokens_acc, t_last = 0, now
            history.append(m)
        if ckpt_every and (i + 1) % ckpt_every == 0 and rank == 0:
            host, ok = _host_state(state)
            if ok:
                manager.save(host, i + 1,
                             metadata={"generation": generation,
                                       "world": world})
            elif not sharded_save_warned:
                sharded_save_warned = True
                import warnings
                warnings.warn(
                    "elastic checkpointing skipped: state has "
                    "cross-host sharded leaves (fsdp/tp across "
                    "processes); per-rank commit needs replicated or "
                    "locally-addressable state", stacklevel=1)
        if i + 1 < cfg["total_steps"]:
            try:
                batch_np = {k: np.asarray(v)
                            for k, v in next(data).items()}
            except StopIteration:
                data = cfg["data_iter_fn"]()
                batch_np = {k: np.asarray(v)
                            for k, v in next(data).items()}
    final = None
    if ckpt_every and rank == 0:
        done = manager.latest()
        if done is not None \
                and done.metadata().get("step") == cfg["total_steps"]:
            # restored AT the final step (death raced the last commit):
            # the checkpoint is already committed — re-saving the same
            # path would only re-open the overwrite window
            final = done.path
        else:
            host, ok = _host_state(state)
            if ok:
                final = manager.save(
                    host, cfg["total_steps"],
                    metadata={"generation": generation,
                              "world": world}).path
    # an already-complete restore (death raced the final commit) yields
    # an empty history; the metrics still name the terminal step
    last = history[-1] if history else {
        "step": start_step, "world": world, "generation": generation}
    return {"rank": rank, "world": world, "generation": generation,
            "start_step": start_step, "history": history,
            "metrics": last, "checkpoint": final}


class ElasticSpmdTrainer:
    """Gang-supervised multi-host SpmdTrainer with checkpoint-resume.

    fit() runs the recover cycle end-to-end: train on a supervised
    MultiHostSpmd gang; on a rank death (preempted host, killed worker)
    the supervisor flags it in ~RAY_TPU_GANG_PROBE_S, the gang reforms
    — replaced at full size when the cluster has capacity, otherwise
    RESHARDED onto the surviving world — and every new rank restores
    the last COMMITTED checkpoint onto the new mesh and continues from
    `state.step`. Emits the `train.gang.rank_death` -> `train.gang.
    reform` (/`train.gang.reshard`) -> `train.restore` event chain and
    the ray_tpu_train_gang_reforms_total / _restore_seconds metrics.

    `data_iter_fn` must be deterministic per process (every rank draws
    the same global batch stream and uploads only its shard); resume
    skips batches consumed before the last committed checkpoint.
    """

    def __init__(self, config: SpmdTrainerConfig,
                 data_iter_fn: Callable[[], Iterator[Dict[str, Any]]],
                 *, num_hosts: int,
                 resources_per_host: Optional[Dict[str, float]] = None,
                 env_per_host: Optional[Dict[str, str]] = None,
                 spread: bool = False,
                 run_config: Optional[RunConfig] = None,
                 max_failures: Optional[int] = None,
                 collective_groups: Sequence[str] = ()):
        self.cfg = config
        self.data_iter_fn = data_iter_fn
        self.num_hosts = num_hosts
        self.resources_per_host = resources_per_host
        self.env_per_host = env_per_host
        self.spread = spread
        self.run_config = run_config or RunConfig(name="elastic_spmd")
        if max_failures is None:
            mf = self.run_config.failure_config.max_failures
            max_failures = mf if mf > 0 \
                else knobs.get_int("RAY_TPU_TRAIN_MAX_FAILURES")
        self.max_failures = max_failures
        self.collective_groups = tuple(collective_groups)

    def _payload(self, gang) -> Dict[str, Any]:
        cfg = self.cfg
        ckpt_root = os.path.join(self.run_config.run_dir(), "checkpoints")
        return {
            "model": cfg.model, "mesh": cfg.mesh,
            "optimizer": cfg.optimizer,
            "learning_rate": cfg.learning_rate,
            "warmup_steps": cfg.warmup_steps,
            "total_steps": cfg.total_steps, "log_every": cfg.log_every,
            "checkpoint_every": cfg.checkpoint_every,
            "grad_clip": cfg.grad_clip, "seed": cfg.seed,
            "ckpt_root": ckpt_root,
            "num_to_keep": self.run_config.checkpoint_config.num_to_keep,
            "generation": gang.generation,
            "data_iter_fn": self.data_iter_fn,
        }

    def _await_round(self, gang, refs) -> bool:
        """True when every rank finished; False the moment the
        supervisor flags a death (the refs then belong to a doomed
        world and are abandoned)."""
        import ray_tpu
        pending = list(refs)
        while True:
            if gang.failure is not None:
                return False
            _done, pending = ray_tpu.wait(
                pending, num_returns=len(pending), timeout=0.5)
            if not pending:
                # all refs settled (a just-dead rank's ref settles as an
                # error); the get() in fit() decides success vs reform
                return True

    def fit(self) -> Result:
        import ray_tpu
        from ..exceptions import (ActorDiedError, TaskError,
                                  error_cause_is)
        from .multihost import MultiHostSpmd

        cfg = self.cfg
        if not cfg.checkpoint_every:
            raise ValueError(
                "ElasticSpmdTrainer needs checkpoint_every > 0: without "
                "committed checkpoints a reform would restart from "
                "step 0")
        run_dir = self.run_config.run_dir()
        gang = MultiHostSpmd(
            self.num_hosts, resources_per_host=self.resources_per_host,
            env_per_host=self.env_per_host, spread=self.spread,
            supervised=True, collective_groups=self.collective_groups)
        failures = 0
        try:
            while True:
                refs = gang.run_async(_elastic_rank_fn,
                                      self._payload(gang))
                if self._await_round(gang, refs):
                    try:
                        results = ray_tpu.get(refs, timeout=120)
                        break
                    except (ActorDiedError, TaskError) as e:
                        # A survivor's collateral failure (its collective
                        # died under it) can settle BEFORE the supervisor
                        # flags the rank death — give the 0.25s watch a
                        # grace before calling it a training bug.
                        if isinstance(e, TaskError) \
                                and not error_cause_is(
                                    e, "CollectiveRankDiedError",
                                    "CollectiveStaleGenerationError") \
                                and gang.wait_failure(timeout=3.0) is None:
                            raise   # a training error, not elasticity
                        pass        # gang failure: reform below
                failures += 1
                if failures > self.max_failures:
                    death = gang.failure
                    raise RuntimeError(
                        f"elastic training exceeded max_failures="
                        f"{self.max_failures}; last death: "
                        f"{death and death.cause}")
                gang.reform()
        finally:
            gang.shutdown()
        r0 = results[0]
        manager = CheckpointManager(
            os.path.join(run_dir, "checkpoints"),
            self.run_config.checkpoint_config.num_to_keep)
        from .checkpoint import Checkpoint
        ckpt = (Checkpoint(r0["checkpoint"]) if r0.get("checkpoint")
                else manager.latest())
        return Result(metrics=r0["metrics"], checkpoint=ckpt,
                      metrics_history=r0["history"], path=run_dir,
                      config={"num_hosts": self.num_hosts,
                              "final_world": r0["world"],
                              "generations": gang.generation,
                              "failures": failures})
