"""Lazy task/actor DAGs: `.bind()` builds, `.execute()` runs.

Reference counterpart: python/ray/dag (DAGNode, FunctionNode, ClassNode,
ClassMethodNode, InputNode, MultiOutputNode). Binding records the graph
without running anything; execute() walks it, submits every function/
method node as a normal task with ObjectRefs wired as dependencies, and
returns the terminal ObjectRef(s). The scheduler's dependency tracking
(C4) gives the same pipelining the reference's compiled DAGs get from
ownership: downstream tasks are queued immediately and start the moment
their upstream refs seal.

Serve's deployment graphs (`ray_tpu/serve`) build on the same bind()
idiom.
"""
from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Tuple

_node_ids = itertools.count()


class DAGNode:
    """Base: a recorded, not-yet-executed computation."""

    def __init__(self, bound_args: Tuple, bound_kwargs: Dict[str, Any]):
        self._node_id = next(_node_ids)
        self._bound_args = bound_args
        self._bound_kwargs = bound_kwargs

    # -- traversal --
    def _children(self) -> List["DAGNode"]:
        out = []
        for a in list(self._bound_args) + list(self._bound_kwargs.values()):
            if isinstance(a, DAGNode):
                out.append(a)
        return out

    def _resolve_args(self, ctx: "_ExecContext"):
        args = tuple(ctx.resolve(a) if isinstance(a, DAGNode) else a
                     for a in self._bound_args)
        kwargs = {k: ctx.resolve(v) if isinstance(v, DAGNode) else v
                  for k, v in self._bound_kwargs.items()}
        return args, kwargs

    def _exec(self, ctx: "_ExecContext"):
        raise NotImplementedError

    def execute(self, *input_args, **input_kwargs):
        """Run the DAG; returns ObjectRef (or list for MultiOutputNode)."""
        ctx = _ExecContext(input_args, input_kwargs)
        return ctx.resolve(self)


class _ExecContext:
    def __init__(self, input_args: Tuple, input_kwargs: Dict[str, Any]):
        self.input_args = input_args
        self.input_kwargs = input_kwargs
        self._memo: Dict[int, Any] = {}

    def resolve(self, node: DAGNode):
        if node._node_id not in self._memo:
            self._memo[node._node_id] = node._exec(self)
        return self._memo[node._node_id]


class InputNode(DAGNode):
    """Placeholder for execute()-time input (reference: ray.dag.InputNode).

    Usable as a context manager for the reference idiom:
        with InputNode() as inp:
            dag = f.bind(inp)
    Attribute/index access binds a sub-field of the input.
    """

    def __init__(self):
        super().__init__((), {})

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def _exec(self, ctx: _ExecContext):
        if ctx.input_kwargs or len(ctx.input_args) != 1:
            if not ctx.input_args and not ctx.input_kwargs:
                raise TypeError("DAG has an InputNode; execute() needs an "
                                "argument")
            return (ctx.input_args, ctx.input_kwargs)
        return ctx.input_args[0]

    def __getattr__(self, key: str):
        if key.startswith("_"):
            raise AttributeError(key)
        return InputAttributeNode(self, key, "attr")

    def __getitem__(self, key) -> "InputAttributeNode":
        return InputAttributeNode(self, key, "item")


class InputAttributeNode(DAGNode):
    def __init__(self, parent: InputNode, key, kind: str):
        super().__init__((parent,), {})
        self._key = key
        self._kind = kind

    def _exec(self, ctx: _ExecContext):
        base = ctx.resolve(self._bound_args[0])
        if self._kind == "attr":
            return getattr(base, self._key)
        return base[self._key]


class FunctionNode(DAGNode):
    """A bound remote-function call (reference: ray.dag.FunctionNode)."""

    def __init__(self, remote_fn, args: Tuple, kwargs: Dict[str, Any]):
        super().__init__(args, kwargs)
        self._remote_fn = remote_fn

    def _exec(self, ctx: _ExecContext):
        args, kwargs = self._resolve_args(ctx)
        return self._remote_fn.remote(*args, **kwargs)


class ClassNode(DAGNode):
    """A bound actor construction. The actor is created on first execute
    and reused across executions (reference: compiled-DAG actor reuse)."""

    def __init__(self, actor_cls, args: Tuple, kwargs: Dict[str, Any]):
        super().__init__(args, kwargs)
        self._actor_cls = actor_cls
        self._handle = None

    def __getattr__(self, method_name: str):
        if method_name.startswith("_"):
            raise AttributeError(method_name)
        return _MethodBinder(self, method_name)

    def _exec(self, ctx: _ExecContext):
        if self._handle is None:
            args, kwargs = self._resolve_args(ctx)
            self._handle = self._actor_cls.remote(*args, **kwargs)
        return self._handle


class _MethodBinder:
    def __init__(self, class_node: ClassNode, method_name: str):
        self._class_node = class_node
        self._method_name = method_name

    def bind(self, *args, **kwargs) -> "ClassMethodNode":
        return ClassMethodNode(self._class_node, self._method_name,
                               args, kwargs)


class ClassMethodNode(DAGNode):
    def __init__(self, class_node: ClassNode, method_name: str,
                 args: Tuple, kwargs: Dict[str, Any]):
        super().__init__(args, kwargs)
        self._class_node = class_node
        self._method_name = method_name

    def _exec(self, ctx: _ExecContext):
        handle = ctx.resolve(self._class_node)
        args, kwargs = self._resolve_args(ctx)
        return getattr(handle, self._method_name).remote(*args, **kwargs)


class MultiOutputNode(DAGNode):
    """Bundle several terminal nodes (reference: ray.dag.MultiOutputNode);
    execute() returns their refs as a list."""

    def __init__(self, outputs: List[DAGNode]):
        super().__init__(tuple(outputs), {})

    def _exec(self, ctx: _ExecContext):
        return [ctx.resolve(n) for n in self._bound_args]


__all__ = ["DAGNode", "InputNode", "InputAttributeNode", "FunctionNode",
           "ClassNode", "ClassMethodNode", "MultiOutputNode"]
