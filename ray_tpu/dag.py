"""Lazy task/actor DAGs: `.bind()` builds, `.execute()` runs.

Reference counterpart: python/ray/dag (DAGNode, FunctionNode, ClassNode,
ClassMethodNode, InputNode, MultiOutputNode). Binding records the graph
without running anything; execute() walks it, submits every function/
method node as a normal task with ObjectRefs wired as dependencies, and
returns the terminal ObjectRef(s). The scheduler's dependency tracking
(C4) gives the same pipelining the reference's compiled DAGs get from
ownership: downstream tasks are queued immediately and start the moment
their upstream refs seal.

Serve's deployment graphs (`ray_tpu/serve`) build on the same bind()
idiom.
"""
from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Tuple

_node_ids = itertools.count()


class DAGNode:
    """Base: a recorded, not-yet-executed computation."""

    def __init__(self, bound_args: Tuple, bound_kwargs: Dict[str, Any]):
        self._node_id = next(_node_ids)
        self._bound_args = bound_args
        self._bound_kwargs = bound_kwargs

    # -- traversal --
    def _children(self) -> List["DAGNode"]:
        out = []
        for a in list(self._bound_args) + list(self._bound_kwargs.values()):
            if isinstance(a, DAGNode):
                out.append(a)
        return out

    def _resolve_args(self, ctx: "_ExecContext"):
        args = tuple(ctx.resolve(a) if isinstance(a, DAGNode) else a
                     for a in self._bound_args)
        kwargs = {k: ctx.resolve(v) if isinstance(v, DAGNode) else v
                  for k, v in self._bound_kwargs.items()}
        return args, kwargs

    def _exec(self, ctx: "_ExecContext"):
        raise NotImplementedError

    def execute(self, *input_args, **input_kwargs):
        """Run the DAG; returns ObjectRef (or list for MultiOutputNode)."""
        ctx = _ExecContext(input_args, input_kwargs)
        return ctx.resolve(self)

    def experimental_compile(self) -> "CompiledDAG":
        """Compile the graph once into a reusable level-ordered plan
        (SURVEY C16; reference: ray.dag DAGNode.experimental_compile /
        python/ray/dag/compiled_dag_node.py). Every execute() then
        submits each topological level in ONE batched driver call."""
        return CompiledDAG(self)


class _ExecContext:
    def __init__(self, input_args: Tuple, input_kwargs: Dict[str, Any]):
        self.input_args = input_args
        self.input_kwargs = input_kwargs
        self._memo: Dict[int, Any] = {}

    def resolve(self, node: DAGNode):
        if node._node_id not in self._memo:
            self._memo[node._node_id] = node._exec(self)
        return self._memo[node._node_id]


class InputNode(DAGNode):
    """Placeholder for execute()-time input (reference: ray.dag.InputNode).

    Usable as a context manager for the reference idiom:
        with InputNode() as inp:
            dag = f.bind(inp)
    Attribute/index access binds a sub-field of the input.
    """

    def __init__(self):
        super().__init__((), {})

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def _exec(self, ctx: _ExecContext):
        if ctx.input_kwargs or len(ctx.input_args) != 1:
            if not ctx.input_args and not ctx.input_kwargs:
                raise TypeError("DAG has an InputNode; execute() needs an "
                                "argument")
            return (ctx.input_args, ctx.input_kwargs)
        return ctx.input_args[0]

    def __getattr__(self, key: str):
        if key.startswith("_"):
            raise AttributeError(key)
        return InputAttributeNode(self, key, "attr")

    def __getitem__(self, key) -> "InputAttributeNode":
        return InputAttributeNode(self, key, "item")


class InputAttributeNode(DAGNode):
    def __init__(self, parent: InputNode, key, kind: str):
        super().__init__((parent,), {})
        self._key = key
        self._kind = kind

    def _exec(self, ctx: _ExecContext):
        base = ctx.resolve(self._bound_args[0])
        if self._kind == "attr":
            return getattr(base, self._key)
        return base[self._key]


class FunctionNode(DAGNode):
    """A bound remote-function call (reference: ray.dag.FunctionNode)."""

    def __init__(self, remote_fn, args: Tuple, kwargs: Dict[str, Any]):
        super().__init__(args, kwargs)
        self._remote_fn = remote_fn

    def _exec(self, ctx: _ExecContext):
        args, kwargs = self._resolve_args(ctx)
        return self._remote_fn.remote(*args, **kwargs)


class ClassNode(DAGNode):
    """A bound actor construction. The actor is created on first execute
    and reused across executions (reference: compiled-DAG actor reuse)."""

    def __init__(self, actor_cls, args: Tuple, kwargs: Dict[str, Any]):
        super().__init__(args, kwargs)
        self._actor_cls = actor_cls
        self._handle = None

    def __getattr__(self, method_name: str):
        if method_name.startswith("_"):
            raise AttributeError(method_name)
        return _MethodBinder(self, method_name)

    def _exec(self, ctx: _ExecContext):
        if self._handle is None:
            args, kwargs = self._resolve_args(ctx)
            self._handle = self._actor_cls.remote(*args, **kwargs)
        return self._handle


class _MethodBinder:
    def __init__(self, class_node: ClassNode, method_name: str):
        self._class_node = class_node
        self._method_name = method_name

    def bind(self, *args, **kwargs) -> "ClassMethodNode":
        return ClassMethodNode(self._class_node, self._method_name,
                               args, kwargs)


class ClassMethodNode(DAGNode):
    def __init__(self, class_node: ClassNode, method_name: str,
                 args: Tuple, kwargs: Dict[str, Any]):
        super().__init__(args, kwargs)
        self._class_node = class_node
        self._method_name = method_name

    def _children(self) -> List[DAGNode]:
        # the actor itself is a dependency (compiled scheduling needs
        # the handle materialized before the method spec is built)
        return [self._class_node] + super()._children()

    def _exec(self, ctx: _ExecContext):
        handle = ctx.resolve(self._class_node)
        args, kwargs = self._resolve_args(ctx)
        return getattr(handle, self._method_name).remote(*args, **kwargs)


class MultiOutputNode(DAGNode):
    """Bundle several terminal nodes (reference: ray.dag.MultiOutputNode);
    execute() returns their refs as a list."""

    def __init__(self, outputs: List[DAGNode]):
        super().__init__(tuple(outputs), {})

    def _exec(self, ctx: _ExecContext):
        return [ctx.resolve(n) for n in self._bound_args]


class _CompiledCtx:
    """resolve() view over the compiled executor's value table, so
    inline nodes (Input*, ClassNode, MultiOutput) reuse their _exec."""

    def __init__(self, values: Dict[int, Any], input_args, input_kwargs):
        self._values = values
        self.input_args = input_args
        self.input_kwargs = input_kwargs

    def resolve(self, node: DAGNode):
        return self._values[node._node_id]


class CompiledDAG:
    """A DAG compiled ONCE into a level-ordered submission plan.

    Reference parity: python/ray/dag/compiled_dag_node.py — the
    reference compiles a DAG into a reusable execution loop with
    pre-wired channels between actors; here (single-controller runtime)
    the equivalent win is (a) the graph walk, topological schedule and
    actor construction happen once at compile, not per execute(), and
    (b) every task/method node in a topological level is submitted in a
    SINGLE dispatcher round-trip (runtime.submit_many) instead of one
    per node. Dependency wiring between levels stays ObjectRefs, so the
    scheduler still pipelines across levels.

    `stats` after an execute(): {"levels": N, "submit_calls": M,
    "nodes": K} — M equals the number of levels that contain at least
    one submittable node, once per execute.
    """

    def __init__(self, root: DAGNode):
        self._root = root
        # -- one-time compile: collect + topo-order + level-assign --
        order: List[DAGNode] = []
        seen: Dict[int, DAGNode] = {}
        on_path: set = set()

        def visit(n: DAGNode):
            if n._node_id in seen:
                if n._node_id in on_path:
                    raise ValueError("cycle detected in DAG")
                return
            seen[n._node_id] = n
            on_path.add(n._node_id)
            for c in n._children():
                visit(c)
            on_path.discard(n._node_id)
            order.append(n)              # postorder = topological

        visit(root)
        self._order = order
        self._levels_of: Dict[int, int] = {}
        for n in order:
            dep_lvl = max((self._levels_of[c._node_id]
                           for c in n._children()), default=-1)
            submittable = isinstance(n, (FunctionNode, ClassMethodNode))
            # submittable: one level below its deepest dependency;
            # inline: rides its deepest dependency's level (floor 0)
            self._levels_of[n._node_id] = (dep_lvl + 1 if submittable
                                           else max(dep_lvl, 0))
            if submittable and self._num_returns_of(n) in ("streaming",
                                                           "dynamic"):
                raise NotImplementedError(
                    "streaming (num_returns='streaming') nodes cannot "
                    "be compiled; use .execute() on the lazy DAG")
        self._n_levels = 1 + max(self._levels_of.values(), default=0)
        # fixed level schedule, built once (not rescanned per execute)
        self._levels: List[List[DAGNode]] = [
            [] for _ in range(self._n_levels)]
        for n in order:
            self._levels[self._levels_of[n._node_id]].append(n)
        self.stats = {"levels": self._n_levels, "nodes": len(order),
                      "submit_calls": 0}

    @staticmethod
    def _num_returns_of(n: DAGNode):
        """num_returns a node's .remote() would use — @method(...)
        declarations on the actor class included (the lazy path applies
        them via ActorMethod; the compiled path must match)."""
        if isinstance(n, FunctionNode):
            return n._remote_fn._opts.get("num_returns", 1)
        cls = getattr(n._class_node._actor_cls, "_cls", None)
        fn = getattr(cls, n._method_name, None)
        opts = getattr(fn, "__ray_tpu_method_opts__", None) or {}
        return opts.get("num_returns", 1)

    def execute(self, *input_args, **input_kwargs):
        """Run the compiled plan; same result contract as
        DAGNode.execute()."""
        from .core import runtime as runtime_mod
        rt = runtime_mod.get_runtime()
        values: Dict[int, Any] = {}
        ctx = _CompiledCtx(values, input_args, input_kwargs)
        self.stats["submit_calls"] = 0
        for in_level in self._levels:
            batch: List[tuple] = []
            deferred: List[DAGNode] = []
            for n in in_level:
                if isinstance(n, (FunctionNode, ClassMethodNode)):
                    args = tuple(values[a._node_id]
                                 if isinstance(a, DAGNode) else a
                                 for a in n._bound_args)
                    kwargs = {k: values[v._node_id]
                              if isinstance(v, DAGNode) else v
                              for k, v in n._bound_kwargs.items()}
                    if isinstance(n, FunctionNode):
                        spec, _s = n._remote_fn._make_spec(rt, args,
                                                           kwargs)
                    else:
                        handle = values[n._class_node._node_id]
                        spec, _s = handle._make_task_spec(
                            n._method_name, args, kwargs,
                            self._num_returns_of(n))
                    batch.append((n, spec))
                elif all(c._node_id in values for c in n._children()):
                    values[n._node_id] = n._exec(ctx)
                else:
                    # inline node fed by this level's batch (e.g.
                    # MultiOutputNode): run after submission
                    deferred.append(n)
            if batch:
                ref_lists = rt.submit_many([s for _, s in batch])
                self.stats["submit_calls"] += 1
                for (n, spec), refs in zip(batch, ref_lists):
                    values[n._node_id] = (refs[0] if len(refs) == 1
                                          else refs)
            for n in deferred:
                values[n._node_id] = n._exec(ctx)
        return values[self._root._node_id]


__all__ = ["DAGNode", "InputNode", "InputAttributeNode", "FunctionNode",
           "ClassNode", "ClassMethodNode", "MultiOutputNode",
           "CompiledDAG"]
