"""Lazy task/actor DAGs: `.bind()` builds, `.execute()` runs.

Reference counterpart: python/ray/dag (DAGNode, FunctionNode, ClassNode,
ClassMethodNode, InputNode, MultiOutputNode). Binding records the graph
without running anything; execute() walks it, submits every function/
method node as a normal task with ObjectRefs wired as dependencies, and
returns the terminal ObjectRef(s). The scheduler's dependency tracking
(C4) gives the same pipelining the reference's compiled DAGs get from
ownership: downstream tasks are queued immediately and start the moment
their upstream refs seal.

`experimental_compile()` goes further (docs/DAG.md): when the graph is
pipeline-eligible it resolves placement ONCE — a pinned worker per
stage, dependency-local — wires reusable object channels between them,
and every execute() just pushes the input into the root channels. Data
flows worker->worker with zero driver control messages; the driver only
sees the terminal value. Ineligible graphs (and
RAY_TPU_COMPILED_DAGS=0) fall back to the dynamic level-batched plan,
which submits each topological level in one driver call.

Serve's deployment graphs (`ray_tpu/serve`) build on the same bind()
idiom.
"""
from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Tuple

_node_ids = itertools.count()


class DAGNode:
    """Base: a recorded, not-yet-executed computation."""

    def __init__(self, bound_args: Tuple, bound_kwargs: Dict[str, Any]):
        self._node_id = next(_node_ids)
        self._bound_args = bound_args
        self._bound_kwargs = bound_kwargs

    # -- traversal --
    def _children(self) -> List["DAGNode"]:
        out = []
        for a in list(self._bound_args) + list(self._bound_kwargs.values()):
            if isinstance(a, DAGNode):
                out.append(a)
        return out

    def _resolve_args(self, ctx: "_ExecContext"):
        args = tuple(ctx.resolve(a) if isinstance(a, DAGNode) else a
                     for a in self._bound_args)
        kwargs = {k: ctx.resolve(v) if isinstance(v, DAGNode) else v
                  for k, v in self._bound_kwargs.items()}
        return args, kwargs

    def _exec(self, ctx: "_ExecContext"):
        raise NotImplementedError

    def execute(self, *input_args, **input_kwargs):
        """Run the DAG; returns ObjectRef (or list for MultiOutputNode)."""
        ctx = _ExecContext(input_args, input_kwargs)
        return ctx.resolve(self)

    def experimental_compile(self) -> "CompiledDAG":
        """Compile the graph once into a reusable level-ordered plan
        (SURVEY C16; reference: ray.dag DAGNode.experimental_compile /
        python/ray/dag/compiled_dag_node.py). Every execute() then
        submits each topological level in ONE batched driver call."""
        return CompiledDAG(self)


class _ExecContext:
    def __init__(self, input_args: Tuple, input_kwargs: Dict[str, Any]):
        self.input_args = input_args
        self.input_kwargs = input_kwargs
        self._memo: Dict[int, Any] = {}

    def resolve(self, node: DAGNode):
        if node._node_id not in self._memo:
            self._memo[node._node_id] = node._exec(self)
        return self._memo[node._node_id]


class InputNode(DAGNode):
    """Placeholder for execute()-time input (reference: ray.dag.InputNode).

    Usable as a context manager for the reference idiom:
        with InputNode() as inp:
            dag = f.bind(inp)
    Attribute/index access binds a sub-field of the input.
    """

    def __init__(self):
        super().__init__((), {})

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def _exec(self, ctx: _ExecContext):
        if ctx.input_kwargs or len(ctx.input_args) != 1:
            if not ctx.input_args and not ctx.input_kwargs:
                raise TypeError("DAG has an InputNode; execute() needs an "
                                "argument")
            return (ctx.input_args, ctx.input_kwargs)
        return ctx.input_args[0]

    def __getattr__(self, key: str):
        if key.startswith("_"):
            raise AttributeError(key)
        return InputAttributeNode(self, key, "attr")

    def __getitem__(self, key) -> "InputAttributeNode":
        return InputAttributeNode(self, key, "item")


class InputAttributeNode(DAGNode):
    def __init__(self, parent: InputNode, key, kind: str):
        super().__init__((parent,), {})
        self._key = key
        self._kind = kind

    def _exec(self, ctx: _ExecContext):
        base = ctx.resolve(self._bound_args[0])
        if self._kind == "attr":
            return getattr(base, self._key)
        return base[self._key]


class FunctionNode(DAGNode):
    """A bound remote-function call (reference: ray.dag.FunctionNode)."""

    def __init__(self, remote_fn, args: Tuple, kwargs: Dict[str, Any]):
        super().__init__(args, kwargs)
        self._remote_fn = remote_fn

    def _exec(self, ctx: _ExecContext):
        args, kwargs = self._resolve_args(ctx)
        return self._remote_fn.remote(*args, **kwargs)


class ClassNode(DAGNode):
    """A bound actor construction. The actor is created on first execute
    and reused across executions (reference: compiled-DAG actor reuse)."""

    def __init__(self, actor_cls, args: Tuple, kwargs: Dict[str, Any]):
        super().__init__(args, kwargs)
        self._actor_cls = actor_cls
        self._handle = None

    def __getattr__(self, method_name: str):
        if method_name.startswith("_"):
            raise AttributeError(method_name)
        return _MethodBinder(self, method_name)

    def _exec(self, ctx: _ExecContext):
        if self._handle is None:
            args, kwargs = self._resolve_args(ctx)
            self._handle = self._actor_cls.remote(*args, **kwargs)
        return self._handle


class _MethodBinder:
    def __init__(self, class_node: ClassNode, method_name: str):
        self._class_node = class_node
        self._method_name = method_name

    def bind(self, *args, **kwargs) -> "ClassMethodNode":
        return ClassMethodNode(self._class_node, self._method_name,
                               args, kwargs)


class ClassMethodNode(DAGNode):
    def __init__(self, class_node: ClassNode, method_name: str,
                 args: Tuple, kwargs: Dict[str, Any]):
        super().__init__(args, kwargs)
        self._class_node = class_node
        self._method_name = method_name

    def _children(self) -> List[DAGNode]:
        # the actor itself is a dependency (compiled scheduling needs
        # the handle materialized before the method spec is built)
        return [self._class_node] + super()._children()

    def _exec(self, ctx: _ExecContext):
        handle = ctx.resolve(self._class_node)
        args, kwargs = self._resolve_args(ctx)
        return getattr(handle, self._method_name).remote(*args, **kwargs)


class MultiOutputNode(DAGNode):
    """Bundle several terminal nodes (reference: ray.dag.MultiOutputNode);
    execute() returns their refs as a list."""

    def __init__(self, outputs: List[DAGNode]):
        super().__init__(tuple(outputs), {})

    def _exec(self, ctx: _ExecContext):
        return [ctx.resolve(n) for n in self._bound_args]


class _CompiledCtx:
    """resolve() view over the compiled executor's value table, so
    inline nodes (Input*, ClassNode, MultiOutput) reuse their _exec."""

    def __init__(self, values: Dict[int, Any], input_args, input_kwargs):
        self._values = values
        self.input_args = input_args
        self.input_kwargs = input_kwargs

    def resolve(self, node: DAGNode):
        return self._values[node._node_id]


class _Ineligible(Exception):
    """Graph shape the pipelined engine cannot express; carries the
    reason string surfaced in the dag.exec.fallback event."""


class CompiledDAG:
    """A DAG compiled ONCE — pipelined when eligible, level-batched
    otherwise (docs/DAG.md).

    Reference parity: python/ray/dag/compiled_dag_node.py. In pipelined
    mode the graph gets what the reference's accelerated DAGs get from
    pre-resolved channels: placement happens at compile (one pinned
    worker per task stage, actor stages on their actor's worker,
    dependency-local via scheduling.compiled_stage_node), values move
    over reusable object channels (same-node: one rewritten shm
    segment; cross-node: a persistent socket), and execute() costs the
    driver zero control messages — it writes the input into the root
    channels and the terminal value comes back on the driver's own
    channel host. Worker death / revoked capacity fails in-flight
    executions with a typed CompiledDagError, tears the channels down,
    and the NEXT execute() transparently re-compiles.

    The batched fallback submits each topological level in one
    dispatcher round-trip (runtime.submit_many) — same result contract,
    ObjectRefs between levels.

    `stats`: {"levels", "nodes", "mode", "recompiles", "execs",
    "submit_calls"} — submit_calls counts batched-mode driver calls of
    the LAST execute (always 0 in pipelined mode).
    """

    def __init__(self, root: DAGNode):
        self._root = root
        # -- one-time compile: collect + topo-order + level-assign --
        order: List[DAGNode] = []
        seen: Dict[int, DAGNode] = {}
        on_path: set = set()

        def visit(n: DAGNode):
            if n._node_id in seen:
                if n._node_id in on_path:
                    raise ValueError("cycle detected in DAG")
                return
            seen[n._node_id] = n
            on_path.add(n._node_id)
            for c in n._children():
                visit(c)
            on_path.discard(n._node_id)
            order.append(n)              # postorder = topological

        visit(root)
        self._order = order
        self._levels_of: Dict[int, int] = {}
        for n in order:
            dep_lvl = max((self._levels_of[c._node_id]
                           for c in n._children()), default=-1)
            submittable = isinstance(n, (FunctionNode, ClassMethodNode))
            # submittable: one level below its deepest dependency;
            # inline: rides its deepest dependency's level (floor 0)
            self._levels_of[n._node_id] = (dep_lvl + 1 if submittable
                                           else max(dep_lvl, 0))
            if submittable and self._num_returns_of(n) in ("streaming",
                                                           "dynamic"):
                raise NotImplementedError(
                    "streaming (num_returns='streaming') nodes cannot "
                    "be compiled; use .execute() on the lazy DAG")
        self._n_levels = 1 + max(self._levels_of.values(), default=0)
        # fixed level schedule, built once (not rescanned per execute)
        self._levels: List[List[DAGNode]] = [
            [] for _ in range(self._n_levels)]
        for n in order:
            self._levels[self._levels_of[n._node_id]].append(n)
        self.stats = {"levels": self._n_levels, "nodes": len(order),
                      "submit_calls": 0, "mode": "batched",
                      "recompiles": 0, "execs": 0}
        # -- pipelined-mode eligibility + static plan (docs/DAG.md) --
        self._ctl = None
        self._fallback_reason: Optional[str] = None
        self._fallback_emitted = False
        self._stage_proto: Dict[int, dict] = {}
        self._stage_class_node: Dict[int, "ClassNode"] = {}
        self._drv_exprs: List[Tuple] = []
        self._out_desc: Optional[Tuple] = None
        from .util import knobs  # noqa: PLC0415
        if not knobs.get_bool("RAY_TPU_COMPILED_DAGS"):
            self._mode = "batched"
            self._fallback_reason = "disabled by RAY_TPU_COMPILED_DAGS=0"
        else:
            try:
                self._build_plan()
                self._mode = "pipelined"
                self.stats["mode"] = "pipelined"
            except _Ineligible as e:
                self._mode = "batched"
                self._fallback_reason = str(e)

    # ---------------- pipelined mode ----------------
    def _build_plan(self) -> None:
        """Static analysis: raises _Ineligible unless every node maps
        onto the channel pipeline. Builds per-stage prototypes (args as
        const/input/stage entries) and the output-slot descriptor."""
        from .core.object_ref import ObjectRef  # noqa: PLC0415
        root = self._root

        def expr_of(n) -> Tuple:
            if isinstance(n, InputNode):
                return ("whole",)
            return (("attr", n._key) if n._kind == "attr"
                    else ("item", n._key))

        def entry_of(a) -> Tuple:
            if isinstance(a, (InputNode, InputAttributeNode)):
                return ("input", expr_of(a))
            if isinstance(a, (FunctionNode, ClassMethodNode)):
                return ("stage", a._node_id)
            if isinstance(a, DAGNode):
                raise _Ineligible(
                    f"unsupported argument node {type(a).__name__}")
            if isinstance(a, ObjectRef):
                raise _Ineligible("ObjectRef argument (dynamic value)")
            return ("const", a)

        n_stages = 0
        for n in self._order:
            if isinstance(n, MultiOutputNode) and n is not root:
                raise _Ineligible("MultiOutputNode below the root")
            if isinstance(n, ClassNode):
                for a in (list(n._bound_args)
                          + list(n._bound_kwargs.values())):
                    if isinstance(a, (DAGNode, ObjectRef)):
                        raise _Ineligible(
                            "actor constructor takes a DAG value")
            if not isinstance(n, (FunctionNode, ClassMethodNode)):
                continue
            n_stages += 1
            nr = self._num_returns_of(n) or 1
            if nr != 1 and n is not root:
                raise _Ineligible(
                    "intermediate stage with num_returns != 1")
            if isinstance(n, FunctionNode):
                opts = n._remote_fn._opts
                if opts.get("num_tpus") or opts.get("resources") \
                        or opts.get("max_calls"):
                    raise _Ineligible(
                        "stage needs TPU/custom resources or max_calls")
                if opts.get("placement_group") is not None or (
                        opts.get("scheduling_strategy")
                        not in (None, "DEFAULT")):
                    raise _Ineligible(
                        "stage has placement constraints")
                proto = {"sid": n._node_id, "kind": "func",
                         "fn": n._remote_fn._fn,
                         "name": getattr(n._remote_fn._fn, "__name__",
                                         "dag_stage"),
                         "num_cpus": opts.get("num_cpus") or 1}
            else:
                proto = {"sid": n._node_id, "kind": "method",
                         "method": n._method_name,
                         "name": n._method_name, "num_cpus": 0}
                self._stage_class_node[n._node_id] = n._class_node
            proto["args"] = [entry_of(a) for a in n._bound_args]
            proto["kwargs"] = {k: entry_of(v)
                               for k, v in n._bound_kwargs.items()}
            proto["deps"] = [a._node_id for a in
                             (list(n._bound_args)
                              + list(n._bound_kwargs.values()))
                             if isinstance(a, (FunctionNode,
                                               ClassMethodNode))]
            self._stage_proto[n._node_id] = proto
        if not n_stages:
            raise _Ineligible("no task/method stages to pipeline")
        self._check_no_reentry()
        # output descriptor: what execute() hands back
        if isinstance(root, MultiOutputNode):
            slots = []
            for c in root._bound_args:
                if isinstance(c, (FunctionNode, ClassMethodNode)):
                    if (self._num_returns_of(c) or 1) != 1:
                        raise _Ineligible(
                            "multi-output child with num_returns != 1")
                    slots.append(("stage", c._node_id, None))
                elif isinstance(c, (InputNode, InputAttributeNode)):
                    self._drv_exprs.append(expr_of(c))
                    slots.append(("drv", len(self._drv_exprs) - 1))
                else:
                    raise _Ineligible(
                        "multi-output child is not a stage or input")
            self._out_desc = ("list", slots)
        elif isinstance(root, (FunctionNode, ClassMethodNode)):
            nr = int(self._num_returns_of(root) or 1)
            if nr == 1:
                self._out_desc = ("single",
                                  [("stage", root._node_id, None)])
            else:
                self._out_desc = ("list",
                                  [("stage", root._node_id, i)
                                   for i in range(nr)])
        else:
            raise _Ineligible(
                "root is not a task, method, or MultiOutputNode")

    def _check_no_reentry(self) -> None:
        """Co-located stages (same actor) whose dependency path leaves
        the worker and comes back would deadlock the worker's per-seq
        read barrier — fall back instead."""
        owner: Dict[int, Any] = {}
        for sid in self._stage_proto:
            owner[sid] = self._stage_class_node[sid]._node_id \
                if sid in self._stage_class_node else ("f", sid)
        deps_of = {sid: p["deps"]
                   for sid, p in self._stage_proto.items()}
        groups: Dict[Any, List[int]] = {}
        for sid, own in owner.items():
            if not isinstance(own, tuple):
                groups.setdefault(own, []).append(sid)
        for own, sids in groups.items():
            targets = set(sids)
            for v in sids:
                # DFS upward from v; flag = passed a foreign stage
                stack = [(d, False) for d in deps_of[v]]
                seen = set()
                while stack:
                    s, foreign = stack.pop()
                    if (s, foreign) in seen:
                        continue
                    seen.add((s, foreign))
                    if foreign and s in targets:
                        raise _Ineligible(
                            "actor pipeline re-enters its worker "
                            "through a foreign stage")
                    nxt = foreign or owner[s] != own
                    for d in deps_of[s]:
                        stack.append((d, nxt))

    def _ensure_actors(self, rt) -> None:
        from .util import knobs  # noqa: PLC0415
        timeout = knobs.get_float("RAY_TPU_DAG_COMPILE_TIMEOUT_S")
        for n in self._order:
            if not isinstance(n, ClassNode):
                continue
            if n._handle is not None and rt.actor_state(
                    n._handle.actor_id) in (None, "DEAD"):
                n._handle = None
            if n._handle is None:
                n._handle = n._actor_cls.remote(*n._bound_args,
                                                **n._bound_kwargs)
            rt.wait_actor_alive(n._handle.actor_id, timeout=timeout)

    def _make_cplan(self) -> dict:
        stages = []
        for n in self._order:
            sid = n._node_id
            proto = self._stage_proto.get(sid)
            if proto is None:
                continue
            st = dict(proto)
            if st["kind"] == "method":
                st["actor_id"] = \
                    self._stage_class_node[sid]._handle.actor_id
            stages.append(st)
        return {"stages": stages, "output_slots": self._out_desc[1],
                "drv_exprs": list(self._drv_exprs)}

    def _ensure_controller(self):
        from .core import runtime as runtime_mod  # noqa: PLC0415
        from .core.dag_runtime import DriverDagController  # noqa: PLC0415
        from .exceptions import CompiledDagError  # noqa: PLC0415
        rt = runtime_mod.get_runtime()
        if self._ctl is not None and not self._ctl.dead:
            return self._ctl
        if self._ctl is not None:
            self._ctl = None
            self.stats["recompiles"] += 1
        last_err: Optional[CompiledDagError] = None
        for attempt in (0, 1):
            self._ensure_actors(rt)
            try:
                self._ctl = DriverDagController(rt, self._make_cplan())
                return self._ctl
            except CompiledDagError as e:
                last_err = e
                # a pinned actor died between compiles: reset its
                # handle (restart) and retry once
                cause = getattr(e, "cause", "") or ""
                if attempt == 0 and cause.startswith("actor:") \
                        and cause.endswith(":dead"):
                    aid = cause.split(":")[1]
                    for n in self._order:
                        if isinstance(n, ClassNode) \
                                and n._handle is not None \
                                and n._handle.actor_id == aid:
                            n._handle = None
                    continue
                raise
        raise last_err

    def close(self) -> None:
        """Tear down the pipeline (channels + pinned workers). The
        next execute() re-compiles."""
        ctl, self._ctl = self._ctl, None
        if ctl is not None:
            ctl.close()

    teardown = close

    def __del__(self):
        try:
            if self._ctl is not None and not self._ctl.dead:
                self._ctl.close()
        except Exception:
            pass

    @staticmethod
    def _num_returns_of(n: DAGNode):
        """num_returns a node's .remote() would use — @method(...)
        declarations on the actor class included (the lazy path applies
        them via ActorMethod; the compiled path must match)."""
        if isinstance(n, FunctionNode):
            return n._remote_fn._opts.get("num_returns", 1)
        cls = getattr(n._class_node._actor_cls, "_cls", None)
        fn = getattr(cls, n._method_name, None)
        opts = getattr(fn, "__ray_tpu_method_opts__", None) or {}
        return opts.get("num_returns", 1)

    def execute(self, *input_args, **input_kwargs):
        """Run the compiled plan; same result contract as
        DAGNode.execute(). Pipelined mode returns CompiledDagRef(s)
        (resolved by ray_tpu.get / .get()); batched mode returns
        ObjectRef(s)."""
        self.stats["execs"] += 1
        if self._mode == "pipelined":
            ctl = self._ensure_controller()
            seq = ctl.execute(input_args, input_kwargs)
            kind, slots = self._out_desc
            if kind == "single":
                return ctl.make_ref(seq, slots[0])
            return [ctl.make_ref(seq, s) for s in slots]
        return self._execute_batched(*input_args, **input_kwargs)

    def _execute_batched(self, *input_args, **input_kwargs):
        from .core import runtime as runtime_mod
        rt = runtime_mod.get_runtime()
        if not self._fallback_emitted:
            self._fallback_emitted = True
            try:
                rt._emit("dag.exec.fallback",
                         reason=self._fallback_reason or "explicit")
            except Exception:
                pass
        try:
            from .util import metrics_catalog  # noqa: PLC0415
            metrics_catalog.get("ray_tpu_dag_execs_total").inc(
                tags={"mode": "batched"})
        except Exception:
            pass
        values: Dict[int, Any] = {}
        ctx = _CompiledCtx(values, input_args, input_kwargs)
        self.stats["submit_calls"] = 0
        for in_level in self._levels:
            batch: List[tuple] = []
            deferred: List[DAGNode] = []
            for n in in_level:
                if isinstance(n, (FunctionNode, ClassMethodNode)):
                    args = tuple(values[a._node_id]
                                 if isinstance(a, DAGNode) else a
                                 for a in n._bound_args)
                    kwargs = {k: values[v._node_id]
                              if isinstance(v, DAGNode) else v
                              for k, v in n._bound_kwargs.items()}
                    if isinstance(n, FunctionNode):
                        spec, _s = n._remote_fn._make_spec(rt, args,
                                                           kwargs)
                    else:
                        handle = values[n._class_node._node_id]
                        spec, _s = handle._make_task_spec(
                            n._method_name, args, kwargs,
                            self._num_returns_of(n))
                    batch.append((n, spec))
                elif all(c._node_id in values for c in n._children()):
                    values[n._node_id] = n._exec(ctx)
                else:
                    # inline node fed by this level's batch (e.g.
                    # MultiOutputNode): run after submission
                    deferred.append(n)
            if batch:
                ref_lists = rt.submit_many([s for _, s in batch])
                self.stats["submit_calls"] += 1
                for (n, spec), refs in zip(batch, ref_lists):
                    values[n._node_id] = (refs[0] if len(refs) == 1
                                          else refs)
            for n in deferred:
                values[n._node_id] = n._exec(ctx)
        return values[self._root._node_id]


__all__ = ["DAGNode", "InputNode", "InputAttributeNode", "FunctionNode",
           "ClassNode", "ClassMethodNode", "MultiOutputNode",
           "CompiledDAG", "CompiledDagRef"]


def __getattr__(name):
    # CompiledDagRef re-export without importing core at module load
    # (ray_tpu/__init__ imports this module before core is ready)
    if name == "CompiledDagRef":
        from .core.dag_runtime import CompiledDagRef
        return CompiledDagRef
    raise AttributeError(name)
