"""Host -> HBM double-buffered batch loader.

Reference parity: ray.train.torch.prepare_data_loader's device-mover +
iter_torch_batches prefetching. TPU version: a background thread stages the
NEXT batch's jax.device_put (optionally with a NamedSharding spanning the
mesh) while the current step runs, so HBM fill rides behind compute.

Prefetch depth defaults to the ``RAY_TPU_DATA_PREFETCH_DEPTH`` knob when
``prefetch=None``. Abandoning the iterator mid-stream (``close()`` /
``GeneratorExit`` / garbage collection) signals the producer thread to
stop: its puts are timeout-bounded and re-check a stop event, so it never
parks forever on a full queue the consumer will no longer drain.
"""
from __future__ import annotations

import queue
import threading
from typing import Any, Dict, Iterator, Optional

import numpy as np

_SENTINEL = object()


def device_put_iterator(host_batches: Iterator[Dict[str, np.ndarray]],
                        *, sharding=None, prefetch: Optional[int] = None,
                        dtypes: Optional[Dict[str, Any]] = None):
    import jax
    import jax.numpy as jnp

    if prefetch is None:
        from ..util import knobs
        prefetch = knobs.get_int("RAY_TPU_DATA_PREFETCH_DEPTH")

    def convert(batch):
        out = {}
        for k, v in batch.items():
            arr = np.asarray(v)
            if arr.dtype == object or arr.dtype.kind in ("U", "S"):
                # non-numeric columns (paths, labels-as-text) stay on
                # host — devices only hold numeric arrays (reference:
                # iter_torch_batches passes non-tensor columns through)
                out[k] = arr
                continue
            if dtypes and k in dtypes:
                arr = arr.astype(dtypes[k])
            elif arr.dtype == np.int64:
                arr = arr.astype(np.int32)
            elif arr.dtype == np.float64:
                arr = arr.astype(np.float32)
            out[k] = (jax.device_put(arr, sharding)
                      if sharding is not None else jnp.asarray(arr))
        return out

    q: "queue.Queue" = queue.Queue(maxsize=max(prefetch, 1))
    err: list = []
    stop = threading.Event()

    def bounded_put(item) -> bool:
        """Put that never parks past the stop signal. Returns False if
        the consumer abandoned the iterator."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def producer():
        try:
            for batch in host_batches:
                if not bounded_put(convert(batch)):
                    return  # consumer gone; drop remaining batches
        except BaseException as e:  # noqa: BLE001
            err.append(e)
        finally:
            close = getattr(host_batches, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:
                    pass
            bounded_put(_SENTINEL)

    t = threading.Thread(target=producer, daemon=True,
                         name="rtpu-device-loader")
    t.start()

    try:
        while True:
            # raylint: disable=RT003 the producer's finally ALWAYS posts
            # the sentinel (even on error), and a full queue drains as
            # this consumer iterates — the get cannot park forever
            item = q.get()
            if item is _SENTINEL:
                if err:
                    raise err[0]
                return
            yield item
    finally:
        # consumer abandoned us (GeneratorExit / close / GC) or we hit
        # the sentinel: release the producer, then drain so a put that
        # raced the stop flag cannot strand it
        stop.set()
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass
