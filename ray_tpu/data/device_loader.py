"""Host -> HBM double-buffered batch loader.

Reference parity: ray.train.torch.prepare_data_loader's device-mover +
iter_torch_batches prefetching. TPU version: a background thread stages the
NEXT batch's jax.device_put (optionally with a NamedSharding spanning the
mesh) while the current step runs, so HBM fill rides behind compute.
"""
from __future__ import annotations

import queue
import threading
from typing import Any, Dict, Iterator, Optional

import numpy as np

_SENTINEL = object()


def device_put_iterator(host_batches: Iterator[Dict[str, np.ndarray]],
                        *, sharding=None, prefetch: int = 2,
                        dtypes: Optional[Dict[str, Any]] = None):
    import jax
    import jax.numpy as jnp

    def convert(batch):
        out = {}
        for k, v in batch.items():
            arr = np.asarray(v)
            if arr.dtype == object or arr.dtype.kind in ("U", "S"):
                # non-numeric columns (paths, labels-as-text) stay on
                # host — devices only hold numeric arrays (reference:
                # iter_torch_batches passes non-tensor columns through)
                out[k] = arr
                continue
            if dtypes and k in dtypes:
                arr = arr.astype(dtypes[k])
            elif arr.dtype == np.int64:
                arr = arr.astype(np.int32)
            elif arr.dtype == np.float64:
                arr = arr.astype(np.float32)
            out[k] = (jax.device_put(arr, sharding)
                      if sharding is not None else jnp.asarray(arr))
        return out

    q: "queue.Queue" = queue.Queue(maxsize=max(prefetch, 1))
    err: list = []

    def producer():
        try:
            for batch in host_batches:
                q.put(convert(batch))
        except BaseException as e:  # noqa: BLE001
            err.append(e)
        finally:
            q.put(_SENTINEL)

    t = threading.Thread(target=producer, daemon=True,
                         name="rtpu-device-loader")
    t.start()

    while True:
        # raylint: disable=RT003 the producer's finally ALWAYS posts the
        # sentinel (even on error), and a full queue drains as this
        # consumer iterates — the get cannot park forever
        item = q.get()
        if item is _SENTINEL:
            if err:
                raise err[0]
            return
        yield item
