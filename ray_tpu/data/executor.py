"""Streaming executor for Dataset plans.

Reference parity: python/ray/data/_internal/execution/streaming_executor.py
— per-block tasks flow through the stage chain with bounded in-flight
parallelism (backpressure), stateful stages run on an actor pool, shuffle
stages act as barriers. Runs over the ray_tpu core runtime when
initialized; otherwise executes inline (local mode), which is also the
fast path for small datasets.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence


from ..util import knobs
from .block import Block, block_size_bytes
from .plan import Stage, call_block_fn, fuse_stages

MAX_IN_FLIGHT = 8
# Byte budget for in-flight blocks (VERDICT r4 weak #3: count-only
# backpressure lets 8 x 1-GB blocks pin 8 GB). Mirrors the reference's
# resource-budgeted streaming_executor_state; the count bound still
# applies on top. At least one block is always admitted so a single
# over-budget block can't deadlock the stream.
MAX_IN_FLIGHT_BYTES = knobs.get_int("RAY_TPU_DATA_INFLIGHT_BYTES")


class DatasetStats:
    def __init__(self):
        self.stage_wall: Dict[str, float] = {}
        self.stage_blocks: Dict[str, int] = {}
        # per-exchange instrumentation: map/reduce task counts and the
        # max bytes any single reduce task held (the ~1/N guarantee)
        self.exchange: Dict[str, Dict[str, int]] = {}
        # per-stage backpressure: byte budget + peak in-flight bytes
        self.backpressure: Dict[str, Dict[str, int]] = {}

    def record(self, name: str, dt: float, nblocks: int = 1):
        self.stage_wall[name] = self.stage_wall.get(name, 0.0) + dt
        self.stage_blocks[name] = self.stage_blocks.get(name, 0) + nblocks

    def summary(self) -> str:
        lines = ["Dataset execution stats:"]
        for name, wall in self.stage_wall.items():
            lines.append(f"  {name}: {wall*1000:.1f} ms over "
                         f"{self.stage_blocks.get(name, 0)} blocks")
        for name, ex in self.exchange.items():
            lines.append(
                f"  {name}: {ex['map_tasks']} map + {ex['reduce_tasks']} "
                f"reduce tasks, max reduce input "
                f"{ex['max_reduce_in_bytes']} B")
        for name, bp in self.backpressure.items():
            lines.append(
                f"  {name}: in-flight peak {bp['peak_inflight_bytes']} B "
                f"(budget {bp['budget_bytes']} B)")
        return "\n".join(lines)


def _runtime():
    from ..core import runtime as runtime_mod
    if runtime_mod.runtime_initialized():
        return runtime_mod.get_runtime()
    return None


def _stage_metrics():
    """(inflight-bytes gauge, stall counter, blocks counter); any
    registry failure degrades to None (metrics never break execution)."""
    try:
        from ..util import metrics_catalog as mcat
        return (mcat.get("ray_tpu_data_inflight_bytes"),
                mcat.get("ray_tpu_data_backpressure_stall_s_total"),
                mcat.get("ray_tpu_data_blocks_total"))
    except Exception:
        return None, None, None


def _emit_stall_event(stage_name: str, stall_s: float,
                      peak_bytes: int) -> None:
    """One cluster event per stage run that actually stalled on the
    in-flight budget (the counter metric carries the magnitude; the
    event makes the episode visible in `events` / post-mortems)."""
    if stall_s <= 0:
        return
    try:
        from ..util import events as events_mod
        events_mod.emit(
            "data.executor_stall",
            f"stage {stage_name!r} stalled {stall_s:.3f}s on the "
            f"in-flight backpressure budget",
            stage=stage_name, stall_s=round(stall_s, 4),
            budget_bytes=MAX_IN_FLIGHT_BYTES,
            peak_inflight_bytes=peak_bytes)
    except Exception:
        pass


def _apply_map(fn: Callable[[Block], Block], block: Block,
               index: int = 0) -> Block:
    return call_block_fn(fn, block, index)


class _StatefulMapActor:
    """Actor wrapper for map_batches(compute="actors") with a class fn."""

    def __init__(self, ctor_bytes):
        import cloudpickle
        ctor = cloudpickle.loads(ctor_bytes)
        self.fn = ctor()

    def apply(self, block: Block, index: int = 0) -> Block:
        return call_block_fn(self.fn, block, index)


def execute_plan(source_blocks: Iterator[Block], stages: Sequence[Stage],
                 stats: Optional[DatasetStats] = None,
                 parallelism: int = MAX_IN_FLIGHT,
                 local: bool = False) -> Iterator[Block]:
    """Stream blocks through the fused stage chain.

    ``local=True`` forces the inline execution paths even when the core
    runtime is initialized — used by data-service workers, which are
    themselves actors and must not fan out nested remote tasks.
    """
    stats = stats or DatasetStats()
    stages = fuse_stages(list(stages))
    stream: Iterator[Block] = source_blocks
    for stage in stages:
        stream = _apply_stage(stream, stage, stats, parallelism, local)
    return stream


def _apply_stage(stream: Iterator[Block], stage: Stage, stats: DatasetStats,
                 parallelism: int, local: bool = False) -> Iterator[Block]:
    if stage.kind == "map_block":
        if stage.compute == "actors" and stage.fn_constructor is not None:
            return _actor_pool_map(stream, stage, stats, parallelism, local)
        return _task_map(stream, stage, stats, parallelism, local)
    if stage.kind == "shuffle":
        def shuffled() -> Iterator[Block]:
            t0 = time.time()
            blocks = list(stream)
            out = stage.shuffle_fn(blocks)
            stats.record(stage.name, time.time() - t0, len(out))
            yield from out
        return shuffled()
    if stage.kind == "exchange":
        return _apply_exchange(stream, stage, stats, parallelism, local)
    if stage.kind == "window":
        def windowed() -> Iterator[Block]:
            t0 = time.time()
            n = 0
            for out in stage.window_fn(stream):
                n += 1
                yield out
            stats.record(stage.name, time.time() - t0, n)
        return windowed()
    raise ValueError(f"unknown stage kind {stage.kind}")


def _apply_exchange(stream: Iterator[Block], stage: Stage,
                    stats: DatasetStats,
                    parallelism: int,
                    local_mode: bool = False) -> Iterator[Block]:
    """Distributed two-round shuffle (map-partition + reduce-merge) over
    the core runtime; inline two-round fallback without it."""
    from .exchange import run_exchange_distributed, run_exchange_local
    if not local_mode and _runtime() is not None:
        return run_exchange_distributed(stream, stage.exchange, stats,
                                        parallelism)

    def local() -> Iterator[Block]:
        t0 = time.time()
        out = run_exchange_local(list(stream), stage.exchange)
        stats.record(stage.name, time.time() - t0, len(out))
        yield from out
    return local()


def _task_map(stream: Iterator[Block], stage: Stage, stats: DatasetStats,
              parallelism: int, local: bool = False) -> Iterator[Block]:
    rt = None if local else _runtime()
    if rt is None:
        def local() -> Iterator[Block]:
            for i, block in enumerate(stream):
                t0 = time.time()
                out = call_block_fn(stage.fn, block, i)
                stats.record(stage.name, time.time() - t0)
                yield out
        return local()

    from .. import api

    remote_fn = api.remote(num_cpus=1)(_apply_map)

    def distributed() -> Iterator[Block]:
        import collections
        t_start = time.time()
        window: "collections.deque" = collections.deque()  # (ref, bytes)
        inflight_bytes = 0
        peak = 0
        stall_s = 0.0
        g_inflight, c_stall, c_blocks = _stage_metrics()
        mtags = {"stage": stage.name}
        fn_ref = api.put(stage.fn)  # ship the (possibly fused) fn once

        def drain_one():
            nonlocal inflight_bytes
            ref, nbytes = window.popleft()
            inflight_bytes -= nbytes
            out = api.get(ref)
            if g_inflight is not None:
                g_inflight.set(float(inflight_bytes), tags=mtags)
            return out

        for i, block in enumerate(stream):
            nbytes = block_size_bytes(block)
            # byte budget first (count cap on top); always admit one
            while window and (inflight_bytes + nbytes
                              > MAX_IN_FLIGHT_BYTES
                              or len(window) >= parallelism):
                t0 = time.perf_counter()
                out = drain_one()
                dt = time.perf_counter() - t0
                stall_s += dt
                if c_stall is not None:
                    c_stall.inc(dt, tags=mtags)
                yield out
            window.append((remote_fn.remote(fn_ref, block, i), nbytes))
            inflight_bytes += nbytes
            peak = max(peak, inflight_bytes)
            if g_inflight is not None:
                g_inflight.set(float(inflight_bytes), tags=mtags)
            if c_blocks is not None:
                c_blocks.inc(tags=mtags)
        while window:
            yield drain_one()
        stats.record(stage.name, time.time() - t_start)
        stats.backpressure[stage.name] = {
            "budget_bytes": MAX_IN_FLIGHT_BYTES,
            "peak_inflight_bytes": peak,
            "stall_s": stall_s}
        _emit_stall_event(stage.name, stall_s, peak)
    return distributed()


def _actor_pool_map(stream: Iterator[Block], stage: Stage,
                    stats: DatasetStats, parallelism: int,
                    local: bool = False) -> Iterator[Block]:
    rt = None if local else _runtime()
    import cloudpickle
    ctor_bytes = cloudpickle.dumps(stage.fn_constructor)
    if rt is None:
        fn = stage.fn_constructor()

        def local() -> Iterator[Block]:
            for i, block in enumerate(stream):
                t0 = time.time()
                out = call_block_fn(fn, block, i)
                stats.record(stage.name, time.time() - t0)
                yield out
        return local()

    from .. import api
    pool_size = min(2, parallelism)
    actor_cls = api.remote(num_cpus=1)(_StatefulMapActor)
    actors = [actor_cls.remote(ctor_bytes) for _ in range(pool_size)]

    def distributed() -> Iterator[Block]:
        import collections
        t_start = time.time()
        window: "collections.deque" = collections.deque()  # (ref, bytes)
        inflight_bytes = 0
        peak = 0
        stall_s = 0.0
        i = 0
        g_inflight, c_stall, c_blocks = _stage_metrics()
        mtags = {"stage": stage.name}

        def drain_one():
            nonlocal inflight_bytes
            ref, nbytes = window.popleft()
            inflight_bytes -= nbytes
            out = api.get(ref)
            if g_inflight is not None:
                g_inflight.set(float(inflight_bytes), tags=mtags)
            return out

        for block in stream:
            nbytes = block_size_bytes(block)
            while window and (inflight_bytes + nbytes
                              > MAX_IN_FLIGHT_BYTES
                              or len(window) >= parallelism):
                t0 = time.perf_counter()
                out = drain_one()
                dt = time.perf_counter() - t0
                stall_s += dt
                if c_stall is not None:
                    c_stall.inc(dt, tags=mtags)
                yield out
            actor = actors[i % pool_size]
            window.append((actor.apply.remote(block, i), nbytes))
            i += 1
            inflight_bytes += nbytes
            peak = max(peak, inflight_bytes)
            if g_inflight is not None:
                g_inflight.set(float(inflight_bytes), tags=mtags)
            if c_blocks is not None:
                c_blocks.inc(tags=mtags)
        while window:
            yield drain_one()
        stats.record(stage.name, time.time() - t_start)
        stats.backpressure[stage.name] = {
            "budget_bytes": MAX_IN_FLIGHT_BYTES,
            "peak_inflight_bytes": peak,
            "stall_s": stall_s}
        _emit_stall_event(stage.name, stall_s, peak)
        for a in actors:
            try:
                api.kill(a)
            except Exception:
                pass
    return distributed()
