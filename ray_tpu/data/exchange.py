"""Distributed exchange (shuffle) execution: map-partition + reduce-merge.

Reference parity: python/ray/data/_internal/planner/exchange/
(push_based_shuffle_task_scheduler.py, pull_based_shuffle_task_scheduler.py,
sort_task_spec.py). The reference fans each input block out to N partition
pieces via map tasks, then merges piece i from every map via reduce tasks —
no process ever holds more than ~1/N of the dataset. ray_tpu re-designs the
same two-round exchange over its own runtime:

  map round:   one task per input block — `partition_fn` splits the block
               into `n_parts` pieces, each piece `put()` into the shm store
               from the worker; only the (tiny) piece refs return.
  reduce round: one task per partition — receives the matching piece refs
               as top-level args (the runtime resolves them to values in
               the worker), merges via `reduce_fn`, returns output blocks.

The driver holds refs + at most one in-flight output block (bounded
window); input refs are freed after the map round and piece refs after
each reduce, so store residency decays as the exchange drains.

Sort/groupby use sampled range partitioning (reference sort_task_spec.py's
SortTaskSpec.sample_boundaries): the driver gathers per-block key samples,
picks n-1 quantile boundaries, and range-partitions so reduce outputs are
globally ordered end-to-end.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, List, Optional

import numpy as np

from .block import (Block, block_concat, block_num_rows, block_sort,
                    block_take)

# piece sample cap per block for boundary estimation
_SAMPLE_PER_BLOCK = 64


@dataclasses.dataclass
class ExchangeSpec:
    """A two-round distributed exchange.

    partition_fn(block, block_idx, n_parts, meta) -> List[Block] of
        exactly n_parts pieces (piece j goes to reduce task j).
    reduce_fn(pieces, part_idx, meta) -> List[Block] output blocks.
    sample_fn(block) -> small ndarray used by meta_fn (e.g. key samples).
    meta_fn(samples, counts, n_parts) -> broadcast metadata (boundaries,
        global offsets, ...) shipped to every map/reduce task.
    """
    name: str
    partition_fn: Callable[[Block, int, int, Any], List[Block]]
    reduce_fn: Callable[[List[Block], int, Any], List[Block]]
    n_partitions: Optional[int] = None   # default: len(input blocks)
    sample_fn: Optional[Callable[[Block], np.ndarray]] = None
    meta_fn: Optional[Callable[[list, list, int], Any]] = None


def exchange_map_task(partition_fn, block, block_idx, n_parts, meta):
    """Map round body (runs in a worker): partition and put each piece
    separately so a reduce task fetches only its own 1/n_parts share."""
    from .. import api
    pieces = partition_fn(block, block_idx, n_parts, meta)
    assert len(pieces) == n_parts, (len(pieces), n_parts)
    return [api.put(p) for p in pieces]


def exchange_reduce_task(reduce_fn, part_idx, meta, *pieces):
    """Reduce round body: pieces arrive as values (refs resolved by the
    runtime). Returns (out_blocks, in_bytes) — in_bytes instruments the
    1/N-footprint guarantee for stats/tests."""
    from .block import block_size_bytes
    in_bytes = sum(block_size_bytes(p) for p in pieces)
    return reduce_fn(list(pieces), part_idx, meta), in_bytes


# ---------------------------------------------------------------------------
# concrete exchanges


def random_shuffle_spec(seed: Optional[int]) -> ExchangeSpec:
    """Uniform global permutation: map assigns each row an independent
    uniform partition, reduce permutes its merged rows. Deterministic for
    a fixed seed (per-block / per-partition derived streams)."""
    if seed is None:
        # non-deterministic run: draw a fresh base seed once
        seed = int(np.random.randint(0, 2**31 - 1))

    def partition(block: Block, block_idx: int, n_parts: int,
                  meta: Any) -> List[Block]:
        rng = np.random.RandomState((seed * 1_000_003 + block_idx)
                                    % (2**32 - 1))
        assign = rng.randint(0, n_parts, size=block_num_rows(block))
        return [block_take(block, np.nonzero(assign == j)[0])
                for j in range(n_parts)]

    def reduce(pieces: List[Block], part_idx: int, meta: Any) -> List[Block]:
        merged = block_concat(pieces)
        n = block_num_rows(merged)
        if n == 0:
            return []
        rng = np.random.RandomState((seed * 7_368_787 + 31 + part_idx)
                                    % (2**32 - 1))
        return [block_take(merged, rng.permutation(n))]

    return ExchangeSpec("random_shuffle", partition, reduce)


def repartition_spec(num_blocks: int) -> ExchangeSpec:
    """Contiguous re-chunking: row order is preserved; output block j
    holds global rows [j*per, (j+1)*per)."""
    def meta(samples: list, counts: List[int], n_parts: int):
        offsets = np.concatenate([[0], np.cumsum(counts)])
        total = int(offsets[-1])
        per = -(-total // max(n_parts, 1))  # ceil
        return {"offsets": offsets, "per": max(per, 1)}

    def partition(block: Block, block_idx: int, n_parts: int,
                  meta: Any) -> List[Block]:
        start = int(meta["offsets"][block_idx])
        per = meta["per"]
        n = block_num_rows(block)
        gids = (start + np.arange(n)) // per
        return [block_take(block, np.nonzero(gids == j)[0])
                for j in range(n_parts)]

    def reduce(pieces: List[Block], part_idx: int, meta: Any) -> List[Block]:
        merged = block_concat(pieces)  # map order == global row order
        return [merged] if block_num_rows(merged) else []

    return ExchangeSpec(f"repartition({num_blocks})", partition, reduce,
                        n_partitions=num_blocks, meta_fn=meta)


def _boundaries_from_samples(samples: list, n_parts: int) -> np.ndarray:
    allv = (np.concatenate([s for s in samples if len(s)])
            if any(len(s) for s in samples) else np.asarray([]))
    if allv.size == 0 or n_parts <= 1:
        return np.asarray([])
    allv = np.sort(allv)
    idx = (np.arange(1, n_parts) * allv.size) // n_parts
    return allv[np.minimum(idx, allv.size - 1)]


def sort_spec(key: str, descending: bool) -> ExchangeSpec:
    """Sampled range partition + per-partition sort => globally sorted
    output (reference sort_task_spec.py). Descending is handled by
    reversing both the partition ids and the in-partition sort."""
    def sample(block: Block) -> np.ndarray:
        keys = block[key]
        if len(keys) <= _SAMPLE_PER_BLOCK:
            return np.asarray(keys)
        step = len(keys) // _SAMPLE_PER_BLOCK
        return np.asarray(keys[::step][:_SAMPLE_PER_BLOCK])

    def meta(samples: list, counts: List[int], n_parts: int):
        return {"bounds": _boundaries_from_samples(samples, n_parts)}

    def partition(block: Block, block_idx: int, n_parts: int,
                  meta: Any) -> List[Block]:
        bounds = meta["bounds"]
        ids = (np.searchsorted(bounds, block[key], side="right")
               if len(bounds) else np.zeros(block_num_rows(block), np.int64))
        if descending:
            ids = (n_parts - 1) - ids
        return [block_take(block, np.nonzero(ids == j)[0])
                for j in range(n_parts)]

    def reduce(pieces: List[Block], part_idx: int, meta: Any) -> List[Block]:
        merged = block_concat(pieces)
        if not block_num_rows(merged):
            return []
        return [block_sort(merged, key, descending)]

    return ExchangeSpec(f"sort({key})", partition, reduce,
                        sample_fn=sample, meta_fn=meta)


def groupby_agg_spec(key: str, aggs: List[tuple],
                     agg_factory: Callable) -> ExchangeSpec:
    """Range-partition rows by group key (samples, like sort) so every
    group lands wholly in one partition AND partitions come out in
    ascending key order — preserving the single-process implementation's
    sorted-by-key output. Reduce groups + aggregates its partition."""
    def sample(block: Block) -> np.ndarray:
        keys = block[key]
        step = max(1, len(keys) // _SAMPLE_PER_BLOCK)
        return np.asarray(keys[::step][:_SAMPLE_PER_BLOCK])

    def meta(samples: list, counts: List[int], n_parts: int):
        return {"bounds": _boundaries_from_samples(samples, n_parts)}

    def partition(block: Block, block_idx: int, n_parts: int,
                  meta: Any) -> List[Block]:
        bounds = meta["bounds"]
        ids = (np.searchsorted(bounds, block[key], side="right")
               if len(bounds) else np.zeros(block_num_rows(block), np.int64))
        return [block_take(block, np.nonzero(ids == j)[0])
                for j in range(n_parts)]

    def reduce(pieces: List[Block], part_idx: int, meta: Any) -> List[Block]:
        from .block import block_from_rows
        merged = block_concat(pieces)
        if not block_num_rows(merged):
            return []
        keys = merged[key]
        rows = []
        for kval in np.unique(keys):   # np.unique returns sorted keys
            mask = keys == kval
            row = {key: kval.item() if hasattr(kval, "item") else kval}
            for kind, col in aggs:
                agg = agg_factory(kind, col or key)
                vals = merged[col][mask] if col else \
                    next(iter(merged.values()))[mask]
                row[agg.name] = agg.finalize(
                    agg.accumulate(agg.init(), vals))
            rows.append(row)
        return [block_from_rows(rows)]

    return ExchangeSpec(f"groupby({key})", partition, reduce,
                        sample_fn=sample, meta_fn=meta)


def groupby_map_spec(key: str, fn: Callable) -> ExchangeSpec:
    """GroupedData.map_groups (reference grouped_data.py): range-
    partition by key so each group lands wholly in one reduce task,
    then apply `fn` to each group's block; results concatenate in
    ascending key order."""
    base = groupby_agg_spec(key, [], lambda *a: None)

    def reduce(pieces: List[Block], part_idx: int, meta: Any) -> List[Block]:
        merged = block_concat(pieces)
        if not block_num_rows(merged):
            return []
        keys = merged[key]
        out: List[Block] = []
        for kval in np.unique(keys):   # sorted group order
            mask = keys == kval
            res = fn({c: v[mask] for c, v in merged.items()})
            if res and block_num_rows(res):
                out.append(res)
        return out

    return ExchangeSpec(f"map_groups({key})", base.partition_fn, reduce,
                        sample_fn=base.sample_fn, meta_fn=base.meta_fn)


# ---------------------------------------------------------------------------
# execution


def run_exchange_local(blocks: List[Block], spec: ExchangeSpec
                       ) -> List[Block]:
    """Inline fallback when the runtime isn't initialized: identical
    two-round structure, one process (small-data / unit-test path)."""
    n_parts = spec.n_partitions or max(1, len(blocks))
    samples = [spec.sample_fn(b) for b in blocks] if spec.sample_fn else []
    counts = [block_num_rows(b) for b in blocks]
    meta = spec.meta_fn(samples, counts, n_parts) if spec.meta_fn else None
    buckets: List[List[Block]] = [[] for _ in range(n_parts)]
    for i, b in enumerate(blocks):
        for j, piece in enumerate(spec.partition_fn(b, i, n_parts, meta)):
            buckets[j].append(piece)
    out: List[Block] = []
    for j in range(n_parts):
        out.extend(spec.reduce_fn(buckets[j], j, meta))
    return out


def run_exchange_distributed(stream, spec: ExchangeSpec, stats,
                             parallelism: int):
    """Two-round exchange over the core runtime. Yields output blocks.

    Driver residency: refs + one in-flight result; every piece travels
    worker->store->worker without the driver touching its bytes.
    """
    import time

    from .. import api

    t0 = time.time()
    # Every store ref the exchange creates registers here and is removed
    # as it's freed; the finally block frees the remainder, so an
    # abandoned generator (e.g. .take(5) breaking out mid-drain) cannot
    # pin the dataset in the shm store.
    live: dict = {}

    def track(ref):
        live[ref.id] = ref
        return ref

    def untrack_free(refs):
        for r in refs:
            live.pop(r.id, None)
        api.free(refs)

    max_reduce_bytes = 0
    n_out = 0
    n_maps = 0
    n_parts = 0
    # Peer-transfer accounting: pieces travel worker->store->worker, and
    # on a multi-node cluster the transfer plane moves them holder->
    # requester directly — any driver-relayed byte during the exchange
    # shows up in this delta (0 on a healthy peer path).
    from ..core import runtime as _rt_mod
    _rt = _rt_mod.get_runtime() if _rt_mod.runtime_initialized() else None
    relay_before = getattr(_rt, "relay_bytes", 0)
    try:
        block_refs: List[Any] = []
        samples: list = []
        counts: List[int] = []
        for b in stream:
            counts.append(block_num_rows(b))
            if spec.sample_fn:
                samples.append(spec.sample_fn(b))
            # driver drops the block right away
            block_refs.append(track(api.put(b)))
        if not block_refs:
            return
        n_maps = len(block_refs)
        n_parts = spec.n_partitions or max(1, n_maps)
        meta = (spec.meta_fn(samples, counts, n_parts)
                if spec.meta_fn else None)

        map_remote = api.remote(num_cpus=1)(exchange_map_task)
        reduce_remote = api.remote(num_cpus=1)(exchange_reduce_task)
        pfn_ref = track(api.put(spec.partition_fn))
        meta_ref = track(api.put(meta))

        # map round: bounded submission window; results are tiny ref lists
        piece_refs: List[List[Any]] = []
        pending: List[Any] = []

        def pop_map_result():
            ref = track(pending.pop(0))
            pieces = api.get(ref)
            for p in pieces:
                track(p)
            piece_refs.append(pieces)
            untrack_free([ref])  # the ref-list envelope, not the pieces
        for i, bref in enumerate(block_refs):
            pending.append(map_remote.remote(pfn_ref, bref, i, n_parts,
                                             meta_ref))
            if len(pending) >= parallelism:
                pop_map_result()
        while pending:
            pop_map_result()
        untrack_free(block_refs)  # inputs fully partitioned; drop them

        rfn_ref = track(api.put(spec.reduce_fn))

        inflight: List[tuple] = []  # (result_ref, pieces_to_free)

        def drain_one():
            nonlocal max_reduce_bytes, n_out
            ref, to_free = inflight.pop(0)
            out_blocks, in_bytes = api.get(ref)
            max_reduce_bytes = max(max_reduce_bytes, in_bytes)
            # pieces + the consumed result object
            untrack_free(to_free + [ref])
            n_out += len(out_blocks)
            return out_blocks

        for j in range(n_parts):
            pieces_j = [pr[j] for pr in piece_refs]
            inflight.append(
                (track(reduce_remote.remote(rfn_ref, j, meta_ref,
                                            *pieces_j)),
                 pieces_j))
            if len(inflight) >= max(2, parallelism // 2):
                yield from drain_one()
        while inflight:
            yield from drain_one()
    finally:
        if live:
            api.free(list(live.values()))
            live.clear()
        stats.record(spec.name, time.time() - t0, n_out)
        stats.exchange[spec.name] = {
            "map_tasks": n_maps, "reduce_tasks": n_parts,
            "max_reduce_in_bytes": int(max_reduce_bytes),
            "relay_bytes": int(getattr(_rt, "relay_bytes", 0)
                               - relay_before)}
