"""Logical plan + optimizer for Datasets.

Reference parity: python/ray/data/_internal/logical/ (operators) and
_internal/planner/ (fusion). The plan is a linear chain of stages over
blocks; the optimizer fuses adjacent row/batch transforms into one task
per block (same goal as the reference's OperatorFusionRule).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from .block import (Block, block_concat, block_from_rows, block_num_rows,
                    block_select, block_slice, block_sort, block_take,
                    block_to_rows)


@dataclasses.dataclass
class Stage:
    name: str
    # "map_block" | "shuffle" | "exchange" | "window" | "source"
    kind: str
    fn: Optional[Callable] = None  # map_block: Block -> Block
    shuffle_fn: Optional[Callable] = None  # shuffle: List[Block] -> List[Block]
    can_fuse: bool = True
    compute: str = "tasks"         # "tasks" | "actors"
    fn_constructor: Optional[Callable] = None  # for actor compute
    exchange: Optional[Any] = None  # ExchangeSpec for kind="exchange"
    # window: Iterator[Block] -> Iterator[Block], streaming (holds only
    # a bounded carry — never the whole dataset)
    window_fn: Optional[Callable] = None


def map_rows_stage(name: str, row_fn: Callable[[Dict], Optional[Dict]],
                   *, flat: bool = False, drop_none: bool = False) -> Stage:
    def fn(block: Block) -> Block:
        out_rows: List[Dict] = []
        for row in block_to_rows(block):
            r = row_fn(row)
            if r is None and drop_none:
                continue
            if flat:
                out_rows.extend(r)
            else:
                out_rows.append(r)
        return block_from_rows(out_rows)
    return Stage(name=name, kind="map_block", fn=fn)


def filter_stage(name: str, pred: Callable[[Dict], bool]) -> Stage:
    def fn(block: Block) -> Block:
        if not block:
            return block
        mask = np.asarray([bool(pred(r)) for r in block_to_rows(block)])
        return block_select(block, mask)
    return Stage(name=name, kind="map_block", fn=fn)


def map_batches_stage(name: str, batch_fn: Callable[[Block], Block],
                      compute: str = "tasks",
                      fn_constructor: Optional[Callable] = None) -> Stage:
    return Stage(name=name, kind="map_block", fn=batch_fn, compute=compute,
                 fn_constructor=fn_constructor,
                 can_fuse=(compute == "tasks"))


def fn_wants_index(fn: Callable) -> bool:
    """Stage fns marked `_wants_block_index = True` receive the block's
    position in the stage's input stream as a second argument — the
    hook that lets per-block randomness (random_sample) derive seeds
    from a value that SURVIVES serialization to workers, instead of a
    closure counter that restarts at 0 in every deserialized copy."""
    return bool(getattr(fn, "_wants_block_index", False))


def call_block_fn(fn: Callable, block: Block, index: int) -> Block:
    return fn(block, index) if fn_wants_index(fn) else fn(block)


def fuse_stages(stages: Sequence[Stage]) -> List[Stage]:
    """Fuse runs of adjacent fusible map_block stages into single stages."""
    fused: List[Stage] = []
    run: List[Stage] = []

    def flush():
        nonlocal run
        if not run:
            return
        if len(run) == 1:
            fused.append(run[0])
        else:
            fns = [s.fn for s in run]
            name = "+".join(s.name for s in run)

            def combined(block: Block, _index: int = 0,
                         fns=fns) -> Block:
                for f in fns:
                    block = call_block_fn(f, block, _index)
                return block
            combined._wants_block_index = any(
                fn_wants_index(f) for f in fns)
            fused.append(Stage(name=name, kind="map_block", fn=combined))
        run = []

    for s in stages:
        if s.kind == "map_block" and s.can_fuse and s.compute == "tasks":
            run.append(s)
        else:
            flush()
            fused.append(s)
    flush()
    return fused
