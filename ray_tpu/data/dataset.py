"""Dataset: lazy, distributed, streaming data pipelines.

Reference parity: python/ray/data/dataset.py (transform/consume verbs),
read_api.py (sources), grouped_data.py (groupby/aggregate),
iterator.py (iter_batches). Execution goes through
ray_tpu/data/executor.py; terminal `iter_jax_batches` double-buffers
host->HBM transfers (device_loader.py) so the accelerator never waits on
input (reference: iter_torch_batches + its prefetching).
"""
from __future__ import annotations

import dataclasses
import itertools
import math
from typing import (Any, Callable, Dict, Iterator, List, Optional, Sequence,
                    Tuple, Union)

import numpy as np

from .block import (Block, block_concat, block_from_rows, block_num_rows,
                    block_select, block_slice, block_take,
                    block_to_rows,
                    block_size_bytes)
from .executor import DatasetStats, execute_plan
from .plan import (Stage, filter_stage, map_batches_stage, map_rows_stage)

DEFAULT_BLOCK_ROWS = 1024


@dataclasses.dataclass
class _Source:
    name: str
    make_blocks: Callable[[], Iterator[Block]]
    num_rows: Optional[int] = None


class Dataset:
    def __init__(self, source: _Source, stages: Tuple[Stage, ...] = ()):
        self._source = source
        self._stages = tuple(stages)
        self._stats = DatasetStats()
        self._materialized: Optional[List[Block]] = None

    # ---------------- transforms (lazy) ----------------
    def _with_stage(self, stage: Stage) -> "Dataset":
        return Dataset(self._source, self._stages + (stage,))

    def map(self, fn: Callable[[Dict], Dict]) -> "Dataset":
        return self._with_stage(map_rows_stage(f"map({_name(fn)})", fn))

    def flat_map(self, fn: Callable[[Dict], List[Dict]]) -> "Dataset":
        return self._with_stage(
            map_rows_stage(f"flat_map({_name(fn)})", fn, flat=True))

    def filter(self, pred: Callable[[Dict], bool]) -> "Dataset":
        return self._with_stage(filter_stage(f"filter({_name(pred)})", pred))

    def map_batches(self, fn, *, batch_size: Optional[int] = None,
                    compute: str = "tasks",
                    fn_constructor_args: Tuple = ()) -> "Dataset":
        if isinstance(fn, type):
            ctor = (lambda fn=fn, a=fn_constructor_args: fn(*a))
            stage = map_batches_stage(f"map_batches({fn.__name__})",
                                      None, compute="actors",
                                      fn_constructor=ctor)
        else:
            stage = map_batches_stage(f"map_batches({_name(fn)})", fn,
                                      compute=compute)
        ds = self._with_stage(stage)
        if batch_size is not None:
            return ds._rebatched(batch_size)
        return ds

    def add_column(self, name: str, fn: Callable[[Block], np.ndarray]
                   ) -> "Dataset":
        def add(block: Block) -> Block:
            out = dict(block)
            out[name] = np.asarray(fn(block))
            return out
        return self._with_stage(map_batches_stage(f"add_column({name})", add))

    def drop_columns(self, cols: Sequence[str]) -> "Dataset":
        cols = set(cols)
        return self._with_stage(map_batches_stage(
            f"drop_columns({sorted(cols)})",
            lambda b: {k: v for k, v in b.items() if k not in cols}))

    def select_columns(self, cols: Sequence[str]) -> "Dataset":
        keep = list(cols)
        return self._with_stage(map_batches_stage(
            f"select_columns({keep})",
            lambda b: {k: b[k] for k in keep}))

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        return self._with_stage(map_batches_stage(
            f"rename_columns({mapping})",
            lambda b: {mapping.get(k, k): v for k, v in b.items()}))

    def _rebatched(self, rows_per_block: int) -> "Dataset":
        """STREAMING re-chunk: holds at most (carry + one block), never
        the concatenated dataset (pre-r5 this block_concat'd it all)."""
        def window_fn(stream):
            # parts accumulate as SLICES and concat once per emitted
            # batch — concatenating the carry into every incoming block
            # would copy O(rows_per_block^2) rows for tiny input blocks
            parts: List[Block] = []
            have = 0
            for block in stream:
                i = 0
                n = block_num_rows(block)
                while i < n:
                    take = min(rows_per_block - have, n - i)
                    parts.append(block_slice(block, i, i + take))
                    have += take
                    i += take
                    if have == rows_per_block:
                        yield block_concat(parts)
                        parts, have = [], 0
            if have:
                yield block_concat(parts)
        return self._with_stage(Stage(
            name=f"rebatch({rows_per_block})", kind="window",
            window_fn=window_fn))

    # ---------------- shuffles (distributed exchanges) ----------------
    # Each is a two-round map-partition + reduce-merge exchange over the
    # core runtime (ray_tpu/data/exchange.py) — no process ever holds the
    # concatenated dataset, unlike the pre-r5 block_concat implementations
    # (VERDICT r4 missing #1; reference: _internal/planner/exchange/).
    def repartition(self, num_blocks: int) -> "Dataset":
        from .exchange import repartition_spec
        spec = repartition_spec(num_blocks)
        return self._with_stage(Stage(name=spec.name, kind="exchange",
                                      exchange=spec))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        from .exchange import random_shuffle_spec
        spec = random_shuffle_spec(seed)
        return self._with_stage(Stage(name=spec.name, kind="exchange",
                                      exchange=spec))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        from .exchange import sort_spec
        spec = sort_spec(key, descending)
        return self._with_stage(Stage(name=spec.name, kind="exchange",
                                      exchange=spec))

    def random_sample(self, fraction: float,
                      *, seed: Optional[int] = None) -> "Dataset":
        """Bernoulli sample each row with probability `fraction`
        (reference: Dataset.random_sample) — a vectorized per-block
        mask, deterministic per (seed, block index)."""
        if not 0.0 <= fraction <= 1.0:
            raise ValueError(f"fraction must be in [0, 1], got {fraction}")

        def sample(block: Block, block_index: int = 0) -> Block:
            n = block_num_rows(block)
            # Seed from (seed, block_index) — the index is threaded
            # through the stage by the executor, so every deserialized
            # worker copy of this fn derives the SAME per-block stream.
            # A closure counter here would restart at 0 in each copy and
            # correlate masks across blocks under distributed execution.
            rng = np.random.default_rng(
                None if seed is None
                else (seed & 0xFFFF_FFFF_FFFF_FFFF, block_index))
            keep = rng.random(n) < fraction
            return {k: np.asarray(v)[keep] for k, v in block.items()}

        sample._wants_block_index = True
        return self._with_stage(map_batches_stage(
            f"random_sample({fraction})", sample))

    def limit(self, n: int) -> "Dataset":
        def shuffle_fn(blocks: List[Block]) -> List[Block]:
            out, got = [], 0
            for b in blocks:
                take = min(block_num_rows(b), n - got)
                if take > 0:
                    out.append(block_slice(b, 0, take))
                    got += take
                if got >= n:
                    break
            return out
        return self._with_stage(Stage(name=f"limit({n})", kind="shuffle",
                                      shuffle_fn=shuffle_fn))

    def union(self, other: "Dataset") -> "Dataset":
        left, right = self, other

        def make_blocks():
            yield from left.iter_blocks()
            yield from right.iter_blocks()
        return Dataset(_Source("union", make_blocks))

    def zip(self, other: "Dataset") -> "Dataset":
        """Row-aligned column zip, STREAMING: both sides advance block
        by block with bounded carries — the pre-r5 version concatenated
        BOTH datasets wholesale. Extra rows on the longer side drop
        (reference zip semantics: truncate to the shorter)."""
        left, right = self, other

        def make_blocks():
            rit = right.iter_blocks()
            rcarry: Optional[Block] = None
            right_done = False
            for lb in left.iter_blocks():
                need = block_num_rows(lb)
                if need == 0:
                    continue   # empty left block (e.g. filtered out)
                parts: List[Block] = []
                got = 0
                while got < need:
                    if rcarry is None or not block_num_rows(rcarry):
                        rcarry = next(rit, None)
                        if rcarry is None:
                            right_done = True
                            break
                    take = min(block_num_rows(rcarry), need - got)
                    parts.append(block_slice(rcarry, 0, take))
                    rcarry = block_slice(rcarry, take,
                                         block_num_rows(rcarry))
                    got += take
                if got:
                    rb = block_concat(parts)
                    merged = dict(block_slice(lb, 0, got))
                    for k, v in rb.items():
                        merged[k if k not in merged else f"{k}_1"] = v
                    yield merged
                if right_done:
                    return   # truncate to the shorter side
        return Dataset(_Source("zip", make_blocks))

    def groupby(self, key: str) -> "GroupedData":
        return GroupedData(self, key)

    # ---------------- splits ----------------
    def split(self, n: int) -> List["Dataset"]:
        """Contiguous n-way split at BLOCK granularity: interior blocks
        pass through by reference, only boundary blocks are sliced —
        no whole-dataset concatenation (same approach as
        split_proportionately)."""
        blocks = list(self.iter_blocks())
        counts = [block_num_rows(b) for b in blocks]
        total = sum(counts)
        per = math.ceil(total / n)
        out: List["Dataset"] = []
        bi, off = 0, 0
        for i in range(n):
            need = min(per, total - i * per) if total > i * per else 0
            parts: List[Block] = []
            while need > 0 and bi < len(blocks):
                take = min(counts[bi] - off, need)
                if take == counts[bi] and off == 0:
                    parts.append(blocks[bi])
                else:
                    parts.append(block_slice(blocks[bi], off, off + take))
                need -= take
                off += take
                if off >= counts[bi]:
                    bi += 1
                    off = 0
            out.append(from_blocks(parts, name=f"split_{i}"))
        return out

    def split_proportionately(self, fractions: Sequence[float]
                              ) -> List["Dataset"]:
        """Split by row fractions; the remainder forms a final dataset
        (reference: Dataset.split_proportionately — n fractions yield
        n+1 datasets)."""
        if not fractions or sum(fractions) >= 1.0 \
                or any(f <= 0 for f in fractions):
            raise ValueError("fractions must be positive and sum to <1")
        # one plan execution at BLOCK granularity: only boundary blocks
        # are sliced; interior blocks pass through by reference (no
        # per-row materialization in driver memory)
        blocks = list(self.iter_blocks())
        counts = [block_num_rows(b) for b in blocks]
        total = sum(counts)
        sizes = [int(total * f) for f in fractions]
        sizes.append(total - sum(sizes))
        out: List["Dataset"] = []
        bi, off = 0, 0
        for sz in sizes:
            need = sz
            parts: List[Block] = []
            while need > 0 and bi < len(blocks):
                take = min(counts[bi] - off, need)
                if take == counts[bi] and off == 0:
                    parts.append(blocks[bi])
                else:
                    parts.append(block_slice(blocks[bi], off, off + take))
                need -= take
                off += take
                if off >= counts[bi]:
                    bi += 1
                    off = 0
            out.append(from_blocks(parts, name="split_prop"))
        return out

    def train_test_split(self, test_size: float, *,
                         shuffle: bool = False,
                         seed: Optional[int] = None
                         ) -> Tuple["Dataset", "Dataset"]:
        """(train, test) split (reference: Dataset.train_test_split)."""
        if not 0.0 < test_size < 1.0:
            raise ValueError("test_size must be in (0, 1)")
        ds = self.random_shuffle(seed=seed) if shuffle else self
        train, test = ds.split_proportionately([1.0 - test_size])
        return train, test

    def streaming_split(self, n: int) -> List["Dataset"]:
        """Round-robin block split; each shard re-streams the parent."""
        parent = self

        def make_shard(idx):
            def make_blocks():
                for i, b in enumerate(parent.iter_blocks()):
                    if i % n == idx:
                        yield b
            return Dataset(_Source(f"stream_split_{idx}", make_blocks))
        return [make_shard(i) for i in range(n)]

    def split_for_worker(self, rank: int, world: int) -> "Dataset":
        return self.streaming_split(world)[rank]

    def to_service(self, job_name: str, *, mode: str = "fcfs",
                   world_size: int = 1, epochs: int = 1,
                   dataset_name: Optional[str] = None,
                   n_slices: Optional[int] = None) -> str:
        """Register this dataset's plan with the shared data service.

        The plan runs once on the service's data-worker pool no matter
        how many jobs consume it; consumers obtain per-consumer
        iterators via ``data.service.iterator(job_name, rank=...)``.
        Returns the dataset key (jobs registering the same plan — or
        the same explicit ``dataset_name`` — share production). See
        docs/DATA_SERVICE.md.
        """
        from . import service
        return service.register(self, job_name, mode=mode,
                                world_size=world_size, epochs=epochs,
                                dataset_name=dataset_name,
                                n_slices=n_slices)

    # ---------------- execution ----------------
    def iter_blocks(self) -> Iterator[Block]:
        if self._materialized is not None:
            yield from self._materialized
            return
        yield from execute_plan(self._source.make_blocks(), self._stages,
                                self._stats)

    def materialize(self) -> "Dataset":
        self._materialized = list(self.iter_blocks())
        return self

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for block in self.iter_blocks():
            yield from block_to_rows(block)

    def iter_batches(self, *, batch_size: int = 256,
                     drop_last: bool = False,
                     local_shuffle_buffer_size: Optional[int] = None,
                     local_shuffle_seed: Optional[int] = None
                     ) -> Iterator[Block]:
        """Batches of `batch_size` rows. local_shuffle_buffer_size
        enables reference-style windowed shuffling at iteration time: a
        rolling buffer of at least that many rows is sampled without
        replacement per batch — an O(buffer) approximate shuffle, no
        full-dataset pass (reference: iter_batches
        local_shuffle_buffer_size)."""
        if local_shuffle_buffer_size:
            yield from self._iter_batches_shuffled(
                batch_size, drop_last, local_shuffle_buffer_size,
                local_shuffle_seed)
            return
        carry: Optional[Block] = None
        for block in self.iter_blocks():
            if carry is not None:
                block = block_concat([carry, block])
                carry = None
            n = block_num_rows(block)
            i = 0
            while n - i >= batch_size:
                yield block_slice(block, i, i + batch_size)
                i += batch_size
            if i < n:
                carry = block_slice(block, i, n)
        if carry is not None and not drop_last:
            yield carry

    def _iter_batches_shuffled(self, batch_size: int, drop_last: bool,
                               buffer_rows: int,
                               seed: Optional[int]) -> Iterator[Block]:
        rng = np.random.RandomState(seed)
        buf: Optional[Block] = None
        for block in self.iter_blocks():
            buf = block if buf is None else block_concat([buf, block])
            while block_num_rows(buf) >= buffer_rows + batch_size:
                pick = rng.choice(block_num_rows(buf), batch_size,
                                  replace=False)
                yield block_take(buf, pick)
                keep = np.ones(block_num_rows(buf), bool)
                keep[pick] = False
                buf = block_select(buf, keep)
        if buf is not None:
            order = rng.permutation(block_num_rows(buf))
            buf = block_take(buf, order)
            n = block_num_rows(buf)
            for i in range(0, n, batch_size):
                if i + batch_size <= n:
                    yield block_slice(buf, i, i + batch_size)
                elif not drop_last:
                    yield block_slice(buf, i, n)

    def iter_jax_batches(self, *, batch_size: int = 256,
                         drop_last: bool = True, sharding=None,
                         prefetch: int = 2,
                         dtypes: Optional[Dict[str, Any]] = None):
        from .device_loader import device_put_iterator
        host_iter = self.iter_batches(batch_size=batch_size,
                                      drop_last=drop_last)
        return device_put_iterator(host_iter, sharding=sharding,
                                   prefetch=prefetch, dtypes=dtypes)

    def iter_torch_batches(self, *, batch_size: int = 256,
                           drop_last: bool = False, device=None,
                           dtypes: Optional[Dict[str, Any]] = None):
        """Batches as torch tensors (reference: iter_torch_batches).
        Object-dtype columns (strings) pass through as-is."""
        import torch  # noqa: PLC0415
        for batch in self.iter_batches(batch_size=batch_size,
                                       drop_last=drop_last):
            out = {}
            for k, v in batch.items():
                if v.dtype == object:
                    out[k] = v
                    continue
                t = torch.from_numpy(np.ascontiguousarray(v))
                if dtypes and k in dtypes:
                    t = t.to(dtypes[k])
                if device is not None:
                    t = t.to(device)
                out[k] = t
            yield out

    # ---------------- consumption ----------------
    def take(self, n: int = 20) -> List[Dict[str, Any]]:
        out = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[Dict[str, Any]]:
        return list(self.iter_rows())

    def count(self) -> int:
        if self._source.num_rows is not None and not self._stages:
            return self._source.num_rows
        return sum(block_num_rows(b) for b in self.iter_blocks())

    def schema(self) -> Dict[str, Any]:
        for block in self.iter_blocks():
            return {k: v.dtype for k, v in block.items()}
        return {}

    def columns(self) -> List[str]:
        return list(self.schema().keys())

    # ---- global aggregate terminals (reference: Dataset.sum/mean/...) ----
    def _col_blocks(self, col: str):
        for block in self.iter_blocks():
            if col not in block:
                raise KeyError(f"no column {col!r}; have "
                               f"{list(block.keys())}")
            yield np.asarray(block[col])

    def sum(self, col: str):
        return sum(b.sum() for b in self._col_blocks(col))

    def min(self, col: str):
        return min(b.min() for b in self._col_blocks(col))

    def max(self, col: str):
        return max(b.max() for b in self._col_blocks(col))

    def mean(self, col: str) -> float:
        total, n = 0.0, 0
        for b in self._col_blocks(col):
            total += float(b.sum())
            n += b.size
        return total / max(n, 1)

    def std(self, col: str, ddof: int = 1) -> float:
        # two-pass over streamed blocks: exact, no full materialization
        mu = self.mean(col)
        ssq, n = 0.0, 0
        for b in self._col_blocks(col):
            ssq += float(((b - mu) ** 2).sum())
            n += b.size
        return math.sqrt(ssq / max(n - ddof, 1))

    def unique(self, col: str) -> List[Any]:
        seen = set()
        out: List[Any] = []
        for b in self._col_blocks(col):
            for v in np.unique(b):
                key = v.item() if hasattr(v, "item") else v
                if key not in seen:
                    seen.add(key)
                    out.append(key)
        return out

    def size_bytes(self) -> int:
        return sum(block_size_bytes(b) for b in self.iter_blocks())

    def stats(self) -> str:
        return self._stats.summary()

    def stats_object(self) -> DatasetStats:
        """The raw DatasetStats (per-stage wall/blocks + per-exchange
        map/reduce task counts and max reduce-task bytes)."""
        return self._stats

    def show(self, n: int = 20) -> None:
        for row in self.take(n):
            print(row)

    # ---- writes (reference: Dataset.write_csv/json/numpy/parquet; one
    # part-<i> file per block into a directory) ----
    def _write_parts(self, path: str, ext: str, write_block) -> List[str]:
        import os as osmod
        osmod.makedirs(path, exist_ok=True)
        out = []
        for i, block in enumerate(self.iter_blocks()):
            fname = osmod.path.join(path, f"part-{i:05d}.{ext}")
            write_block(fname, block)
            out.append(fname)
        return out

    def write_csv(self, path: str) -> List[str]:
        import csv as csvmod

        def wb(fname, block):
            cols = list(block.keys())
            n = len(next(iter(block.values()))) if block else 0
            # csv.writer quotes/escapes commas, quotes, and newlines —
            # pairs with read_csv's csv.DictReader
            with open(fname, "w", newline="") as f:
                w = csvmod.writer(f)
                w.writerow(cols)
                for r in range(n):
                    w.writerow([block[c][r] for c in cols])
        return self._write_parts(path, "csv", wb)

    def write_jsonl(self, path: str) -> List[str]:
        import json as jsonmod

        def wb(fname, block):
            cols = list(block.keys())
            n = len(next(iter(block.values()))) if block else 0
            with open(fname, "w") as f:
                for r in range(n):
                    row = {c: block[c][r].item()
                           if hasattr(block[c][r], "item")
                           else block[c][r] for c in cols}
                    f.write(jsonmod.dumps(row) + "\n")
        return self._write_parts(path, "jsonl", wb)

    def write_json(self, path: str) -> List[str]:
        return self.write_jsonl(path)

    def write_npy(self, path: str, column: str) -> List[str]:
        def wb(fname, block):
            # write through the handle: np.save(path) would append a
            # second .npy to the part name
            with open(fname, "wb") as f:
                np.save(f, np.asarray(block[column]))
        return self._write_parts(path, "npy", wb)

    def to_pandas(self, limit: Optional[int] = None):
        """Materialize into one pandas DataFrame (reference:
        Dataset.to_pandas). `limit` caps rows like the reference's
        default guard; None = no cap."""
        import pandas as pd  # noqa: PLC0415
        frames = []
        seen = 0
        for block in self.iter_blocks():
            n = len(next(iter(block.values()))) if block else 0
            if limit is not None and seen + n > limit:
                block = {k: v[:limit - seen] for k, v in block.items()}
                n = limit - seen
            frames.append(pd.DataFrame(
                {k: np.asarray(v) for k, v in block.items()}))
            seen += n
            if limit is not None and seen >= limit:
                break
        if not frames:
            return pd.DataFrame()
        return pd.concat(frames, ignore_index=True)

    def write_parquet(self, path: str) -> List[str]:
        import pyarrow as pa  # noqa: PLC0415
        import pyarrow.parquet as pq  # noqa: PLC0415

        def wb(fname, block):
            table = pa.table({k: pa.array(np.asarray(v))
                              for k, v in block.items()})
            pq.write_table(table, fname)
        return self._write_parts(path, "parquet", wb)

    def __repr__(self):
        stages = " -> ".join(s.name for s in self._stages) or "identity"
        return f"Dataset(source={self._source.name}, plan={stages})"


# ---------------- grouped data ----------------
@dataclasses.dataclass
class AggregateFn:
    name: str
    init: Callable[[], Any]
    accumulate: Callable[[Any, np.ndarray], Any]
    finalize: Callable[[Any], Any]


def _builtin_agg(kind: str, col: str) -> AggregateFn:
    if kind == "count":
        return AggregateFn(f"count()", lambda: 0,
                           lambda acc, v: acc + len(v), lambda acc: acc)
    ops = {
        "sum": (lambda: 0.0, lambda acc, v: acc + v.sum(), lambda a: a),
        "min": (lambda: np.inf, lambda acc, v: min(acc, v.min()),
                lambda a: a),
        "max": (lambda: -np.inf, lambda acc, v: max(acc, v.max()),
                lambda a: a),
        "mean": (lambda: (0.0, 0), lambda acc, v: (acc[0] + v.sum(),
                                                   acc[1] + len(v)),
                 lambda a: a[0] / max(a[1], 1)),
        "std": (lambda: (0.0, 0.0, 0),
                lambda acc, v: (acc[0] + v.sum(),
                                acc[1] + (v.astype(np.float64) ** 2).sum(),
                                acc[2] + len(v)),
                lambda a: float(np.sqrt(max(
                    a[1] / max(a[2], 1) - (a[0] / max(a[2], 1)) ** 2, 0.0)))),
    }
    init, acc, fin = ops[kind]
    return AggregateFn(f"{kind}({col})", init, acc, fin)


class GroupedData:
    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def _aggregate(self, aggs: List[Tuple[str, Optional[str]]]) -> Dataset:
        """Distributed: range-partition rows by group key (sampled
        boundaries, like sort) so each group lands wholly in one reduce
        task AND the concatenated output stays globally key-sorted —
        identical ordering to the pre-r5 single-process implementation."""
        from .exchange import groupby_agg_spec
        spec = groupby_agg_spec(self._key, list(aggs), _builtin_agg)
        return self._ds._with_stage(Stage(name=spec.name, kind="exchange",
                                          exchange=spec))

    def count(self) -> Dataset:
        return self._aggregate([("count", None)])

    def sum(self, col: str) -> Dataset:
        return self._aggregate([("sum", col)])

    def mean(self, col: str) -> Dataset:
        return self._aggregate([("mean", col)])

    def min(self, col: str) -> Dataset:
        return self._aggregate([("min", col)])

    def max(self, col: str) -> Dataset:
        return self._aggregate([("max", col)])

    def std(self, col: str) -> Dataset:
        return self._aggregate([("std", col)])

    def aggregate(self, *specs: Tuple[str, str]) -> Dataset:
        return self._aggregate(list(specs))

    def map_groups(self, fn: Callable[[Block], Block]) -> Dataset:
        """Apply `fn` to each group's block (reference:
        GroupedData.map_groups); distributed like the aggregations —
        each group lands wholly in one reduce task, output stays in
        ascending key order."""
        from .exchange import groupby_map_spec
        spec = groupby_map_spec(self._key, fn)
        return self._ds._with_stage(Stage(name=spec.name, kind="exchange",
                                          exchange=spec))


def _name(fn) -> str:
    return getattr(fn, "__name__", "fn")


# ---------------- sources (read_api parity) ----------------
def from_blocks(blocks: List[Block], name: str = "blocks") -> Dataset:
    n = sum(block_num_rows(b) for b in blocks)
    return Dataset(_Source(name, lambda: iter(list(blocks)), num_rows=n))


def from_items(items: List[Any],
               block_rows: int = DEFAULT_BLOCK_ROWS) -> Dataset:
    rows = [it if isinstance(it, dict) else {"item": it} for it in items]
    blocks = [block_from_rows(rows[i:i + block_rows])
              for i in range(0, len(rows), block_rows)]
    return from_blocks(blocks, "from_items")


def range_(n: int, block_rows: int = DEFAULT_BLOCK_ROWS) -> Dataset:
    def make_blocks():
        for i in range(0, n, block_rows):
            hi = min(i + block_rows, n)
            yield {"id": np.arange(i, hi, dtype=np.int64)}
    return Dataset(_Source("range", make_blocks, num_rows=n))


def from_numpy(arrays: Dict[str, np.ndarray],
               block_rows: int = DEFAULT_BLOCK_ROWS) -> Dataset:
    n = len(next(iter(arrays.values())))

    def make_blocks():
        for i in range(0, n, block_rows):
            yield {k: v[i:min(i + block_rows, n)] for k, v in arrays.items()}
    return Dataset(_Source("from_numpy", make_blocks, num_rows=n))


def from_pandas(df, block_rows: int = DEFAULT_BLOCK_ROWS) -> Dataset:
    """pandas DataFrame -> numpy-columnar Dataset (reference:
    ray.data.from_pandas; object-dtype columns stay object arrays)."""
    arrays = {str(col): df[col].to_numpy() for col in df.columns}
    if not arrays:
        return from_items([])
    return from_numpy(arrays, block_rows)


def read_text(path: str, block_rows: int = DEFAULT_BLOCK_ROWS) -> Dataset:
    def make_blocks():
        with open(path) as f:
            lines = [ln.rstrip("\n") for ln in f]
        for i in range(0, len(lines), block_rows):
            yield {"text": np.asarray(lines[i:i + block_rows], dtype=object)}
    return Dataset(_Source(f"read_text({path})", make_blocks))


def read_jsonl(path: str, block_rows: int = DEFAULT_BLOCK_ROWS) -> Dataset:
    import json

    def make_blocks():
        rows = []
        with open(path) as f:
            for ln in f:
                if ln.strip():
                    rows.append(json.loads(ln))
        for i in range(0, len(rows), block_rows):
            yield block_from_rows(rows[i:i + block_rows])
    return Dataset(_Source(f"read_jsonl({path})", make_blocks))


def read_csv(path: str, block_rows: int = DEFAULT_BLOCK_ROWS) -> Dataset:
    import csv

    def make_blocks():
        with open(path) as f:
            rows = list(csv.DictReader(f))
        conv = []
        for r in rows:
            out = {}
            for k, v in r.items():
                try:
                    out[k] = float(v) if "." in v else int(v)
                except (ValueError, TypeError):
                    out[k] = v
            conv.append(out)
        for i in range(0, len(conv), block_rows):
            yield block_from_rows(conv[i:i + block_rows])
    return Dataset(_Source(f"read_csv({path})", make_blocks))


def read_npy(path: str, column: str = "data",
             block_rows: int = DEFAULT_BLOCK_ROWS) -> Dataset:
    arr = np.load(path)
    return from_numpy({column: arr}, block_rows)


def _list_files(path: str, *, suffixes=None,
                pattern: str = "*") -> List[str]:
    """Shared reader file listing: directory (recursive) or single
    file; case-insensitive suffix filter; deterministic order."""
    import glob as globmod
    import os as osmod
    if not osmod.path.isdir(path):
        return [path]
    sfx = (None if suffixes is None
           else tuple(s.lower() for s in suffixes))
    files = sorted(
        f for f in globmod.glob(osmod.path.join(path, "**", pattern),
                                recursive=True)
        if osmod.path.isfile(f)
        and (sfx is None or f.lower().endswith(sfx)))
    if not files:
        raise FileNotFoundError(f"no matching files under {path!r}")
    return files


_IMAGE_EXTS = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp")


def read_images(path: str, *, size: Optional[Tuple[int, int]] = None,
                mode: str = "RGB", include_paths: bool = False,
                block_rows: int = 64) -> Dataset:
    """Directory (recursive) or single file of images -> blocks with an
    "image" column of uint8 arrays (reference: python/ray/data
    read_api.py read_images — Arrow/PIL there; numpy blocks + PIL here,
    feeding the ViT/CLIP pipeline of BASELINE config 3).

    size=(H, W) resizes at decode so the column stacks into one dense
    (N, H, W, C) array per block — the layout iter_jax_batches ships to
    TPU. Without `size`, images keep their native resolutions as an
    object column (stack later with a map_batches resize).
    """
    from PIL import Image

    files = _list_files(path, suffixes=_IMAGE_EXTS)

    def decode(fp: str) -> np.ndarray:
        with Image.open(fp) as im:
            im = im.convert(mode)
            if size is not None:
                im = im.resize((size[1], size[0]))  # PIL wants (W, H)
            return np.asarray(im, dtype=np.uint8)

    def make_blocks():
        for i in range(0, len(files), block_rows):
            chunk = files[i:i + block_rows]
            imgs = [decode(f) for f in chunk]
            if size is not None:
                col = np.stack(imgs)
            else:
                col = np.empty(len(imgs), dtype=object)
                for j, a in enumerate(imgs):
                    col[j] = a
            block: Block = {"image": col}
            if include_paths:
                block["path"] = np.asarray(chunk, dtype=object)
            yield block

    return Dataset(_Source(f"read_images({path})", make_blocks,
                           num_rows=len(files)))


def read_binary_files(path: str, *, include_paths: bool = True,
                      suffixes: Optional[Sequence[str]] = None,
                      block_rows: int = 64) -> Dataset:
    """Directory (recursive) or single file -> blocks with a "bytes"
    object column (+ "path"). Reference: read_api.py
    read_binary_files — the escape hatch for formats without a
    dedicated reader."""
    files = _list_files(path, suffixes=suffixes)

    def make_blocks():
        for i in range(0, len(files), block_rows):
            chunk = files[i:i + block_rows]
            col = np.empty(len(chunk), dtype=object)
            for j, f in enumerate(chunk):
                with open(f, "rb") as fh:
                    col[j] = fh.read()
            block: Block = {"bytes": col}
            if include_paths:
                block["path"] = np.asarray(chunk, dtype=object)
            yield block

    return Dataset(_Source(f"read_binary_files({path})", make_blocks,
                           num_rows=len(files)))


def _tfrecord_records(path: str):
    """Iterate raw record payloads of one TFRecord file. Framing per the
    public format: uint64 length, uint32 masked-crc(length), payload,
    uint32 masked-crc(payload); CRCs are not verified (no snappy/crc32c
    dependency in-image)."""
    import struct
    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if not header:
                return                     # clean EOF between records
            if len(header) < 8:
                raise ValueError(f"truncated TFRecord {path!r} "
                                 f"(partial length header)")
            (length,) = struct.unpack("<Q", header)
            if len(f.read(4)) < 4:         # length crc
                raise ValueError(f"truncated TFRecord {path!r} "
                                 f"(missing length crc)")
            payload = f.read(length)
            if len(payload) < length:
                raise ValueError(f"truncated TFRecord {path!r}")
            if len(f.read(4)) < 4:         # payload crc
                raise ValueError(f"truncated TFRecord {path!r} "
                                 f"(missing payload crc)")
            yield payload


def read_tfrecords(path: str, *, parse_fn: Optional[Callable] = None,
                   block_rows: int = DEFAULT_BLOCK_ROWS) -> Dataset:
    """TFRecord file(s) -> blocks (reference: read_api.py
    read_tfrecords; Arrow/TFX there, numpy blocks here).

    Default rows are {"bytes": record} — pass parse_fn(record_bytes) ->
    dict to decode (e.g. a tf.train.Example parser via the protobuf
    runtime); its dicts become columnar blocks."""
    files = _list_files(path, pattern="*.tfrecord*")

    def make_blocks():
        rows: List[Dict[str, Any]] = []
        for f in files:
            for rec in _tfrecord_records(f):
                rows.append(parse_fn(rec) if parse_fn
                            else {"bytes": rec})
                if len(rows) >= block_rows:
                    yield block_from_rows(rows)
                    rows = []
        if rows:
            yield block_from_rows(rows)

    return Dataset(_Source(f"read_tfrecords({path})", make_blocks))


def read_parquet(path: str,
                 block_rows: int = DEFAULT_BLOCK_ROWS,
                 columns=None) -> Dataset:
    """Parquet file(s) -> numpy-columnar blocks, one block per row group
    (re-chunked to block_rows). `path` may be a file or a directory of
    .parquet files. Reference: python/ray/data read_parquet (Arrow-backed
    there; columns land as numpy here like every other block)."""
    import glob as globmod
    import os as osmod
    try:
        import pyarrow.parquet as pq  # noqa: PLC0415
    except ImportError as e:  # pragma: no cover - baked into this image
        raise ImportError("read_parquet requires pyarrow") from e

    if osmod.path.isdir(path):
        files = sorted(globmod.glob(osmod.path.join(path, "*.parquet")))
        if not files:
            raise FileNotFoundError(
                f"no *.parquet files in directory {path!r}")
    else:
        files = [path]

    def make_blocks():
        for f in files:
            pf = pq.ParquetFile(f)
            for batch in pf.iter_batches(batch_size=block_rows,
                                         columns=columns):
                cols = {}
                for name, col in zip(batch.schema.names, batch.columns):
                    arr = col.to_numpy(zero_copy_only=False)
                    if not arr.flags.writeable:
                        # Arrow hands out read-only views; every other
                        # source yields mutable blocks, so copy for a
                        # consistent contract.
                        arr = np.array(arr)
                    cols[name] = arr
                yield cols
    return Dataset(_Source(f"read_parquet({path})", make_blocks))
