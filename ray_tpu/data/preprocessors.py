"""Preprocessors (reference: python/ray/data/preprocessor.py +
preprocessors/{scaler,encoder,chain,batch_mapper}.py): fit on a Dataset,
transform Datasets or single batches."""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from .block import Block
from .dataset import Dataset


class Preprocessor:
    _fitted = False

    def fit(self, ds: Dataset) -> "Preprocessor":
        self._fit(ds)
        self._fitted = True
        return self

    def transform(self, ds: Dataset) -> Dataset:
        if not self._fitted and self._needs_fit():
            raise RuntimeError(f"{type(self).__name__} must be fit() first")
        return ds.map_batches(self.transform_batch)

    def fit_transform(self, ds: Dataset) -> Dataset:
        return self.fit(ds).transform(ds)

    def _fit(self, ds: Dataset) -> None:
        pass

    def _needs_fit(self) -> bool:
        return True

    def transform_batch(self, batch: Block) -> Block:
        raise NotImplementedError


class StandardScaler(Preprocessor):
    def __init__(self, columns: Sequence[str]):
        self.columns = list(columns)
        self.stats: Dict[str, tuple] = {}

    def _fit(self, ds: Dataset) -> None:
        sums = {c: (0.0, 0.0, 0) for c in self.columns}
        for block in ds.iter_blocks():
            for c in self.columns:
                v = block[c].astype(np.float64)
                s, s2, n = sums[c]
                sums[c] = (s + v.sum(), s2 + (v ** 2).sum(), n + len(v))
        for c, (s, s2, n) in sums.items():
            mean = s / max(n, 1)
            var = max(s2 / max(n, 1) - mean ** 2, 1e-12)
            self.stats[c] = (mean, float(np.sqrt(var)))

    def transform_batch(self, batch: Block) -> Block:
        out = dict(batch)
        for c, (mean, std) in self.stats.items():
            out[c] = ((batch[c] - mean) / std).astype(np.float32)
        return out


class MinMaxScaler(Preprocessor):
    def __init__(self, columns: Sequence[str]):
        self.columns = list(columns)
        self.ranges: Dict[str, tuple] = {}

    def _fit(self, ds: Dataset) -> None:
        r = {c: (np.inf, -np.inf) for c in self.columns}
        for block in ds.iter_blocks():
            for c in self.columns:
                lo, hi = r[c]
                r[c] = (min(lo, block[c].min()), max(hi, block[c].max()))
        self.ranges = {c: (lo, max(hi - lo, 1e-12)) for c, (lo, hi)
                       in r.items()}

    def transform_batch(self, batch: Block) -> Block:
        out = dict(batch)
        for c, (lo, span) in self.ranges.items():
            out[c] = ((batch[c] - lo) / span).astype(np.float32)
        return out


class LabelEncoder(Preprocessor):
    def __init__(self, column: str):
        self.column = column
        self.classes_: List = []

    def _fit(self, ds: Dataset) -> None:
        seen = set()
        for block in ds.iter_blocks():
            seen.update(np.unique(block[self.column]).tolist())
        self.classes_ = sorted(seen)

    def transform_batch(self, batch: Block) -> Block:
        table = {v: i for i, v in enumerate(self.classes_)}
        out = dict(batch)
        out[self.column] = np.asarray(
            [table[v] for v in batch[self.column]], dtype=np.int32)
        return out


class BatchMapper(Preprocessor):
    def __init__(self, fn: Callable[[Block], Block]):
        self.fn = fn

    def _needs_fit(self) -> bool:
        return False

    def transform_batch(self, batch: Block) -> Block:
        return self.fn(batch)


class Chain(Preprocessor):
    def __init__(self, *steps: Preprocessor):
        self.steps = list(steps)

    def fit(self, ds: Dataset) -> "Chain":
        cur = ds
        for s in self.steps:
            s.fit(cur)
            cur = s.transform(cur)
        self._fitted = True
        return self

    def transform_batch(self, batch: Block) -> Block:
        for s in self.steps:
            batch = s.transform_batch(batch)
        return batch


class Tokenizer(Preprocessor):
    """Text -> fixed-length token ids using a callable tokenizer (e.g. HF).

    tokenize_fn(list[str]) -> np.ndarray (N, max_len) int32.
    """

    def __init__(self, column: str, tokenize_fn, output_column="tokens"):
        self.column = column
        self.tokenize_fn = tokenize_fn
        self.output_column = output_column

    def _needs_fit(self) -> bool:
        return False

    def transform_batch(self, batch: Block) -> Block:
        out = dict(batch)
        texts = [str(t) for t in batch[self.column]]
        out[self.output_column] = np.asarray(self.tokenize_fn(texts),
                                             dtype=np.int32)
        return out


class ImageAugmenter(Preprocessor):
    """Host-side decode-time augmentation for the image pipeline
    (reference: the torchvision transform stacks ray.data examples feed
    TorchTrainer; here numpy-only so dense uint8 blocks stay the wire
    format and the device sees ready float batches).

    Operates on an "image" column of (N, H, W, C) uint8: optional
    horizontal random flip + random crop (pad-and-crop), then scales to
    float32 and normalizes with per-channel mean/std (defaults: simple
    [0,1] scaling)."""

    def __init__(self, *, flip: bool = True, crop_padding: int = 0,
                 mean=None, std=None, column: str = "image",
                 seed: int = 0):
        self.flip = flip
        self.crop_padding = crop_padding
        self.mean = None if mean is None else np.asarray(
            mean, np.float32)
        self.std = None if std is None else np.asarray(std, np.float32)
        self.column = column
        self._rng = np.random.RandomState(seed)

    def _needs_fit(self) -> bool:
        return False

    def transform_batch(self, batch: Block) -> Block:
        imgs = batch[self.column]
        if imgs.dtype == object:
            raise ValueError(
                "ImageAugmenter needs a dense (N,H,W,C) image column; "
                "pass size=(H,W) to read_images")
        n, h, w, _c = imgs.shape
        if self.flip:
            do = self._rng.rand(n) < 0.5
            imgs = np.where(do[:, None, None, None],
                            imgs[:, :, ::-1, :], imgs)
        if self.crop_padding > 0:
            p = self.crop_padding
            padded = np.pad(imgs, ((0, 0), (p, p), (p, p), (0, 0)),
                            mode="reflect")
            ys = self._rng.randint(0, 2 * p + 1, size=n)
            xs = self._rng.randint(0, 2 * p + 1, size=n)
            imgs = np.stack([padded[i, ys[i]:ys[i] + h,
                                    xs[i]:xs[i] + w] for i in range(n)])
        out = dict(batch)
        x = imgs.astype(np.float32) / 255.0
        if self.mean is not None or self.std is not None:
            mean = self.mean if self.mean is not None else 0.0
            std = self.std if self.std is not None else 1.0
            x = (x - mean) / std
        out[self.column] = x
        return out
