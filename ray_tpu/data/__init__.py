"""ray_tpu.data — scalable datasets for ML (reference: python/ray/data).

Numpy-columnar blocks, lazy fused plans, a streaming executor over the
core runtime, and a device loader that prefetches batches into TPU HBM.
"""
from .block import Block
from .dataset import (Dataset, from_items, from_blocks, from_numpy,
                      from_pandas, range_,
                      read_text, read_jsonl, read_csv, read_npy,
                      read_parquet, read_images, read_binary_files,
                      read_tfrecords, AggregateFn)
from .device_loader import device_put_iterator
from . import preprocessors
from . import service

# ray.data.range parity name
range = range_  # noqa: A001

__all__ = ["Block", "Dataset", "from_items", "from_blocks", "from_numpy",
           "from_pandas",
           "range", "range_", "read_text", "read_jsonl", "read_csv",
           "read_npy", "read_parquet", "read_images", "read_binary_files",
           "read_tfrecords", "AggregateFn",
           "device_put_iterator", "preprocessors", "service"]
