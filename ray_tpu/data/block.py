"""Blocks: the unit of distributed data.

Reference parity: python/ray/data/block.py (Block + BlockAccessor) and
_internal/arrow_block.py / pandas_block.py. Design difference: blocks are
numpy-columnar dicts ({column: ndarray}) — TPU input pipelines end in
fixed-shape numeric batches, so an Arrow layer would only add copies; the
accessor ops below are exactly the ones the exec plan needs.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence

import numpy as np

Block = Dict[str, np.ndarray]


def block_from_rows(rows: Sequence[Dict[str, Any]]) -> Block:
    if not rows:
        return {}
    cols: Dict[str, list] = {k: [] for k in rows[0]}
    for r in rows:
        for k in cols:
            cols[k].append(r[k])
    return {k: np.asarray(v) for k, v in cols.items()}


def block_to_rows(block: Block) -> List[Dict[str, Any]]:
    if not block:
        return []
    n = block_num_rows(block)
    keys = list(block)
    out = []
    for i in range(n):
        out.append({k: block[k][i] for k in keys})
    return out


def block_num_rows(block: Block) -> int:
    if not block:
        return 0
    return len(next(iter(block.values())))


def block_size_bytes(block: Block) -> int:
    return sum(v.nbytes if isinstance(v, np.ndarray) else 0
               for v in block.values())


def block_slice(block: Block, start: int, end: int) -> Block:
    return {k: v[start:end] for k, v in block.items()}


def block_take(block: Block, indices: np.ndarray) -> Block:
    return {k: v[indices] for k, v in block.items()}


def block_concat(blocks: Sequence[Block]) -> Block:
    blocks = [b for b in blocks if block_num_rows(b) > 0]
    if not blocks:
        return {}
    keys = blocks[0].keys()
    return {k: np.concatenate([b[k] for b in blocks]) for k in keys}


def block_sort(block: Block, key: str, descending: bool = False) -> Block:
    order = np.argsort(block[key], kind="stable")
    if descending:
        order = order[::-1]
    return block_take(block, order)


def block_select(block: Block, mask: np.ndarray) -> Block:
    return {k: v[mask] for k, v in block.items()}
