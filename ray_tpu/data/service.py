"""Disaggregated data service: one shared, fault-tolerant data plane.

Reference counterpart: the tf.data service (PAPERS.md) dispatcher /
worker split, mapped onto ray_tpu primitives. A named
**DataServiceDispatcher** actor owns registered dataset plans and a
pool of **data-worker** actors (autoscaled with the PR-7 synthetic
NodeType pattern from `core/autoscaler.py`). Jobs register a dataset
plan once; any number of consumers then draw *shard grants* (one block
per grant) through per-consumer iterators.

Design invariants (docs/DATA_SERVICE.md holds the long form):

  * **Produce once, feed many.** A dataset plan is keyed by its
    serialized bytes; every JOB registered against that key shares one
    production run per epoch (a key collision with a DIFFERENT plan is
    rejected, never silently shared). Within a job, consumers split
    the job's view: `fcfs` (dynamic first-come-first-served, tune
    sweeps) or `round_robin` (deterministic by block index modulo
    world, SPMD ranks). A job may register LATE: blocks already
    retired by earlier jobs are revived (retired flag cleared, owning
    slices re-pended) and re-produced under their deterministic ids.
  * **Deterministic block identity.** A block produced by slice `s`
    of epoch `e` at position `q` is ALWAYS `e{e}-s{s}-b{q}`, with
    canonical global index `q * n_slices + s`. Re-producing a slice
    after a worker death yields the same ids, so at-most-once handout
    and the census tests are exact under chaos.
  * **Non-blocking dispatcher.** Every dispatcher verb returns
    immediately ({"status": "wait"} when the caller must poll): the
    epoch barrier, production lag, and reconcile gates never park an
    actor call, so `checkpoint_interval_s=0` checkpoints land after
    every completed call.
  * **Lease-fenced grants (PR-8 idiom).** A grant is a lease: if the
    consumer's lease expires (death, wedged step) its outstanding
    grants are revoked back to the pending pool and the consumer is
    fenced; a fenced consumer's next call gets "stale" and must
    re-attach + reconcile. Generations stamp jobs (reshard) and
    consumers (re-attach) so stale acks/grants/refetches are
    rejected. `next_shard` is idempotent per client request nonce —
    an RPC retry after a lost reply replays the original grant
    instead of stranding it.
  * **Restore closes the grant/checkpoint race.** The checkpoint
    ships AFTER the reply, so a SIGKILL between reply and checkpoint
    can lose a grant record. `__ray_restore__` therefore flags every
    consumer `needs_reconcile`; no new grants flow for a job until
    each live consumer reported its consumed block ids (dead ones age
    out via the lease). Zero lost, zero duplicated blocks.
  * **Peer-plane delivery.** Workers `put()` blocks into their own
    store and pass only the ref id; consumers re-materialize
    `ObjectRef(id)` and pull holder->consumer over the PR-2 peer
    transfer plane. Iterators account `relay_bytes` deltas the same
    way `exchange.py` does, and drive them to zero.
"""
from __future__ import annotations

import hashlib
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Set, Tuple

SERVICE_ACTOR_NAME = "_ray_tpu_data_service"
_WORKER_NAME_FMT = "_rtpu_data_worker_{}"

# slice-local execution only: these stage kinds need a cross-slice
# barrier (exchange) or whole-stream view (shuffle/limit), which a
# per-slice producer cannot honor
_REJECTED_STAGE_KINDS = ("exchange", "shuffle")


def _api():
    from .. import api  # noqa: PLC0415 (lazy: avoid import cycles)
    return api


def _knob_float(name: str) -> float:
    from ..util import knobs  # noqa: PLC0415
    return knobs.get_float(name)


def _knob_int(name: str) -> int:
    from ..util import knobs  # noqa: PLC0415
    return knobs.get_int(name)


def _emit(event_type: str, message: str, **fields) -> None:
    try:
        from ..util import events as events_mod  # noqa: PLC0415
        events_mod.emit_safe(event_type, message, **fields)
    except Exception:  # noqa: BLE001 — telemetry never breaks data flow
        pass


def _mcat_get(name: str):
    try:
        from ..util import metrics_catalog as mcat  # noqa: PLC0415
        return mcat.get(name)
    except Exception:  # noqa: BLE001
        return None


def _bid(epoch: int, slice_idx: int, seq: int) -> str:
    return f"e{epoch}-s{slice_idx}-b{seq}"


def plan_bytes_of(ds) -> bytes:
    """Serialized (source, stages) plan; the dataset's identity key is
    sha1 of these bytes unless the caller names the dataset."""
    import cloudpickle  # noqa: PLC0415
    for st in ds._stages:
        if st.kind in _REJECTED_STAGE_KINDS:
            raise ValueError(
                f"data service plans must be slice-local; stage "
                f"{st.name!r} (kind={st.kind!r}) needs a cross-slice "
                f"barrier — materialize it before register()")
    return cloudpickle.dumps((ds._source, ds._stages))


# ---------------------------------------------------------------------------
# data worker
# ---------------------------------------------------------------------------

class _DataWorkerImpl:
    """Executes one plan slice inline and streams block OFFERS (ref ids,
    not values) to the dispatcher. max_concurrency=2 so the
    dispatcher's liveness ping answers while produce_slice runs."""

    def __init__(self, service_name: str, worker_name: str):
        self._service_name = service_name
        self._name = worker_name
        self._disp = None

    def ping(self) -> bool:
        return True

    def pid(self) -> int:
        import os  # noqa: PLC0415
        return os.getpid()

    def _dispatcher(self):
        if self._disp is None:
            api = _api()
            self._disp = api.get_actor(self._service_name,
                                       timeout=10.0)
        return self._disp

    def _call(self, method: str, *args, timeout: float = 30.0):
        """Dispatcher call with retry: the dispatcher may be mid-restart
        (SIGKILL chaos) — same actor id comes back, so retry the handle."""
        api = _api()
        deadline = time.time() + timeout
        last: Optional[BaseException] = None
        while time.time() < deadline:
            try:
                disp = self._dispatcher()
                ref = getattr(disp, method).remote(*args)
                return api.get(ref, timeout=10.0)
            except Exception as e:  # noqa: BLE001 — restart window
                last = e
                self._disp = None
                time.sleep(0.2)
        raise RuntimeError(
            f"data worker {self._name}: dispatcher unreachable for "
            f"{method} ({last!r})")

    def produce_slice(self, plan_blob: bytes, dataset_key: str,
                      epoch: int, slice_idx: int, n_slices: int,
                      skip_seqs: Optional[List[int]] = None) -> int:
        """Run the plan over source blocks i with i % n_slices ==
        slice_idx, inline (no nested distributed execution), offering
        each output block to the dispatcher. skip_seqs: seqs whose
        blocks are already globally acked (re-production after a
        worker death skips the put+offer but still iterates, keeping
        seq numbering deterministic)."""
        import cloudpickle  # noqa: PLC0415
        from .block import block_size_bytes  # noqa: PLC0415
        from .executor import DatasetStats, execute_plan  # noqa: PLC0415

        api = _api()
        source, stages = cloudpickle.loads(plan_blob)
        skip = set(skip_seqs or ())
        ahead = _knob_int("RAY_TPU_DATA_SERVICE_PRODUCE_AHEAD")

        def sliced():
            for i, b in enumerate(source.make_blocks()):
                if i % n_slices == slice_idx:
                    yield b

        produced = 0
        stream = execute_plan(sliced(), stages, DatasetStats(),
                              local=True)
        for seq, block in enumerate(stream):
            if seq in skip:
                continue
            ref = api.put(block)
            out = self._call(
                "offer_block", dataset_key, epoch, slice_idx, seq,
                ref.id, int(block_size_bytes(block)), self._name)
            produced += 1
            # produce-ahead backpressure: the dispatcher reports how
            # many produced blocks sit unretired; pause while over
            # budget so a slow consumer bounds producer memory
            while isinstance(out, dict) \
                    and out.get("outstanding", 0) > ahead:
                time.sleep(0.05)
                out = self._call("queue_depth", dataset_key)
        self._call("slice_done", dataset_key, epoch, slice_idx,
                   self._name)
        return produced

    def stop(self):
        api = _api()
        api.actor_exit()


# ---------------------------------------------------------------------------
# dispatcher
# ---------------------------------------------------------------------------

class DataServiceDispatcher:
    """Named actor owning dataset plans, the per-job grant ledgers, and
    the data-worker pool. All state mutation happens under self._lock
    with NO blocking calls inside it (raylint RT001); worker actor
    calls happen from the tick thread outside the lock."""

    def __init__(self, min_workers: Optional[int] = None,
                 max_workers: Optional[int] = None):
        self._lock = threading.Lock()
        self._shutdown = threading.Event()
        self._incarnation = 0
        self._worker_seq = 0
        self._min_workers = (min_workers if min_workers is not None
                             else _knob_int(
                                 "RAY_TPU_DATA_SERVICE_MIN_WORKERS"))
        self._max_workers = (max_workers if max_workers is not None
                             else _knob_int(
                                 "RAY_TPU_DATA_SERVICE_MAX_WORKERS"))
        # datasets: key -> {"plan": bytes, "n_slices": int}
        self._datasets: Dict[str, Dict[str, Any]] = {}
        # production: key -> epoch -> {"bids": {bid: meta}, "slices":
        # {idx: {"state", "worker"}}, "complete": bool, "jobs": [names]}
        # meta = {"ref": str|None, "nbytes": int, "worker": str,
        #         "idx": int, "acked_by": set}
        self._prod: Dict[str, Dict[int, Dict[str, Any]]] = {}
        # jobs: name -> {"dataset", "mode", "world", "epochs",
        # "generation", "epoch", "consumers": {cid: {...}},
        # "granted": {bid: cid}, "acked": set, "needs_reconcile": set}
        self._jobs: Dict[str, Dict[str, Any]] = {}
        # runtime-only (NOT checkpointed)
        self._workers: Dict[str, Dict[str, Any]] = {}
        self._restored_worker_names: List[str] = []
        self._tick = threading.Thread(target=self._tick_loop,
                                      daemon=True,
                                      name="rtpu-data-service-tick")
        self._tick.start()

    # ---- plumbing ----------------------------------------------------------

    def ping(self) -> bool:
        return True

    def pid(self) -> int:
        import os  # noqa: PLC0415
        return os.getpid()

    def incarnation(self) -> int:
        return self._incarnation

    # ---- registration ------------------------------------------------------

    def register_dataset(self, key: str, plan_blob: bytes,
                         n_slices: int) -> Dict[str, Any]:
        with self._lock:
            ds = self._datasets.get(key)
            if ds is None:
                self._datasets[key] = {"plan": plan_blob,
                                       "n_slices": int(n_slices)}
            elif ds["plan"] != plan_blob:
                # sharing a key across jobs means sharing PRODUCTION;
                # silently keeping the first plan would feed the
                # second job someone else's data
                return {"error":
                        f"dataset {key!r} is already registered "
                        f"with a different plan; use a distinct "
                        f"dataset_name (or the same plan) to share"}
            return {"ok": True, "n_slices":
                    self._datasets[key]["n_slices"]}

    def register_job(self, job_name: str, key: str, mode: str,
                     world: int, epochs: int) -> Dict[str, Any]:
        """Idempotent per (job_name, world); a different world is a
        RESHARD: generation bumps, outstanding grants revoke back to
        pending, consumers drop (they re-attach under the new
        generation), acked blocks stay acked."""
        assert mode in ("fcfs", "round_robin"), mode
        revoked: List[Tuple[str, str]] = []
        with self._lock:
            if key not in self._datasets:
                return {"error": f"unknown dataset {key!r}"}
            job = self._jobs.get(job_name)
            if job is None:
                self._jobs[job_name] = {
                    "dataset": key, "mode": mode, "world": int(world),
                    "epochs": int(epochs), "generation": 0,
                    "epoch": 0, "consumers": {}, "granted": {},
                    "acked": set(), "needs_reconcile": set()}
                for e, ep in (self._prod.get(key) or {}).items():
                    if e < int(epochs) and job_name not in ep["jobs"]:
                        ep["jobs"].append(job_name)
                        self._revive_retired_locked(key, ep, job_name)
                gen = 0
            elif job["world"] != int(world) or job["mode"] != mode:
                job["generation"] += 1
                job["world"] = int(world)
                job["mode"] = mode
                job["epochs"] = max(job["epochs"], int(epochs))
                revoked = [(b, c) for b, c in job["granted"].items()]
                job["granted"] = {}
                job["consumers"] = {}
                job["needs_reconcile"] = set()
                gen = job["generation"]
            else:
                job["epochs"] = max(job["epochs"], int(epochs))
                gen = job["generation"]
        for b, c in revoked:
            _emit("data.service.shard.revoke",
                  f"shard {b} revoked from {c} (job {job_name} "
                  f"resharded to world={world})",
                  job=job_name, bid=b, consumer=c, cause="reshard")
        _emit("data.service.register",
              f"job {job_name!r} registered on dataset {key[:12]} "
              f"(mode={mode}, world={world}, epochs={epochs}, "
              f"generation={gen})",
              job=job_name, dataset=key[:12], mode=mode,
              world=str(world), generation=str(gen))
        return {"generation": gen}

    def _revive_retired_locked(self, key: str, ep: Dict[str, Any],
                               job_name: str) -> None:
        """A job joined an epoch late: blocks retired (ref dropped)
        before it registered were only acked by the PREVIOUS jobs and
        must be re-produced for the newcomer. Clear their retired flag
        (so re-offers are accepted and dispatch stops skip-listing
        their seqs) and re-pend the done slices that own them; slices
        still running converge through slice_done's missing-bid check.
        Double production is harmless — offers dedup by deterministic
        block id."""
        ds = self._datasets.get(key)
        if ds is None:
            return
        n_slices = ds["n_slices"]
        stale_slices: Set[int] = set()
        for m in ep["bids"].values():
            if m.get("retired") and job_name not in m["acked_by"]:
                m["retired"] = False
                stale_slices.add(m["idx"] % n_slices)
        for i in stale_slices:
            sl = ep["slices"].get(i)
            if sl is not None and sl["state"] == "done":
                sl["state"] = "pending"
                sl["worker"] = None
        if stale_slices:
            ep["complete"] = False

    def attach_consumer(self, job_name: str, cid: str,
                        rank: Optional[int] = None) -> Dict[str, Any]:
        """Attach (or re-attach) a consumer. Re-attaching an existing
        cid bumps its generation and requires a reconcile (the PR-8
        fencing idiom: the old incarnation's grants are revoked; its
        acks with the old generation are rejected)."""
        revoked: List[str] = []
        with self._lock:
            job = self._jobs.get(job_name)
            if job is None:
                return {"error": f"unknown job {job_name!r}"}
            if job["mode"] == "round_robin":
                if rank is None or not 0 <= rank < job["world"]:
                    return {"error": f"round_robin consumers need "
                            f"rank in [0, {job['world']})"}
            cons = job["consumers"].get(cid)
            lease = time.time() + _knob_float(
                "RAY_TPU_DATA_SERVICE_LEASE_S")
            if cons is None:
                job["consumers"][cid] = {
                    "rank": rank, "generation": 0, "lease": lease,
                    "consumed": 0, "fenced": False}
                gen = 0
            else:
                cons["generation"] += 1
                cons["fenced"] = False
                cons["lease"] = lease
                cons["rank"] = rank
                # the old incarnation's grants are about to revoke:
                # its cached next_shard reply must not replay
                cons.pop("last_req", None)
                cons.pop("last_reply", None)
                gen = cons["generation"]
                revoked = [b for b, c in job["granted"].items()
                           if c == cid]
                for b in revoked:
                    del job["granted"][b]
                job["needs_reconcile"].add(cid)
        for b in revoked:
            _emit("data.service.shard.revoke",
                  f"shard {b} revoked: consumer {cid} re-attached",
                  job=job_name, bid=b, consumer=cid, cause="reattach")
        return {"generation": gen,
                "job_generation": self._jobs[job_name]["generation"],
                "epoch": self._jobs[job_name]["epoch"]}

    # ---- grants ------------------------------------------------------------

    def _eligible(self, job: Dict[str, Any], ep: Dict[str, Any],
                  rank: Optional[int]) -> List[Tuple[int, str]]:
        """(idx, bid) candidates for one consumer, idx-ascending:
        produced (live ref), not granted, not acked, rank-matched."""
        world = job["world"]
        out = []
        for b, m in ep["bids"].items():
            if m["ref"] is None or b in job["granted"] \
                    or b in job["acked"]:
                continue
            if job["mode"] == "round_robin" \
                    and m["idx"] % world != rank:
                continue
            out.append((m["idx"], b))
        out.sort()
        return out

    def _epoch_fully_granted(self, job: Dict[str, Any],
                             ep: Dict[str, Any]) -> bool:
        return ep["complete"] and all(
            b in job["granted"] or b in job["acked"]
            for b in ep["bids"])

    def _apply_acks(self, job_name: str, job: Dict[str, Any],
                    cid: str, acks: List[str]) -> None:
        key = job["dataset"]
        for b in acks or ():
            if job["granted"].get(b) == cid:
                del job["granted"][b]
            if b in job["acked"]:
                continue
            job["acked"].add(b)
            cons = job["consumers"].get(cid)
            if cons is not None:
                cons["consumed"] += 1
            for ep in (self._prod.get(key) or {}).values():
                m = ep["bids"].get(b)
                if m is not None:
                    m["acked_by"].add(job_name)
                    self._maybe_retire(ep, b, m)

    def _maybe_retire(self, ep: Dict[str, Any], b: str,
                      m: Dict[str, Any]) -> None:
        if all(j in m["acked_by"] for j in ep["jobs"]
               if j in self._jobs):
            m["ref"] = None          # every job consumed it: drop ref
            m["retired"] = True

    def next_shard(self, job_name: str, cid: str, gen: int,
                   acks: Optional[List[str]] = None,
                   req: Optional[str] = None) -> Dict[str, Any]:
        """The consumer verb: piggybacked acks + one grant attempt.
        Never blocks — barrier / production lag / reconcile gates
        return {"status": "wait"|"reconcile"|...} for the client to
        poll. `req` is the client's per-request nonce: a retried call
        (RPC reply lost in transit) replays the cached grant instead
        of handing out a second block, so the verb is idempotent and
        no grant is ever stranded on a timed-out reply."""
        reply: Optional[Dict[str, Any]] = None
        advanced: Optional[int] = None
        with self._lock:
            job = self._jobs.get(job_name)
            if job is None:
                return {"status": "stale",
                        "why": f"unknown job {job_name!r}"}
            cons = job["consumers"].get(cid)
            if cons is None or cons["fenced"] \
                    or gen != cons["generation"]:
                return {"status": "stale", "why": "fenced or stale "
                        "generation; re-attach and reconcile"}
            cons["lease"] = time.time() + _knob_float(
                "RAY_TPU_DATA_SERVICE_LEASE_S")
            self._apply_acks(job_name, job, cid, acks or [])
            if cid in job["needs_reconcile"]:
                return {"status": "reconcile"}
            if job["needs_reconcile"]:
                return {"status": "wait", "why": "peers reconciling"}
            if req is not None and cons.get("last_req") == req:
                # retry of a request whose reply we already computed:
                # replay it (the cached grant is still in job
                # ["granted"] for this cid)
                return dict(cons["last_reply"])
            e = job["epoch"]
            if e >= job["epochs"]:
                return {"status": "end"}
            ep = (self._prod.get(job["dataset"]) or {}).get(e)
            if ep is None:
                return {"status": "wait", "why": "epoch not started"}
            cands = self._eligible(job, ep, cons["rank"])
            if not cands:
                # epoch barrier: advance only when EVERY shard of this
                # epoch has been handed out (granted or acked)
                if self._epoch_fully_granted(job, ep):
                    job["epoch"] = e + 1
                    advanced = e + 1
            else:
                idx, b = cands[0]
                m = ep["bids"][b]
                job["granted"][b] = cid
                reply = {"status": "grant", "bid": b,
                         "ref": m["ref"], "nbytes": m["nbytes"],
                         "epoch": e, "idx": m["idx"]}
                if req is not None:
                    cons["last_req"] = req
                    cons["last_reply"] = dict(reply)
        if advanced is not None:
            _emit("data.service.epoch",
                  f"job {job_name} advanced to epoch {advanced}",
                  job=job_name, epoch=str(advanced))
            return {"status": "wait", "why": "epoch advanced",
                    "epoch": advanced}
        if reply is None:
            return {"status": "wait",
                    "why": "barrier or production lag"}
        _emit("data.service.shard.grant",
              f"shard {reply['bid']} granted to {cid} "
              f"(job {job_name})",
              job=job_name, bid=reply["bid"], consumer=cid,
              epoch=str(reply["epoch"]))
        c = _mcat_get("ray_tpu_data_service_shards_granted_total")
        if c is not None:
            c.inc(tags={"job": job_name,
                        "mode": self._jobs[job_name]["mode"]})
        return reply

    def ack(self, job_name: str, cid: str, gen: int,
            acks: List[str]) -> Dict[str, Any]:
        with self._lock:
            job = self._jobs.get(job_name)
            if job is None:
                return {"ok": False}
            cons = job["consumers"].get(cid)
            if cons is None or gen != cons["generation"]:
                return {"ok": False, "status": "stale"}
            self._apply_acks(job_name, job, cid, acks)
            return {"ok": True}

    def reconcile(self, job_name: str, cid: str, gen: int,
                  consumed: List[str]) -> Dict[str, Any]:
        """Post-restore / post-re-attach dedup: the consumer reports
        every block id it already consumed; those become acks (idempo-
        tent), anything it was granted but did not consume returns to
        the pending pool."""
        dropped: List[str] = []
        with self._lock:
            job = self._jobs.get(job_name)
            if job is None:
                return {"ok": False}
            cons = job["consumers"].get(cid)
            if cons is None or gen != cons["generation"]:
                return {"ok": False, "status": "stale"}
            self._apply_acks(job_name, job, cid, consumed)
            # a re-attached consumer's seek position must reflect what
            # it consumed in its previous incarnation (fast_forward
            # compares against this count)
            cons["consumed"] = max(cons["consumed"],
                                   len(set(consumed)))
            dropped = [b for b, c in job["granted"].items()
                       if c == cid]
            for b in dropped:
                del job["granted"][b]
            # dropped grants must not replay out of the nonce cache
            cons.pop("last_req", None)
            cons.pop("last_reply", None)
            job["needs_reconcile"].discard(cid)
        for b in dropped:
            _emit("data.service.shard.revoke",
                  f"shard {b} returned to pending on reconcile of "
                  f"{cid}", job=job_name, bid=b, consumer=cid,
                  cause="reconcile")
        return {"ok": True}

    def refetch(self, job_name: str, cid: str, gen: int, bid: str
                ) -> Dict[str, Any]:
        """A consumer's get() on a granted ref failed (holder worker
        died): return the re-produced ref once available. Fenced the
        same way as next_shard/ack — a stale consumer must not keep
        pulling refs for a block that was revoked and re-granted
        elsewhere (that would double-deliver the value)."""
        with self._lock:
            job = self._jobs.get(job_name)
            if job is None:
                return {"status": "stale",
                        "why": f"unknown job {job_name!r}"}
            cons = job["consumers"].get(cid)
            if cons is None or cons["fenced"] \
                    or gen != cons["generation"]:
                return {"status": "stale", "why": "fenced or stale "
                        "generation; re-attach and reconcile"}
            if job["granted"].get(bid) != cid:
                return {"status": "stale",
                        "why": f"{bid} is not granted to {cid}"}
            for ep in (self._prod.get(job["dataset"]) or {}).values():
                m = ep["bids"].get(bid)
                if m is not None:
                    if m["ref"] is not None:
                        return {"status": "grant", "bid": bid,
                                "ref": m["ref"],
                                "nbytes": m["nbytes"]}
                    return {"status": "wait", "why": "re-producing"}
        return {"status": "wait", "why": "unknown bid"}

    def fast_forward(self, job_name: str, cid: str, gen: int,
                     n: int) -> Dict[str, Any]:
        """PR-11 resume hook: grant-and-auto-ack this consumer's
        eligible blocks (current epoch, idx order) until its consumed
        count reaches n — an absolute seek, cheap because nothing is
        fetched. Returns how many were skipped."""
        skipped = 0
        with self._lock:
            job = self._jobs.get(job_name)
            if job is None:
                return {"skipped": 0, "status": "stale"}
            cons = job["consumers"].get(cid)
            if cons is None or gen != cons["generation"]:
                return {"skipped": 0, "status": "stale"}
            while cons["consumed"] < n and job["epoch"] < job["epochs"]:
                ep = (self._prod.get(job["dataset"]) or {}).get(
                    job["epoch"])
                if ep is None:
                    break
                cands = self._eligible(job, ep, cons["rank"])
                if not cands:
                    # absolute seeks may span epochs: cross the barrier
                    # the same way next_shard does
                    if self._epoch_fully_granted(job, ep):
                        job["epoch"] += 1
                        continue
                    break
                _, b = cands[0]
                job["granted"][b] = cid
                self._apply_acks(job_name, job, cid, [b])
                skipped += 1
            consumed = cons["consumed"]
            done = job["epoch"] >= job["epochs"]
        return {"skipped": skipped, "consumed": consumed, "done": done}

    # ---- producer verbs ----------------------------------------------------

    def offer_block(self, key: str, epoch: int, slice_idx: int,
                    seq: int, ref_id: str, nbytes: int,
                    worker: str) -> Dict[str, Any]:
        b = _bid(epoch, slice_idx, seq)
        with self._lock:
            ds = self._datasets.get(key)
            eps = self._prod.setdefault(key, {})
            ep = eps.get(epoch)
            if ds is None or ep is None:
                return {"outstanding": 0, "ignored": True}
            m = ep["bids"].get(b)
            if m is not None and m.get("retired"):
                return {"outstanding": self._queue_depth_locked(key)}
            if m is not None and m["ref"] is not None:
                alive = self._workers.get(m["worker"], {})
                if alive.get("state") == "alive":
                    # duplicate offer (re-produced race): keep first
                    return {"outstanding":
                            self._queue_depth_locked(key)}
            idx = seq * ds["n_slices"] + slice_idx
            prev = m or {"acked_by": set()}
            ep["bids"][b] = {"ref": ref_id, "nbytes": int(nbytes),
                             "worker": worker, "idx": idx,
                             "acked_by": prev["acked_by"],
                             "retired": False}
            out = self._queue_depth_locked(key)
        return {"outstanding": out}

    def slice_done(self, key: str, epoch: int, slice_idx: int,
                   worker: str) -> Dict[str, Any]:
        with self._lock:
            ep = (self._prod.get(key) or {}).get(epoch)
            ds = self._datasets.get(key)
            if ep is None or ds is None:
                return {"ok": False}
            sl = ep["slices"].get(slice_idx)
            if sl is not None:
                # a bid with no ref that is NOT retired was revived
                # mid-run (late job registration) or lost: this run's
                # skip list predates it, so the slice must go around
                # again with a fresh skip list
                missing = any(
                    m["ref"] is None and not m.get("retired")
                    and m["idx"] % ds["n_slices"] == slice_idx
                    for m in ep["bids"].values())
                if missing:
                    sl["state"] = "pending"
                    sl["worker"] = None
                else:
                    sl["state"] = "done"
            w = self._workers.get(worker)
            if w is not None and w.get("busy") == (key, epoch,
                                                  slice_idx):
                w["busy"] = None
                w["idle_since"] = time.time()
            ep["complete"] = all(s["state"] == "done"
                                 for s in ep["slices"].values())
            complete = ep["complete"]
        if complete:
            _emit("data.service.epoch",
                  f"epoch {epoch} production complete for dataset "
                  f"{key[:12]}", dataset=key[:12], epoch=str(epoch),
                  phase="produced")
        return {"ok": True}

    def _queue_depth_locked(self, key: str) -> int:
        n = 0
        for ep in (self._prod.get(key) or {}).values():
            n += sum(1 for m in ep["bids"].values()
                     if m["ref"] is not None)
        return n

    def queue_depth(self, key: str) -> Dict[str, Any]:
        with self._lock:
            return {"outstanding": self._queue_depth_locked(key)}

    # ---- introspection -----------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            jobs = {}
            for name, j in self._jobs.items():
                jobs[name] = {
                    "mode": j["mode"], "world": j["world"],
                    "epoch": j["epoch"], "epochs": j["epochs"],
                    "generation": j["generation"],
                    "granted": len(j["granted"]),
                    "acked": len(j["acked"]),
                    "consumers": {
                        c: {"rank": v["rank"],
                            "generation": v["generation"],
                            "consumed": v["consumed"],
                            "fenced": v["fenced"]}
                        for c, v in j["consumers"].items()},
                    "needs_reconcile":
                        sorted(j["needs_reconcile"])}
            prod = {}
            for key, eps in self._prod.items():
                prod[key] = {
                    str(e): {"jobs": sorted(ep["jobs"]),
                             "n_bids": len(ep["bids"]),
                             "complete": ep["complete"]}
                    for e, ep in eps.items()}
            return {
                "incarnation": self._incarnation,
                "workers": {n: {"state": w["state"],
                                "busy": w.get("busy")}
                            for n, w in self._workers.items()},
                "queue_depth": {k: self._queue_depth_locked(k)
                                for k in self._datasets},
                "datasets": {k: d["n_slices"]
                             for k, d in self._datasets.items()},
                "prod": prod,
                "jobs": jobs}

    # ---- persistence (PR-6 WAL) -------------------------------------------

    def __ray_save__(self) -> Dict[str, Any]:
        with self._lock:
            prod = {}
            for key, eps in self._prod.items():
                prod[key] = {}
                for e, ep in eps.items():
                    prod[key][e] = {
                        "bids": {b: {"ref": m["ref"],
                                     "nbytes": m["nbytes"],
                                     "worker": m["worker"],
                                     "idx": m["idx"],
                                     "acked_by":
                                         sorted(m["acked_by"]),
                                     "retired":
                                         m.get("retired", False)}
                                 for b, m in ep["bids"].items()},
                        "slices": {i: {"state": s["state"],
                                       "worker": s.get("worker")}
                                   for i, s in ep["slices"].items()},
                        "complete": ep["complete"],
                        "jobs": list(ep["jobs"])}
            jobs = {}
            for name, j in self._jobs.items():
                jobs[name] = {
                    "dataset": j["dataset"], "mode": j["mode"],
                    "world": j["world"], "epochs": j["epochs"],
                    "generation": j["generation"],
                    "epoch": j["epoch"],
                    "granted": dict(j["granted"]),
                    "acked": sorted(j["acked"]),
                    "consumers": {c: dict(v) for c, v
                                  in j["consumers"].items()}}
            return {"v": 1, "incarnation": self._incarnation,
                    "worker_seq": self._worker_seq,
                    "worker_names": [n for n, w
                                     in self._workers.items()
                                     if w["state"] == "alive"],
                    "datasets": {k: dict(v) for k, v
                                 in self._datasets.items()},
                    "prod": prod, "jobs": jobs}

    def __ray_restore__(self, saved: Dict[str, Any]) -> None:
        with self._lock:
            self._incarnation = int(saved.get("incarnation", 0)) + 1
            self._worker_seq = int(saved.get("worker_seq", 0))
            self._datasets = {k: dict(v) for k, v
                              in (saved.get("datasets") or {}).items()}
            self._prod = {}
            for key, eps in (saved.get("prod") or {}).items():
                self._prod[key] = {}
                for e, ep in eps.items():
                    self._prod[key][int(e)] = {
                        "bids": {b: {"ref": m["ref"],
                                     "nbytes": m["nbytes"],
                                     "worker": m["worker"],
                                     "idx": m["idx"],
                                     "acked_by":
                                         set(m["acked_by"]),
                                     "retired": m["retired"]}
                                 for b, m in ep["bids"].items()},
                        # running slices re-verify in the first tick
                        "slices": {int(i): {"state": s["state"],
                                            "worker":
                                                s.get("worker")}
                                   for i, s in ep["slices"].items()},
                        "complete": ep["complete"],
                        "jobs": list(ep["jobs"])}
            self._jobs = {}
            for name, j in (saved.get("jobs") or {}).items():
                self._jobs[name] = {
                    "dataset": j["dataset"], "mode": j["mode"],
                    "world": j["world"], "epochs": j["epochs"],
                    "generation": j["generation"],
                    "epoch": j["epoch"],
                    "granted": dict(j["granted"]),
                    "acked": set(j["acked"]),
                    "consumers": {c: dict(v) for c, v
                                  in j["consumers"].items()},
                    # the grant/checkpoint race: every consumer must
                    # reconcile before new grants flow for this job
                    "needs_reconcile":
                        set(j["consumers"].keys())}
            self._restored_worker_names = list(
                saved.get("worker_names") or [])
        _emit("data.service.register",
              f"dispatcher restored (incarnation "
              f"{self._incarnation}); {len(self._jobs)} job(s) "
              f"gated on consumer reconcile",
              incarnation=str(self._incarnation), phase="restore")

    # ---- tick: autoscale + production + leases + metrics -------------------

    def _tick_loop(self) -> None:
        tick_s = _knob_float("RAY_TPU_DATA_SERVICE_TICK_S")
        while not self._shutdown.is_set():
            try:
                self._reattach_restored_workers()
                self._check_worker_liveness()
                self._expire_leases()
                self._scale_workers()
                self._dispatch_slices()
                self._update_metrics()
            except Exception:  # noqa: BLE001 — the loop must survive
                import traceback  # noqa: PLC0415
                traceback.print_exc()
            self._shutdown.wait(tick_s)

    def _reattach_restored_workers(self) -> None:
        with self._lock:
            names = list(self._restored_worker_names)
            self._restored_worker_names = []
        if not names:
            return
        api = _api()
        for name in names:
            try:
                h = api.get_actor(name, timeout=1.0)
                api.get(h.ping.remote(), timeout=5.0)
                with self._lock:
                    self._workers[name] = {
                        "handle": h, "state": "alive", "busy": None,
                        "idle_since": time.time()}
            except Exception:  # noqa: BLE001 — worker died with us
                self._on_worker_dead(name)
        # EVERY slice checkpointed as "running" is re-queued — even on a
        # worker that came back alive: its in-flight produce_slice may
        # have died retrying offer_block against the restarting
        # dispatcher, and slice_done would then never arrive. If the old
        # task IS still running, double production is harmless — offers
        # dedup by deterministic block id and retired seqs are skipped.
        with self._lock:
            for eps in self._prod.values():
                for ep in eps.values():
                    for sl in ep["slices"].values():
                        if sl["state"] == "running":
                            sl["state"] = "pending"
                            sl["worker"] = None

    def _check_worker_liveness(self) -> None:
        with self._lock:
            busy = [(n, w["handle"]) for n, w in self._workers.items()
                    if w["state"] == "alive" and w.get("busy")]
        api = _api()
        for name, h in busy:
            try:
                api.get(h.ping.remote(), timeout=10.0)
            except Exception:  # noqa: BLE001 — died or wedged
                self._on_worker_dead(name)

    def _on_worker_dead(self, name: str) -> None:
        """Re-queue the dead worker's slices and invalidate every
        unretired ref it held (its store died with it); grants stay
        outstanding — consumers refetch after re-production."""
        requeued: List[Tuple[str, int, int]] = []
        with self._lock:
            w = self._workers.get(name)
            if w is not None:
                w["state"] = "dead"
                w["busy"] = None
            for key, eps in self._prod.items():
                for e, ep in eps.items():
                    lost = False
                    for b, m in ep["bids"].items():
                        if m["worker"] == name \
                                and not m.get("retired") \
                                and m["ref"] is not None:
                            m["ref"] = None
                            lost = True
                    for i, sl in ep["slices"].items():
                        if sl.get("worker") == name \
                                and sl["state"] != "pending":
                            sl["state"] = "pending"
                            sl["worker"] = None
                            requeued.append((key, e, i))
                        elif lost and sl["state"] == "done" and any(
                                m["worker"] == name
                                and m["ref"] is None
                                and not m.get("retired")
                                for b, m in ep["bids"].items()
                                if b.startswith(_bid(e, i, 0)[:-2])):
                            sl["state"] = "pending"
                            sl["worker"] = None
                            ep["complete"] = False
                            requeued.append((key, e, i))
        for key, e, i in requeued:
            _emit("data.service.shard.revoke",
                  f"slice s{i} of epoch {e} re-queued: worker "
                  f"{name} died", dataset=key[:12], epoch=str(e),
                  slice=str(i), consumer=name, cause="worker_death")

    def _expire_leases(self) -> None:
        now = time.time()
        revoked: List[Tuple[str, str, str]] = []
        with self._lock:
            for job_name, job in self._jobs.items():
                for cid, cons in job["consumers"].items():
                    if cons["fenced"] or cons["lease"] >= now:
                        continue
                    cons["fenced"] = True
                    job["needs_reconcile"].discard(cid)
                    for b in [b for b, c in job["granted"].items()
                              if c == cid]:
                        del job["granted"][b]
                        revoked.append((job_name, cid, b))
        for job_name, cid, b in revoked:
            _emit("data.service.shard.revoke",
                  f"shard {b} revoked: consumer {cid} lease expired",
                  job=job_name, bid=b, consumer=cid,
                  cause="lease_expired")

    def _scale_workers(self) -> None:
        """PR-7 synthetic node-type autoscaling: the pool is one
        NodeType; pending slices are the demand; upscale_step clamps
        the launch rate."""
        from ..core.autoscaler import NodeType, upscale_step  # noqa: PLC0415
        nt = NodeType("data_worker", {"CPU": 1.0},
                      min_workers=self._min_workers,
                      max_workers=self._max_workers)
        now = time.time()
        with self._lock:
            alive = [n for n, w in self._workers.items()
                     if w["state"] == "alive"]
            pending = sum(
                1 for eps in self._prod.values()
                for ep in eps.values()
                for sl in ep["slices"].values()
                if sl["state"] == "pending")
            busy = sum(1 for n in alive
                       if self._workers[n].get("busy"))
            want = min(max(nt.min_workers, pending + busy),
                       nt.max_workers)
            have = len(alive)
            to_spawn = 0
            if want > have:
                to_spawn = upscale_step(have, want - have, 1.0)
            victims: List[str] = []
            if want < have:
                idle_cut = now - 4 * _knob_float(
                    "RAY_TPU_DATA_SERVICE_TICK_S")
                for n in alive:
                    if have - len(victims) <= want:
                        break
                    w = self._workers[n]
                    if not w.get("busy") \
                            and w.get("idle_since", now) < idle_cut:
                        victims.append(n)
            names = []
            for _ in range(to_spawn):
                names.append(_WORKER_NAME_FMT.format(
                    self._worker_seq))
                self._worker_seq += 1
        api = _api()
        for name in names:
            try:
                cls = api.remote(num_cpus=1, max_concurrency=2)(
                    _DataWorkerImpl)
                h = cls.options(name=name).remote(
                    SERVICE_ACTOR_NAME, name)
                with self._lock:
                    self._workers[name] = {
                        "handle": h, "state": "alive", "busy": None,
                        "idle_since": time.time()}
            except Exception:  # noqa: BLE001 — retried next tick
                import traceback  # noqa: PLC0415
                traceback.print_exc()
        for name in victims:
            with self._lock:
                w = self._workers.pop(name, None)
            if w is None:
                continue
            try:
                api.kill(w["handle"])
            except Exception:  # noqa: BLE001
                pass
        if names or victims:
            _emit("data.service.worker.scale",
                  f"data-worker pool scaled: +{len(names)} "
                  f"-{len(victims)} (want {want}, min "
                  f"{nt.min_workers}, max {nt.max_workers})",
                  spawned=str(len(names)), killed=str(len(victims)),
                  want=str(want))

    def _dispatch_slices(self) -> None:
        # start production for any epoch some registered job needs
        assignments: List[Tuple[Any, bytes, str, int, int, int,
                                List[int], str]] = []
        with self._lock:
            for job in self._jobs.values():
                key, e = job["dataset"], job["epoch"]
                if e >= job["epochs"]:
                    continue
                ds = self._datasets.get(key)
                if ds is None:
                    continue
                eps = self._prod.setdefault(key, {})
                if e not in eps:
                    eps[e] = {
                        "bids": {},
                        "slices": {i: {"state": "pending",
                                       "worker": None}
                                   for i in range(ds["n_slices"])},
                        "complete": False,
                        "jobs": [n for n, j in self._jobs.items()
                                 if j["dataset"] == key
                                 and j["epoch"] <= e < j["epochs"]]}
            idle = [n for n, w in self._workers.items()
                    if w["state"] == "alive" and not w.get("busy")]
            for key, eps in self._prod.items():
                ds = self._datasets.get(key)
                if ds is None:
                    continue
                for e, ep in eps.items():
                    for i, sl in ep["slices"].items():
                        if sl["state"] != "pending" or not idle:
                            continue
                        name = idle.pop()
                        w = self._workers[name]
                        sl["state"] = "running"
                        sl["worker"] = name
                        w["busy"] = (key, e, i)
                        skip = [int(b.split("-b")[1])
                                for b, m in ep["bids"].items()
                                if m.get("retired")
                                and b.startswith(f"e{e}-s{i}-")]
                        assignments.append(
                            (w["handle"], ds["plan"], key, e, i,
                             ds["n_slices"], skip, name))
        for h, plan, key, e, i, n_slices, skip, name in assignments:
            try:
                h.produce_slice.remote(plan, key, e, i, n_slices,
                                       skip)
            except Exception:  # noqa: BLE001 — liveness check requeues
                self._on_worker_dead(name)

    def _update_metrics(self) -> None:
        g_depth = _mcat_get("ray_tpu_data_service_queue_depth")
        g_out = _mcat_get("ray_tpu_data_service_outstanding_shards")
        g_lag = _mcat_get("ray_tpu_data_service_consumer_lag")
        if g_depth is None:
            return
        with self._lock:
            for key in self._datasets:
                g_depth.set(float(self._queue_depth_locked(key)),
                            tags={"dataset": key[:12]})
            for name, job in self._jobs.items():
                g_out.set(float(len(job["granted"])),
                          tags={"job": name})
                ep = (self._prod.get(job["dataset"]) or {}).get(
                    job["epoch"])
                for cid, cons in job["consumers"].items():
                    if ep is None:
                        lag = 0
                    else:
                        world = job["world"]
                        eligible = sum(
                            1 for m in ep["bids"].values()
                            if job["mode"] != "round_robin"
                            or m["idx"] % world == cons["rank"])
                        lag = max(0, eligible - cons["consumed"])
                    g_lag.set(float(lag), tags={"job": name,
                                                "consumer": cid})

    def graceful_shutdown(self) -> Dict[str, Any]:
        self._shutdown.set()
        with self._lock:
            handles = [w["handle"] for w in self._workers.values()
                       if w["state"] == "alive"]
            self._workers = {}
        api = _api()
        for h in handles:
            try:
                api.kill(h)
            except Exception:  # noqa: BLE001
                pass
        return {"ok": True}


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------

class StaleConsumerError(RuntimeError):
    """The dispatcher fenced this consumer and automatic re-attach +
    reconcile could not recover it."""


class _GrantRevoked(Exception):
    """A granted shard was revoked mid-fetch (lease expiry / reshard):
    the value must not be consumed — re-attach and re-request."""


def start_service(*, min_workers: Optional[int] = None,
                  max_workers: Optional[int] = None,
                  name: str = SERVICE_ACTOR_NAME):
    """Get-or-create the named dispatcher actor. Restart-capable
    (max_restarts) with checkpoint-after-every-call so a SIGKILL'd
    dispatcher resumes mid-epoch from its PR-6 WAL checkpoint."""
    api = _api()
    cls = api.remote(num_cpus=0.1, max_restarts=4,
                     checkpoint_interval_s=0)(DataServiceDispatcher)
    return cls.options(name=name, get_if_exists=True).remote(
        min_workers, max_workers)


def _dispatcher(name: str = SERVICE_ACTOR_NAME, timeout: float = 5.0):
    api = _api()
    try:
        return api.get_actor(name, timeout=timeout)
    except ValueError:
        return start_service(name=name)


def _call(method: str, *args, name: str = SERVICE_ACTOR_NAME,
          timeout: float = 60.0):
    """Dispatcher call that rides out a dispatcher restart (same actor
    id comes back; the handle stays valid — retry until it answers)."""
    api = _api()
    deadline = time.time() + timeout
    last: Optional[BaseException] = None
    while time.time() < deadline:
        try:
            disp = _dispatcher(name)
            ref = getattr(disp, method).remote(*args)
            return api.get(ref, timeout=15.0)
        except Exception as e:  # noqa: BLE001 — restart window
            last = e
            time.sleep(0.2)
    raise RuntimeError(f"data service unreachable for {method} "
                       f"({last!r})")


def register(ds, job_name: str, *, mode: str = "fcfs",
             world_size: int = 1, epochs: int = 1,
             dataset_name: Optional[str] = None,
             n_slices: Optional[int] = None) -> str:
    """Register a dataset plan + a job against the shared service.
    Jobs passing the same `dataset_name` (or byte-identical plans)
    SHARE production: each block is produced once and granted once per
    job. Returns the dataset key. Idempotent per (job_name, world) —
    re-registering with a different world_size is a reshard."""
    mode = {"rr": "round_robin"}.get(mode, mode)
    if mode not in ("fcfs", "round_robin"):
        raise ValueError(f"mode must be fcfs|round_robin, got {mode!r}")
    blob = plan_bytes_of(ds)
    key = dataset_name or hashlib.sha1(blob).hexdigest()[:16]
    if n_slices is None:
        n_slices = _knob_int("RAY_TPU_DATA_SERVICE_MAX_WORKERS")
    start_service()
    out = _call("register_dataset", key, blob, int(n_slices))
    if "error" in out:
        raise ValueError(out["error"])
    out = _call("register_job", job_name, key, mode, int(world_size),
                int(epochs))
    if "error" in out:
        raise ValueError(out["error"])
    return key


def iterator(job_name: str, *, rank: Optional[int] = None,
             consumer_id: Optional[str] = None
             ) -> "DataServiceIterator":
    """Per-consumer block iterator for a registered job."""
    return DataServiceIterator(job_name, rank=rank,
                               consumer_id=consumer_id)


class DataServiceIterator:
    """Client-side shard iterator: polls the dispatcher for grants,
    pulls block values over the peer transfer plane, piggybacks acks
    on the next grant request, and self-heals through dispatcher
    restarts (reconcile) and its own lease expiry (re-attach).

    `stats` carries {"blocks", "bytes", "relay_bytes"}: relay_bytes is
    the exchange.py-style driver-relay fallback delta observed across
    this iterator's fetches — the acceptance bar is zero.
    """

    def __init__(self, job_name: str, *, rank: Optional[int] = None,
                 consumer_id: Optional[str] = None,
                 service_name: str = SERVICE_ACTOR_NAME):
        import uuid  # noqa: PLC0415
        self._job = job_name
        self._rank = rank
        self._name = service_name
        self._cid = consumer_id or f"c-{uuid.uuid4().hex[:8]}"
        self._pending_acks: List[str] = []
        self._consumed: List[str] = []      # bids, in consumption order
        self._done = False
        self.stats: Dict[str, int] = {"blocks": 0, "bytes": 0,
                                      "relay_bytes": 0}
        out = _call("attach_consumer", self._job, self._cid, rank,
                    name=service_name)
        if "error" in out:
            raise ValueError(out["error"])
        self._gen = out["generation"]

    # -- internals ----------------------------------------------------------

    def _runtime(self):
        from ..core import runtime as runtime_mod  # noqa: PLC0415
        if runtime_mod.runtime_initialized():
            return runtime_mod.get_runtime()
        return None

    def _reattach(self) -> None:
        out = _call("attach_consumer", self._job, self._cid,
                    self._rank, name=self._name)
        if "error" in out:
            raise StaleConsumerError(out["error"])
        self._gen = out["generation"]
        self._reconcile()

    def _reconcile(self) -> None:
        _call("reconcile", self._job, self._cid, self._gen,
              list(self._consumed), name=self._name)
        self._pending_acks = []

    def _fetch(self, grant: Dict[str, Any]):
        """Pull the block value; if the holder died mid-flight, poll
        refetch until the re-produced copy lands. Raises _GrantRevoked
        if the dispatcher fenced us meanwhile (the block may already
        be re-granted to another consumer — consuming it here would
        double-deliver)."""
        from ..core.object_ref import ObjectRef  # noqa: PLC0415
        api = _api()
        rt = self._runtime()
        relay0 = getattr(rt, "relay_bytes", 0)
        ref_id = grant["ref"]
        deadline = time.time() + 120.0
        while True:
            try:
                value = api.get(ObjectRef(ref_id), timeout=15.0)
                break
            except Exception:  # noqa: BLE001 — holder likely died
                if time.time() > deadline:
                    raise
                out = _call("refetch", self._job, self._cid,
                            self._gen, grant["bid"], name=self._name)
                if out.get("status") == "grant":
                    ref_id = out["ref"]
                elif out.get("status") == "stale":
                    raise _GrantRevoked(out.get("why", "stale"))
                else:
                    time.sleep(_knob_float(
                        "RAY_TPU_DATA_SERVICE_POLL_S"))
        self.stats["blocks"] += 1
        self.stats["bytes"] += int(grant.get("nbytes", 0))
        self.stats["relay_bytes"] += int(
            getattr(rt, "relay_bytes", 0) - relay0)
        return value

    # -- iterator protocol --------------------------------------------------

    def __iter__(self) -> "DataServiceIterator":
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        import uuid  # noqa: PLC0415
        from ..util import waits as waits_mod  # noqa: PLC0415
        poll_s = _knob_float("RAY_TPU_DATA_SERVICE_POLL_S")
        stale_retries = 3
        # one park spans consecutive "wait" polls (registered lazily on
        # the first wait status): a starved consumer surfaces as one
        # aged "data-grant" record naming job/consumer, which the wait
        # graph chains to the wedged producer via the dispatcher tables
        wtok = 0
        try:
            while True:
                # per-request nonce: _call may retry the RPC after a
                # lost reply — the same nonce makes the dispatcher
                # replay the original grant instead of handing out a
                # second block
                req = uuid.uuid4().hex[:12]
                out = _call("next_shard", self._job, self._cid,
                            self._gen, self._pending_acks, req,
                            name=self._name)
                status = out.get("status")
                if status == "grant":
                    self._pending_acks = []
                    try:
                        value = self._fetch(out)
                    except _GrantRevoked:
                        # revoked mid-fetch: nothing consumed —
                        # reconcile returns the shard to pending and
                        # we re-request
                        stale_retries -= 1
                        if stale_retries < 0:
                            raise StaleConsumerError(
                                f"consumer {self._cid} fenced "
                                f"mid-fetch")
                        self._reattach()
                        continue
                    b = out["bid"]
                    self._consumed.append(b)
                    self._pending_acks = [b]
                    return value
                if status == "wait":
                    self._pending_acks = []
                    if not wtok:
                        wtok = waits_mod.park(
                            "data-grant", self._job, job=self._job,
                            consumer=self._cid, gen=self._gen)
                    time.sleep(poll_s)
                    continue
                if status == "reconcile":
                    self._reconcile()
                    continue
                if status == "stale":
                    stale_retries -= 1
                    if stale_retries < 0:
                        raise StaleConsumerError(
                            f"consumer {self._cid} fenced: "
                            f"{out.get('why')}")
                    self._reattach()
                    continue
                if status == "end":
                    self._pending_acks = []
                    self._done = True
                    raise StopIteration
                raise RuntimeError(
                    f"unexpected dispatcher reply {out!r}")
        finally:
            waits_mod.unpark(wtok)

    # -- PR-11 resume hook --------------------------------------------------

    def fast_forward(self, n: int) -> int:
        """Absolute seek: the next block drawn is this consumer's n-th
        (grant-and-auto-ack on the dispatcher, nothing fetched). The
        `_fast_forward_batches` hook in train/spmd_trainer.py calls
        this on resume/reform so a restarted trainer skips consumed
        batches instead of re-training on them."""
        self.flush_acks()
        poll_s = _knob_float("RAY_TPU_DATA_SERVICE_POLL_S")
        skipped = 0
        deadline = time.time() + 60.0
        while True:
            out = _call("fast_forward", self._job, self._cid,
                        self._gen, int(n), name=self._name)
            if out.get("status") == "stale":
                self._reattach()
                continue
            skipped += int(out.get("skipped", 0))
            # production may still be warming up: keep seeking until
            # the cursor reaches n (or nothing is left to skip)
            if int(out.get("consumed", n)) >= n \
                    or out.get("done") \
                    or time.time() > deadline:
                return skipped
            time.sleep(poll_s)

    # -- lifecycle ----------------------------------------------------------

    def flush_acks(self) -> None:
        if self._pending_acks:
            _call("ack", self._job, self._cid, self._gen,
                  list(self._pending_acks), name=self._name)
            self._pending_acks = []

    def close(self) -> None:
        try:
            self.flush_acks()
        except Exception:  # noqa: BLE001 — best-effort on teardown
            pass

    @property
    def consumed_bids(self) -> List[str]:
        return list(self._consumed)

    def iter_jax_batches(self, *, sharding=None,
                         prefetch: Optional[int] = None, dtypes=None):
        """Consumer-side prefetch into device memory: blocks flow
        through data/device_loader.py's double-buffered
        device_put_iterator (satellite e)."""
        from .device_loader import device_put_iterator  # noqa: PLC0415
        return device_put_iterator(self, sharding=sharding,
                                   prefetch=prefetch, dtypes=dtypes)


def shutdown_service(name: str = SERVICE_ACTOR_NAME) -> None:
    """Tear down the dispatcher + worker pool (tests / bench)."""
    api = _api()
    try:
        disp = api.get_actor(name, timeout=0.5)
    except ValueError:
        return
    try:
        api.get(disp.graceful_shutdown.remote(), timeout=15.0)
    except Exception:  # noqa: BLE001
        pass
    try:
        api.kill(disp)
    except Exception:  # noqa: BLE001
        pass
