"""Cross-language C++ tasks and actors (SURVEY C18).

Reference parity: ``ray.cross_language`` + the Ray C++ worker API
(reference: python/ray/cross_language.py — ``java_function`` /
``cpp_function`` descriptors; cpp/include/ray/api.h).  Ray routes a
cross-language call to a dedicated C++ worker process speaking the raylet
protocol.  ray_tpu's single-controller redesign runs C++ IN-PROCESS: the
scheduler places the task/actor on a normal worker exactly like any other
(resources, placement groups, retries, lineage all apply), and the worker
``dlopen``s the user's shared library and calls through the stable C ABI
declared in ``ray_tpu/_native/cross_lang.hpp``.  Benefits on this
architecture: no extra process hop or second wire protocol — the only
per-call cost is one encode into a compact wire buffer (C++ reads array
payloads in place from that buffer; results decode as zero-copy numpy
views over the reply).

Usage::

    import ray_tpu
    from ray_tpu import cross_language as xl

    add = xl.cpp_function("libmy.so", "add")
    ray_tpu.get(add.remote(2, 3))                      # -> 5

    Counter = xl.cpp_actor("libmy.so", "Counter", methods=("inc", "get"))
    c = Counter.remote(10)
    ray_tpu.get(c.inc.remote())                        # -> 11

Value interchange (both directions): None, bool, int, float, str, bytes,
list/tuple, dict, numpy ndarray (f32/f64/i8/i32/i64/u8/u32/u64/bool).
ObjectRef arguments work like on any task — the worker resolves them
before invoking the C++ function.  Errors raised in C++ (or unknown
function/class names) surface to the caller as ``CrossLanguageError``
wrapped in the normal ``TaskError`` machinery.
"""
from __future__ import annotations

import ctypes
import os
import struct
import threading
from typing import Any, Dict, Iterable, Optional, Sequence, Tuple

import numpy as np

from .exceptions import RayTpuError

__all__ = [
    "CrossLanguageError", "cpp_function", "cpp_actor", "manifest",
    "encode", "decode",
]


class CrossLanguageError(RayTpuError):
    """An error raised inside a cross-language C++ function/actor."""


# ------------------------------------------------------------------ codec
# Wire format shared with _native/cross_lang.hpp (see header comment there).

_DTYPE_TO_CODE = {
    np.dtype(np.float32): 1, np.dtype(np.float64): 2,
    np.dtype(np.int8): 3, np.dtype(np.int32): 4, np.dtype(np.int64): 5,
    np.dtype(np.uint8): 6, np.dtype(np.uint32): 7, np.dtype(np.uint64): 8,
    np.dtype(np.bool_): 9,
}
_CODE_TO_DTYPE = {v: k for k, v in _DTYPE_TO_CODE.items()}

_I64 = struct.Struct("<q")
_F64 = struct.Struct("<d")
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")


def _encode_into(obj: Any, out: bytearray) -> None:
    if obj is None:
        out += b"N"
    elif isinstance(obj, (bool, np.bool_)):
        out += b"T" if obj else b"F"
    elif isinstance(obj, (int, np.integer)):
        v = int(obj)
        if not -(1 << 63) <= v < (1 << 63):
            raise TypeError(
                f"int {v} exceeds the cross-language int64 wire range")
        out += b"i" + _I64.pack(v)
    elif isinstance(obj, (float, np.floating)):
        out += b"d" + _F64.pack(float(obj))
    elif isinstance(obj, str):
        raw = obj.encode("utf-8")
        out += b"s" + _U32.pack(len(raw)) + raw
    elif isinstance(obj, (bytes, bytearray, memoryview)):
        raw = bytes(obj)
        out += b"b" + _U32.pack(len(raw)) + raw
    elif isinstance(obj, (list, tuple)):
        out += b"l" + _U32.pack(len(obj))
        for item in obj:
            _encode_into(item, out)
    elif isinstance(obj, dict):
        out += b"m" + _U32.pack(len(obj))
        for k, v in obj.items():
            _encode_into(k, out)
            _encode_into(v, out)
    elif isinstance(obj, np.ndarray):
        code = _DTYPE_TO_CODE.get(obj.dtype)
        if code is None:
            raise TypeError(
                f"cross-language arrays support "
                f"{sorted(str(d) for d in _DTYPE_TO_CODE)}; got {obj.dtype}")
        arr = np.ascontiguousarray(obj)
        out += b"a" + bytes([code, arr.ndim])
        for dim in arr.shape:
            out += _U64.pack(dim)
        out += arr.tobytes()
    else:
        raise TypeError(
            f"type {type(obj).__name__} cannot cross the C++ boundary; "
            "supported: None/bool/int/float/str/bytes/list/dict/ndarray")


def encode(obj: Any) -> bytes:
    out = bytearray()
    _encode_into(obj, out)
    return bytes(out)


def _decode_one(buf: memoryview, pos: int) -> Tuple[Any, int]:
    tag = buf[pos]
    pos += 1
    if tag == 0x4E:  # N
        return None, pos
    if tag == 0x54:  # T
        return True, pos
    if tag == 0x46:  # F
        return False, pos
    if tag == 0x69:  # i
        return _I64.unpack_from(buf, pos)[0], pos + 8
    if tag == 0x64:  # d
        return _F64.unpack_from(buf, pos)[0], pos + 8
    if tag in (0x73, 0x62):  # s / b
        (n,) = _U32.unpack_from(buf, pos)
        pos += 4
        raw = bytes(buf[pos:pos + n])
        return (raw.decode("utf-8") if tag == 0x73 else raw), pos + n
    if tag == 0x6C:  # l
        (n,) = _U32.unpack_from(buf, pos)
        pos += 4
        items = []
        for _ in range(n):
            item, pos = _decode_one(buf, pos)
            items.append(item)
        return items, pos
    if tag == 0x6D:  # m
        (n,) = _U32.unpack_from(buf, pos)
        pos += 4
        d: Dict[Any, Any] = {}
        for _ in range(n):
            k, pos = _decode_one(buf, pos)
            v, pos = _decode_one(buf, pos)
            d[k] = v
        return d, pos
    if tag == 0x61:  # a
        code, ndim = buf[pos], buf[pos + 1]
        pos += 2
        dtype = _CODE_TO_DTYPE.get(code)
        if dtype is None:
            raise CrossLanguageError(f"bad ndarray dtype code {code}")
        shape = []
        for _ in range(ndim):
            shape.append(_U64.unpack_from(buf, pos)[0])
            pos += 8
        count = int(np.prod(shape, dtype=np.int64))
        nbytes = count * dtype.itemsize
        if len(buf) - pos < nbytes:
            raise CrossLanguageError(
                f"truncated ndarray payload: need {nbytes} bytes, "
                f"have {len(buf) - pos}")
        # zero-copy view over the reply buffer (kept alive via .base)
        arr = np.frombuffer(buf, dtype=dtype, count=count, offset=pos)
        return arr.reshape(shape), pos + nbytes
    raise CrossLanguageError(f"bad wire tag {tag!r}")


def decode(buf: bytes) -> Any:
    obj, pos = _decode_one(memoryview(buf), 0)
    if pos != len(buf):
        raise CrossLanguageError(
            f"trailing bytes after decode ({len(buf) - pos})")
    return obj


# ------------------------------------------------------------- lib loading

_LIBS: Dict[str, "_CppLib"] = {}
_LIBS_LOCK = threading.Lock()


class _CppLib:
    """A dlopen()ed user library exposing the xl C ABI (cached per
    process; workers are processes, so each worker loads at most once)."""

    def __init__(self, path: str):
        self.path = path
        self.cdll = ctypes.CDLL(path)
        f = self.cdll
        f.xl_invoke.restype = ctypes.c_int
        f.xl_invoke.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_ulonglong,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte)),
            ctypes.POINTER(ctypes.c_ulonglong),
            ctypes.POINTER(ctypes.c_char_p)]
        f.xl_actor_new.restype = ctypes.c_void_p
        f.xl_actor_new.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_ulonglong,
            ctypes.POINTER(ctypes.c_char_p)]
        f.xl_actor_invoke.restype = ctypes.c_int
        f.xl_actor_invoke.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_ulonglong,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte)),
            ctypes.POINTER(ctypes.c_ulonglong),
            ctypes.POINTER(ctypes.c_char_p)]
        f.xl_actor_del.restype = None
        f.xl_actor_del.argtypes = [ctypes.c_void_p]
        f.xl_free.restype = None
        f.xl_free.argtypes = [ctypes.c_void_p]
        f.xl_manifest.restype = ctypes.c_char_p
        f.xl_manifest.argtypes = []

    def _take_out(self, rc: int, out, out_len, err) -> bytes:
        if rc != 0:
            msg = err.value.decode("utf-8", "replace") if err.value \
                else f"cross-language call failed (rc={rc})"
            if err.value is not None:
                self.cdll.xl_free(err)
            raise CrossLanguageError(f"[{os.path.basename(self.path)}] {msg}")
        try:
            return ctypes.string_at(out, out_len.value)
        finally:
            if out:
                self.cdll.xl_free(out)

    def invoke(self, name: str, payload: bytes) -> Any:
        out = ctypes.POINTER(ctypes.c_ubyte)()
        out_len = ctypes.c_ulonglong(0)
        err = ctypes.c_char_p()
        rc = self.cdll.xl_invoke(
            name.encode(), payload, len(payload),
            ctypes.byref(out), ctypes.byref(out_len), ctypes.byref(err))
        return decode(self._take_out(rc, out, out_len, err))

    def actor_new(self, cls: str, payload: bytes) -> int:
        err = ctypes.c_char_p()
        handle = self.cdll.xl_actor_new(
            cls.encode(), payload, len(payload), ctypes.byref(err))
        if not handle:
            msg = err.value.decode("utf-8", "replace") if err.value \
                else f"failed to construct C++ actor {cls}"
            if err.value is not None:
                self.cdll.xl_free(err)
            raise CrossLanguageError(f"[{os.path.basename(self.path)}] {msg}")
        return handle

    def actor_invoke(self, handle: int, method: str, payload: bytes) -> Any:
        out = ctypes.POINTER(ctypes.c_ubyte)()
        out_len = ctypes.c_ulonglong(0)
        err = ctypes.c_char_p()
        rc = self.cdll.xl_actor_invoke(
            ctypes.c_void_p(handle), method.encode(), payload, len(payload),
            ctypes.byref(out), ctypes.byref(out_len), ctypes.byref(err))
        return decode(self._take_out(rc, out, out_len, err))

    def actor_del(self, handle: int) -> None:
        self.cdll.xl_actor_del(ctypes.c_void_p(handle))

    def manifest(self) -> str:
        return self.cdll.xl_manifest().decode()


def _load(path: str) -> _CppLib:
    path = os.path.abspath(path)
    with _LIBS_LOCK:
        lib = _LIBS.get(path)
        if lib is None:
            lib = _CppLib(path)
            _LIBS[path] = lib
        return lib


def manifest(lib_path: str) -> Dict[str, list]:
    """List the functions/actor classes a library registers, e.g.
    ``{"functions": ["add"], "actors": ["Counter"]}``."""
    fns, actors = [], []
    for line in _load(lib_path).manifest().splitlines():
        kind, _, name = line.partition(" ")
        (fns if kind == "fn" else actors).append(name)
    return {"functions": fns, "actors": actors}


def _encode_call(args: tuple, kwargs: dict) -> bytes:
    # kwargs piggyback as a trailing {"__xl_kwargs__": {...}} map so the
    # C++ side (positional-only by convention) can opt in via Value::find.
    items = list(args)
    if kwargs:
        items.append({"__xl_kwargs__": dict(kwargs)})
    return encode(items)


# ---------------------------------------------------------------- task API

def cpp_function(lib_path: str, name: str, **task_options):
    """A remote-callable for C++ function `name` in shared library
    `lib_path` (built against cross_lang.hpp; see module docstring).
    Accepts the same options as ``@ray_tpu.remote`` (num_cpus, resources,
    max_retries, ...)."""
    from . import api

    lib_path = os.path.abspath(lib_path)

    def _cpp_shim(*args, **kwargs):
        return _load(lib_path).invoke(name, _encode_call(args, kwargs))

    _cpp_shim.__name__ = _cpp_shim.__qualname__ = f"cpp:{name}"
    _cpp_shim.__doc__ = f"cross-language C++ task {name} [{lib_path}]"
    return api.RemoteFunction(_cpp_shim, **task_options)


# --------------------------------------------------------------- actor API

def cpp_actor(lib_path: str, cls: str,
              methods: Optional[Sequence[str]] = None, **actor_options):
    """An actor class backed by C++ class `cls` in `lib_path`.

    `methods` names the Python-visible methods (each dispatches to
    ``Actor::call(method, args)`` on the C++ side).  If omitted, the
    driver loads the library locally to check the class exists and
    exposes only the generic ``invoke(method, *args)``.  Accepts the same
    options as ``@ray_tpu.remote`` on a class (num_cpus, resources,
    max_restarts, ...).
    """
    from . import api

    lib_path = os.path.abspath(lib_path)
    if methods is None:
        listed = manifest(lib_path)
        if cls not in listed["actors"]:
            raise CrossLanguageError(
                f"library {lib_path} registers no actor class {cls!r} "
                f"(has: {listed['actors']})")
        methods = ()

    def _make_method(mname: str):
        def method(self, *args, **kwargs):
            return _cpp_actor_invoke_generic(self, mname, *args, **kwargs)
        method.__name__ = mname
        return method

    ns = {
        "__init__": _cpp_actor_init,
        "__module__": __name__,
        "__doc__": f"cross-language C++ actor {cls} [{lib_path}]",
        "_xl_lib_path": lib_path,
        "_xl_cls": cls,
        "invoke": _cpp_actor_invoke_generic,
        "close": _cpp_actor_exit,
    }
    for mname in methods:
        if mname in ns:
            raise CrossLanguageError(
                f"method name {mname!r} collides with the actor protocol")
        ns[mname] = _make_method(mname)
    proxy = type(f"Cpp{cls}", (), ns)
    return api.remote(**actor_options)(proxy)


def _cpp_actor_init(self, *args, **kwargs):
    self._xl_lib = _load(type(self)._xl_lib_path)
    self._xl_lock = threading.Lock()
    self._xl_inflight = 0
    self._xl_close_pending = False
    self._xl_handle = self._xl_lib.actor_new(
        type(self)._xl_cls, _encode_call(args, kwargs))


def _cpp_actor_invoke_generic(self, method: str, *args, **kwargs):
    # With max_concurrency>1 methods run on worker threads; the inflight
    # count keeps close() from deleting the C++ object mid-call.  (Method
    # bodies themselves may still run concurrently — thread-safety INSIDE
    # Actor::call is the C++ class's responsibility, as for any actor.)
    with self._xl_lock:
        if not self._xl_handle:
            raise CrossLanguageError(
                f"C++ actor {type(self).__name__} is closed "
                f"(handle destroyed)")
        handle = self._xl_handle
        self._xl_inflight += 1
    try:
        return self._xl_lib.actor_invoke(
            handle, method, _encode_call(args, kwargs))
    finally:
        with self._xl_lock:
            self._xl_inflight -= 1
            last_out = self._xl_inflight == 0
            deferred = self._xl_close_pending and last_out \
                and self._xl_handle
            if deferred:
                handle, self._xl_handle = self._xl_handle, None
                self._xl_close_pending = False
        if deferred:
            self._xl_lib.actor_del(handle)


def _cpp_actor_exit(self):
    """Destroy the underlying C++ object (optional — the worker process
    owns the actor, so process exit reclaims it either way).  If calls
    are in flight, deletion is deferred to the last one to drain."""
    with self._xl_lock:
        if not getattr(self, "_xl_handle", None):
            return
        if self._xl_inflight:
            self._xl_close_pending = True
            return
        handle, self._xl_handle = self._xl_handle, None
    self._xl_lib.actor_del(handle)
