"""Durable workflows: run a DAG with a persistent step log + resume.

Reference counterpart: python/ray/workflow (workflow.run over a ray.dag,
checkpointed step results, resume by workflow_id, list/status APIs) —
the "lite" scope from SURVEY.md §2.8 O10. Every FunctionNode /
ClassMethodNode result is pickled under
  <storage>/<workflow_id>/steps/<step_key>.pkl
keyed by a deterministic hash of the node's position in the DAG, so a
re-run (or a resume after a crash) skips completed steps and re-executes
only what's missing.
"""
from __future__ import annotations

import hashlib
import json
import os
import pickle
import time
from typing import Any, Dict, List, Optional

from .dag import (ClassMethodNode, ClassNode, DAGNode, FunctionNode,
                  InputAttributeNode, InputNode, MultiOutputNode)

_DEFAULT_STORAGE = os.path.expanduser("~/.ray_tpu/workflows")
_storage = _DEFAULT_STORAGE


def init(storage: Optional[str] = None) -> None:
    global _storage
    _storage = storage or _DEFAULT_STORAGE
    os.makedirs(_storage, exist_ok=True)


def _wf_dir(workflow_id: str) -> str:
    return os.path.join(_storage, workflow_id)


def _step_key(node: DAGNode, child_keys: List[str],
              salt: str = "") -> str:
    """Deterministic key: node kind + callable name + child keys. Bound
    positions (not live ids) so re-built DAGs of the same shape match.
    `salt` carries the run-input digest for nodes downstream of an
    InputNode, so cached results computed from different execute()-time
    inputs are never replayed."""
    if isinstance(node, FunctionNode):
        name = getattr(node._remote_fn, "__name__", "fn")
    elif isinstance(node, ClassMethodNode):
        name = f"{node._class_node._actor_cls._cls.__name__}.{node._method_name}"
    else:
        name = type(node).__name__
    h = hashlib.sha1()
    h.update(name.encode())
    h.update(salt.encode())
    for ck in child_keys:
        h.update(ck.encode())
    # literal (non-node) args participate so different bindings differ
    for a in list(node._bound_args) + sorted(
            f"{k}={v}" for k, v in node._bound_kwargs.items()
            if not isinstance(v, DAGNode)):
        if not isinstance(a, DAGNode):
            h.update(repr(a).encode())
    return f"{name}-{h.hexdigest()[:12]}"


class _DurableExec:
    """Executes a DAG bottom-up, checkpointing durable-node results."""

    def __init__(self, workflow_id: str, input_args, input_kwargs):
        self.wf_dir = _wf_dir(workflow_id)
        self.steps_dir = os.path.join(self.wf_dir, "steps")
        os.makedirs(self.steps_dir, exist_ok=True)
        self.input_args = input_args
        self.input_kwargs = input_kwargs
        # pickle, not repr: repr elides large numpy arrays ('...') and
        # embeds memory addresses for default-repr objects — both break
        # the "same inputs <=> same salt" contract.
        digest = hashlib.sha1(pickle.dumps(
            (input_args, sorted((input_kwargs or {}).items()))
        )).hexdigest()[:12]
        self.input_salt = f"inputs:{digest}"
        self._memo: Dict[int, Any] = {}
        self._keys: Dict[int, str] = {}
        self._uses_input_memo: Dict[int, bool] = {}
        self._base_counts: Dict[str, int] = {}
        self.steps_run = 0
        self.steps_skipped = 0

    def _ckpt_path(self, key: str) -> str:
        return os.path.join(self.steps_dir, key + ".pkl")

    def resolve(self, node: DAGNode) -> Any:
        if node._node_id in self._memo:
            return self._memo[node._node_id]
        value = self._eval(node)
        self._memo[node._node_id] = value
        return value

    def _eval(self, node: DAGNode) -> Any:
        import ray_tpu
        if isinstance(node, InputNode):
            if self.input_kwargs or len(self.input_args) != 1:
                return (self.input_args, self.input_kwargs)
            return self.input_args[0]
        if isinstance(node, InputAttributeNode):
            base = self.resolve(node._bound_args[0])
            return (getattr(base, node._key) if node._kind == "attr"
                    else base[node._key])
        if isinstance(node, MultiOutputNode):
            return [self.resolve(n) for n in node._bound_args]
        if isinstance(node, ClassNode):
            args, kwargs = self._resolved_args(node)
            if node._handle is None:
                node._handle = node._actor_cls.remote(*args, **kwargs)
            return node._handle

        # durable step: FunctionNode / ClassMethodNode
        key = self._key_of(node)
        path = self._ckpt_path(key)
        if os.path.exists(path):
            self.steps_skipped += 1
            with open(path, "rb") as f:
                return pickle.load(f)
        args, kwargs = self._resolved_args(node)
        if isinstance(node, FunctionNode):
            ref = node._remote_fn.remote(*args, **kwargs)
        else:
            handle = self.resolve(node._class_node)
            ref = getattr(handle, node._method_name).remote(*args, **kwargs)
        value = ray_tpu.get(ref)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(value, f)
        os.replace(tmp, path)          # atomic: crash never half-writes
        self.steps_run += 1
        return value

    def _uses_input(self, node: DAGNode) -> bool:
        nid = node._node_id
        if nid not in self._uses_input_memo:
            self._uses_input_memo[nid] = (
                isinstance(node, (InputNode, InputAttributeNode))
                or any(self._uses_input(c) for c in node._children()))
        return self._uses_input_memo[nid]

    def _key_of(self, node: DAGNode) -> str:
        if node._node_id not in self._keys:
            salt = self.input_salt if self._uses_input(node) else ""
            base = _step_key(node, [self._key_of(c) for c in node._children()],
                             salt)
            # identical sibling subtrees (e.g. two sample.bind(cfg) calls)
            # must be distinct steps: suffix by occurrence. DFS resolution
            # order is deterministic for a given DAG shape, so a rebuilt
            # DAG assigns the same suffixes.
            n = self._base_counts.get(base, 0)
            self._base_counts[base] = n + 1
            self._keys[node._node_id] = base if n == 0 else f"{base}-{n}"
        return self._keys[node._node_id]

    def _resolved_args(self, node: DAGNode):
        args = tuple(self.resolve(a) if isinstance(a, DAGNode) else a
                     for a in node._bound_args)
        kwargs = {k: self.resolve(v) if isinstance(v, DAGNode) else v
                  for k, v in node._bound_kwargs.items()}
        return args, kwargs


def run(dag: DAGNode, *, workflow_id: Optional[str] = None,
        args: tuple = (), kwargs: Optional[dict] = None) -> Any:
    """Execute durably; returns the DAG output VALUE (not a ref)."""
    os.makedirs(_storage, exist_ok=True)
    workflow_id = workflow_id or f"wf-{int(time.time() * 1000):x}"
    wf_dir = _wf_dir(workflow_id)
    os.makedirs(wf_dir, exist_ok=True)
    meta_path = os.path.join(wf_dir, "meta.json")
    meta = {"workflow_id": workflow_id, "status": "RUNNING",
            "started_at": time.time()}
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    ex = _DurableExec(workflow_id, args, kwargs or {})
    try:
        result = ex.resolve(dag)
    except BaseException as e:
        meta.update(status="FAILED", error=repr(e),
                    finished_at=time.time())
        with open(meta_path, "w") as f:
            json.dump(meta, f)
        raise
    with open(os.path.join(wf_dir, "output.pkl"), "wb") as f:
        pickle.dump(result, f)
    meta.update(status="SUCCEEDED", finished_at=time.time(),
                steps_run=ex.steps_run, steps_skipped=ex.steps_skipped)
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    return result


def resume(workflow_id: str, dag: DAGNode, *, args: tuple = (),
           kwargs: Optional[dict] = None) -> Any:
    """Re-run by id: completed steps load from the log, the rest execute."""
    if not os.path.isdir(_wf_dir(workflow_id)):
        raise ValueError(f"no workflow {workflow_id!r} under {_storage}")
    return run(dag, workflow_id=workflow_id, args=args, kwargs=kwargs)


def get_status(workflow_id: str) -> Optional[str]:
    try:
        with open(os.path.join(_wf_dir(workflow_id), "meta.json")) as f:
            return json.load(f)["status"]
    except (OSError, KeyError, ValueError):
        return None


def get_output(workflow_id: str) -> Any:
    path = os.path.join(_wf_dir(workflow_id), "output.pkl")
    if not os.path.exists(path):
        raise ValueError(f"workflow {workflow_id!r} has no output "
                         f"(status={get_status(workflow_id)})")
    with open(path, "rb") as f:
        return pickle.load(f)


def list_all() -> List[Dict[str, Any]]:
    if not os.path.isdir(_storage):
        return []
    out = []
    for wid in sorted(os.listdir(_storage)):
        meta_path = os.path.join(_storage, wid, "meta.json")
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                out.append(json.load(f))
    return out


def delete(workflow_id: str) -> None:
    import shutil
    shutil.rmtree(_wf_dir(workflow_id), ignore_errors=True)
