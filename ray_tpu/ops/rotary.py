"""Rotary position embeddings (RoPE), Llama-3 style with NTK scaling hook."""
from __future__ import annotations

import jax
import jax.numpy as jnp


def rope_frequencies(head_dim: int, max_len: int, theta: float = 500000.0,
                     dtype=jnp.float32):
    """Precompute cos/sin tables: shape (max_len, head_dim//2)."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2,
                                           dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


def apply_rotary(x: jax.Array, cos: jax.Array, sin: jax.Array,
                 positions: jax.Array | None = None) -> jax.Array:
    """Rotate pairs (x0,x1) -> (x0 cos - x1 sin, x0 sin + x1 cos).

    x: (..., seq, heads, head_dim). cos/sin: (max_len, head_dim//2).
    positions: optional (..., seq) int array for non-contiguous positions
    (decode steps, packed sequences).
    """
    seq = x.shape[-3]
    if positions is None:
        c = cos[:seq]
        s = sin[:seq]
        # broadcast over leading batch dims and heads
        c = c[None, :, None, :] if x.ndim == 4 else c[:, None, :]
        s = s[None, :, None, :] if x.ndim == 4 else s[:, None, :]
    else:
        c = jnp.take(cos, positions, axis=0)[..., :, None, :]
        s = jnp.take(sin, positions, axis=0)[..., :, None, :]
    x1, x2 = jnp.split(x, 2, axis=-1)
    cdt = c.astype(x.dtype)
    sdt = s.astype(x.dtype)
    return jnp.concatenate([x1 * cdt - x2 * sdt,
                            x1 * sdt + x2 * cdt], axis=-1)
