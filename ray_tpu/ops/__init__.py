"""TPU-native compute ops: the building blocks the reference gets from
torch/CUDA kernels (apex, flash-attn), re-built on XLA + Pallas.

XLA fuses elementwise chains into matmuls on its own; Pallas kernels are
reserved for the patterns XLA won't fuse (flash attention inner loop).
Every op here is jit-traceable with static shapes.
"""
from .norms import rms_norm, layer_norm
from .rotary import apply_rotary, rope_frequencies
from .attention import (multi_head_attention, causal_attention_mask,
                        cached_attention)
from .activations import swiglu, geglu
from .ring_attention import ring_attention
from .moe import (moe_dispatch_combine, top_k_routing, expert_capacity,
                  MoEAux)

__all__ = ["rms_norm", "layer_norm", "apply_rotary", "rope_frequencies",
           "multi_head_attention", "causal_attention_mask",
           "cached_attention", "swiglu",
           "geglu", "ring_attention", "moe_dispatch_combine",
           "top_k_routing", "expert_capacity", "MoEAux"]
