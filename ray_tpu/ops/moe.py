"""Mixture-of-Experts: top-k routing + capacity-factor dispatch, TPU-first.

The reference runs MoE models through HF torch implementations (per-token
gather/scatter with dynamic shapes). That shape-dynamism defeats XLA, so
this is the GShard/Switch formulation instead: routing becomes one-hot
einsums with *static* shapes — dispatch (G,E,C) x tokens (G,d) -> expert
batches (E,C,d) — which XLA lowers to MXU matmuls and, when the expert dim
is sharded over the `ep` mesh axis, to an all-to-all over ICI. Tokens
overflowing an expert's capacity C are dropped (output 0 for that expert's
contribution), the standard capacity-factor trade.

Routing follows Mixtral: softmax over the top-k logits only. Aux losses
(load-balance + router z-loss) come back alongside the output.
"""
from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp


class MoEAux(NamedTuple):
    load_balance_loss: jax.Array   # scalar, Switch-style
    router_z_loss: jax.Array       # scalar
    expert_load: jax.Array         # (E,) fraction of tokens per expert


def expert_capacity(n_tokens: int, n_experts: int, k: int,
                    capacity_factor: float) -> int:
    cap = int(n_tokens * k * capacity_factor / n_experts)
    return max(cap, 1)


def top_k_routing(router_logits: jax.Array, k: int):
    """router_logits: (G, E). Returns (weights (G,k), indices (G,k)) with
    weights = softmax over the selected top-k logits (Mixtral convention)."""
    top_logits, top_idx = jax.lax.top_k(router_logits, k)
    weights = jax.nn.softmax(top_logits.astype(jnp.float32), axis=-1)
    return weights, top_idx


def moe_dispatch_combine(x: jax.Array, router_logits: jax.Array,
                         expert_fn: Callable[[jax.Array], jax.Array],
                         *, k: int = 2,
                         capacity_factor: float = 1.25,
                         capacity: Optional[int] = None):
    """x: (G, d) flattened tokens; router_logits: (G, E).

    expert_fn: (E, C, d) -> (E, C, d_out), typically a vmap over the expert
    dim of stacked expert weights (sharded over `ep`).

    Returns (out (G, d_out), MoEAux).
    """
    g, d = x.shape
    e = router_logits.shape[-1]
    c = capacity if capacity is not None else expert_capacity(
        g, e, k, capacity_factor)

    weights, top_idx = top_k_routing(router_logits, k)     # (G,k)
    # (G, k, E) one-hot of chosen experts, ranked by k-slot priority.
    assign = jax.nn.one_hot(top_idx, e, dtype=jnp.float32)
    # Position of each (token, slot) within its expert queue: slot-major
    # ordering so slot-0 (highest-priority) choices win capacity (GShard).
    # int32 cumsum keeps queue positions exact past 2^24 assignments.
    slot_major = assign.transpose(1, 0, 2).reshape(k * g, e).astype(jnp.int32)
    pos_slot_major = jnp.cumsum(slot_major, axis=0) - slot_major   # (k*G, E)
    pos = pos_slot_major.reshape(k, g, e).transpose(1, 0, 2)       # (G,k,E)
    within_cap = pos < c
    keep = assign * within_cap                                      # (G,k,E)
    slot_pos = (pos * keep).sum(-1).astype(jnp.int32)               # (G,k)
    kept_expert = keep                                              # (G,k,E)

    # dispatch (G, E, C): one-hot over capacity slot for kept assignments.
    cap_onehot = jax.nn.one_hot(slot_pos, c, dtype=jnp.float32)     # (G,k,C)
    dispatch = jnp.einsum("gke,gkc->gec", kept_expert, cap_onehot)
    combine = jnp.einsum("gke,gk,gkc->gec", kept_expert,
                         weights, cap_onehot)

    expert_in = jnp.einsum("gec,gd->ecd", dispatch.astype(x.dtype), x)
    expert_out = expert_fn(expert_in)                               # (E,C,do)
    out = jnp.einsum("gec,ecd->gd", combine.astype(expert_out.dtype),
                     expert_out)

    # Aux losses (fp32): Switch load-balance = E * sum(frac_tokens * frac_prob)
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    frac_prob = probs.mean(axis=0)                                  # (E,)
    frac_tokens = assign.sum(axis=1).mean(axis=0)                   # (E,)
    lb = e * jnp.sum(frac_prob * frac_tokens) / k
    z = jnp.mean(jax.nn.logsumexp(
        router_logits.astype(jnp.float32), axis=-1) ** 2)
    return out, MoEAux(lb, z, frac_tokens)
