"""Weight-only int8 quantization for serving.

Reference counterpart: the reference serves large models through vLLM
quantization backends (GPTQ/AWQ/int8 weight-only); TPU-first version:
per-output-channel symmetric int8 kernels with fp32 scales, dequantized
INSIDE the matmul (XLA fuses the int8->bf16 convert into the dot's
operand read, so the kernel streams HBM at 1 byte/weight — the whole
point: Llama-3-8B's ~6.6B matmul weights drop from 13 GB bf16 to
6.6 GB, fitting one 16 GB chip with KV cache to spare).

Accuracy: symmetric per-column scales keep relative error ~1/256 per
weight; logits stay argmax-stable for serving (test-asserted).
"""
from __future__ import annotations

from typing import Any, Dict

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


class QuantDense(nn.Module):
    """Drop-in nn.Dense(use_bias=False) with int8 weights.

    Params: kernel_q (in, out) int8, scale (out,) fp32 — produced by
    quantize_dense / quantize_llama_params, never trained. The matmul
    runs in `dtype` with fp32 accumulation on the MXU.
    """
    features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        in_dim = x.shape[-1]
        kq = self.param("kernel_q", nn.initializers.zeros,
                        (in_dim, self.features), jnp.int8)
        scale = self.param("scale", nn.initializers.ones,
                           (self.features,), jnp.float32)
        y = jnp.einsum("...i,io->...o", x.astype(self.dtype),
                       kq.astype(self.dtype),
                       preferred_element_type=jnp.float32)
        return (y * scale).astype(self.dtype)


def quantize_dense(kernel: np.ndarray) -> Dict[str, np.ndarray]:
    """fp kernel (in, out) -> {kernel_q int8, scale fp32} with
    symmetric per-output-channel scales."""
    w = np.asarray(kernel, np.float32)
    amax = np.maximum(np.abs(w).max(axis=0), 1e-8)      # (out,)
    scale = (amax / 127.0).astype(np.float32)
    q = np.clip(np.rint(w / scale), -127, 127).astype(np.int8)
    return {"kernel_q": q, "scale": scale}


_DENSE_NAMES = ("q_proj", "k_proj", "v_proj", "o_proj",
                "gate_proj", "up_proj", "down_proj")


def quantize_llama_params(params) -> Any:
    """Llama fp param tree -> the tree a quant='int8' Llama expects:
    every projection kernel becomes {kernel_q, scale}; norms,
    embeddings and the LM head stay in their original dtype (the head
    feeds sampling — keep it full precision)."""
    def walk(tree):
        out = {}
        for k, v in tree.items():
            if k in _DENSE_NAMES and isinstance(v, dict) \
                    and "kernel" in v:
                out[k] = quantize_dense(np.asarray(v["kernel"]))
            elif isinstance(v, dict):
                out[k] = walk(v)
            else:
                out[k] = v
        return out

    return walk(jax.device_get(params))


def quantized_bytes(params) -> int:
    """Total parameter bytes of a (possibly quantized) tree."""
    return sum(np.asarray(x).nbytes
               for x in jax.tree_util.tree_leaves(params))


__all__ = ["QuantDense", "quantize_dense", "quantize_llama_params",
           "quantized_bytes"]
