"""Attention on TPU.

Default path: `jax.nn.dot_product_attention`, which XLA lowers to an MXU-
friendly fused kernel (and to TPU flash attention where supported). A Pallas
flash-attention kernel (ray_tpu/ops/pallas/flash_attention.py) can be
selected with impl="pallas" for long sequences.

Replaces the reference's torch scaled_dot_product_attention / flash-attn
dependency in its model code (e.g. rllib models and train examples).
"""
from __future__ import annotations

import logging
from typing import Optional

import jax
import jax.numpy as jnp

from ..util import knobs

logger = logging.getLogger("ray_tpu.ops.attention")


def causal_attention_mask(seq_len: int, dtype=jnp.bool_) -> jax.Array:
    return jnp.tril(jnp.ones((seq_len, seq_len), dtype=dtype))


# signature -> bool: does the Pallas flash kernel lower on this backend?
_PALLAS_LOWER_CACHE: dict = {}


def pallas_flash_lowers(q, k, v, causal: bool,
                        scale: Optional[float]) -> bool:
    """Compile-check the Pallas flash kernel (forward AND backward) for
    this shape signature, once, off to the side of any surrounding trace.

    A Mosaic lowering failure must degrade to the XLA path with a warning
    — never kill the surrounding train/serve step (a single kernel bug
    zeroed the round-2 headline bench). Both directions are probed because
    whether the caller will take grads is unknowable at trace time and a
    fwd-ok/bwd-broken split would die mid-train; the extra compile is
    once per shape signature.
    """
    key = (q.shape, k.shape, str(q.dtype), str(k.dtype), bool(causal))
    hit = _PALLAS_LOWER_CACHE.get(key)
    if hit is not None:
        return hit
    if jax.default_backend() != "tpu":
        # interpret mode: no Mosaic lowering to fail
        _PALLAS_LOWER_CACHE[key] = True
        return True
    from .pallas.flash_attention import flash_attention  # noqa: PLC0415

    def probe(q, k, v):
        def loss(q, k, v):
            out = flash_attention(q, k, v, causal=causal, scale=scale)
            return out.astype(jnp.float32).sum()
        return jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)

    try:
        abstract = [jax.ShapeDtypeStruct(x.shape, x.dtype)
                    for x in (q, k, v)]
        jax.jit(probe).lower(*abstract).compile()
        ok = True
    except Exception as exc:  # Mosaic/XLA lowering errors are varied
        logger.warning(
            "Pallas flash attention failed to lower for q=%s k=%s "
            "(%s: %s); falling back to the XLA path for this signature.",
            q.shape, k.shape, type(exc).__name__, exc)
        ok = False
    _PALLAS_LOWER_CACHE[key] = ok
    return ok


def _resolve_impl(impl: str, q: jax.Array, k: jax.Array, causal: bool,
                  segment_ids) -> str:
    """"auto" = the Pallas flash kernel on TPU whenever the shape suits it
    (self-attention, long enough to tile); XLA otherwise — notably cached
    decode (Sq != Sk under causal), segment masking, and CPU, where
    interpret-mode Pallas would crawl. RAY_TPU_ATTN_IMPL overrides the
    auto choice (benchmark A/B knob)."""
    if impl == "auto":
        impl = knobs.get_str("RAY_TPU_ATTN_IMPL")
    if impl != "auto":
        return impl
    if jax.default_backend() != "tpu":
        return "xla"
    if segment_ids is not None:
        return "xla"
    if causal and q.shape[1] != k.shape[1]:
        return "xla"
    # Measured on v5e (llama 254M train, seq 1024): XLA's fused attention
    # beats the Pallas kernel end-to-end (36.6% vs 27.0% MFU) — XLA wins
    # while the S x S logits still fit comfortably; flash pays off once
    # attention is memory-bound at long sequence. Crossover ~2k.
    if q.shape[1] < 2048:
        return "xla"
    return "pallas"


def cached_attention(q: jax.Array, k: jax.Array, v: jax.Array, cache,
                     positions: jax.Array,
                     scale: Optional[float] = None,
                     impl: str = "auto"):
    """Decode/continuation attention against a per-sequence KV cache.

    q/k/v: (B, S, H{q,kv}, D) for the NEW tokens; cache = (ck, cv,
    lengths) with ck/cv (B, L, Hkv, D) and lengths (B,). Writes k/v at
    `positions` (B, S), attends causally over the written prefix, and
    returns (out (B, S, Hq, D), new_cache). Shared by every decoder in
    the zoo (llama.py, gpt2.py) — the engine's serving contract.

    A PagedKV cache entry routes to paged_cached_attention — same
    semantics over a shared page pool. `impl` (the model's
    cfg.attn_impl) governs the fresh-prefill fast path's attention
    router so a pinned implementation holds on every code path."""
    if isinstance(cache, PagedKV):
        return paged_cached_attention(q, k, v, cache, positions,
                                      scale=scale, impl=impl)
    b, s, hq, d = q.shape
    hkv = k.shape[2]
    if scale is None:
        scale = d ** -0.5
    ck, cv, lengths = cache
    idx = jnp.arange(b)
    ck = ck.at[idx[:, None], positions].set(k.astype(ck.dtype))
    cv = cv.at[idx[:, None], positions].set(v.astype(cv.dtype))
    new_lengths = jnp.maximum(lengths, positions[:, -1] + 1)
    out = _attend_cached(q, ck, cv, positions, new_lengths, scale)
    return out, (ck, cv, new_lengths)


def _attend_cached(q, ck, cv, positions, new_lengths, scale):
    """Shared attention tail for the contiguous and paged cached paths:
    length-valid mask + causal mask + GQA repeat + softmax(QK)V. ONE
    implementation so the paged engine can never drift numerically from
    the contiguous one (their token-identical contract is tested)."""
    hq = q.shape[2]
    L = ck.shape[1]
    valid = jnp.arange(L)[None, :] < new_lengths[:, None]
    logits_mask = jnp.where(valid, 0.0, jnp.finfo(jnp.float32).min)
    hkv = ck.shape[2]
    rep = hq // hkv
    kk = jnp.repeat(ck, rep, axis=2) if rep > 1 else ck
    vv = jnp.repeat(cv, rep, axis=2) if rep > 1 else cv
    att = jnp.einsum("bqhd,bkhd->bhqk", q, kk,
                     preferred_element_type=jnp.float32) * scale
    att = att + logits_mask[:, None, None, :]
    pos_k = jnp.arange(L)[None, None, None, :]
    pos_q = positions[:, None, :, None]
    att = jnp.where(pos_k <= pos_q, att, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(att, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, vv)


@jax.tree_util.register_pytree_node_class
class PagedKV:
    """Per-layer paged KV cache entry (vLLM-style, TPU-first).

    k_flat/v_flat: (N_flat, Hkv, D) — the shared page pool, flattened to
      token rows; N_flat = (n_pages [+ trash]) * page_size. Every
      sequence in the batch reads/writes the SAME pool.
    page_table: (B, P) int32 — page ids backing each sequence, in order;
      logical position p of row b lives at flat row
      page_table[b, p // page_size] * page_size + p % page_size.
      Unallocated entries point at a trash page: writes there are
      discarded by construction, reads are masked by `lengths`.
    lengths: (B,) int32 — tokens currently valid per sequence.
    page_size and `fresh` are STATIC pytree metadata. fresh=True marks
    a PURE PREFILL call (every sequence starts at length 0): attention
    then runs straight over the new tokens' k/v — no page gather at
    all, and the multi_head_attention router can pick the flash kernel
    for long prompts — while KV still scatters into the pages.
    """

    def __init__(self, k_flat, v_flat, page_table, lengths,
                 page_size: int, fresh: bool = False):
        self.k_flat = k_flat
        self.v_flat = v_flat
        self.page_table = page_table
        self.lengths = lengths
        self.page_size = page_size
        self.fresh = fresh

    def flat_rows(self, positions):
        """Flat pool row index for each (sequence, logical position) in
        `positions` (B, S) — the single definition of the page-indexing
        formula (debug/introspection/tests)."""
        ps = self.page_size
        return (jnp.take_along_axis(self.page_table, positions // ps,
                                    axis=1) * ps + positions % ps)

    def tree_flatten(self):
        return ((self.k_flat, self.v_flat, self.page_table,
                 self.lengths), (self.page_size, self.fresh))

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, *aux)


def paged_cached_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                           cache: "PagedKV", positions: jax.Array,
                           scale: Optional[float] = None,
                           impl: str = "auto"):
    """cached_attention semantics over a PagedKV pool.

    Static shapes throughout (gather width = P * page_size), so the
    decode step still compiles exactly once; the page indirection is one
    take + one scatter per layer. Storage win vs the slot cache: the
    pool is sized to the real token budget, not B * max_seq_len.
    """
    b, s, hq, d = q.shape
    if scale is None:
        scale = d ** -0.5
    k_flat, v_flat = cache.k_flat, cache.v_flat
    page_table, lengths = cache.page_table, cache.lengths
    page_size = cache.page_size
    n_pages_per_seq = page_table.shape[1]
    L = n_pages_per_seq * page_size

    # scatter the new tokens' k/v into their flat pool rows
    flat_pos = cache.flat_rows(positions)                     # (B, S)
    k_flat = k_flat.at[flat_pos.reshape(-1)].set(
        k.astype(k_flat.dtype).reshape(b * s, *k.shape[2:]))
    v_flat = v_flat.at[flat_pos.reshape(-1)].set(
        v.astype(v_flat.dtype).reshape(b * s, *v.shape[2:]))
    new_lengths = jnp.maximum(lengths, positions[:, -1] + 1)

    if cache.fresh \
            and knobs.get_str("RAY_TPU_PAGED_ATTN_IMPL") != "gather":
        # pure prefill (all sequences start empty): no prior context to
        # gather — attend directly over the new tokens via the model's
        # configured attention impl (flash-eligible for long prompts on
        # TPU). Padding-tail keys only influence discarded query
        # outputs (causal mask), same as the gather path's semantics.
        # RAY_TPU_PAGED_ATTN_IMPL=gather forces the pool-gather
        # reference path here too (A/B-debugging contract).
        out = multi_head_attention(q, k.astype(q.dtype),
                                   v.astype(q.dtype), causal=True,
                                   impl=impl, scale=scale)
        return out, PagedKV(k_flat, v_flat, page_table, new_lengths,
                            page_size)

    # Single-token decode fast path: the Pallas kernel reads pages
    # DIRECTLY via scalar-prefetched page tables — no (B, L, Hkv, D)
    # contiguous gather temp, and work scales with real sequence
    # lengths. RAY_TPU_PAGED_ATTN_IMPL: auto|gather|pallas.
    impl = knobs.get_str("RAY_TPU_PAGED_ATTN_IMPL")
    if s == 1 and impl != "gather":
        on_tpu = jax.default_backend() == "tpu"
        if impl == "pallas" or on_tpu:
            from .pallas.paged_attention import (  # noqa: PLC0415
                paged_decode_attention, paged_decode_lowers)
            if impl == "pallas" or paged_decode_lowers(
                    q[:, 0], k_flat, page_table, page_size):
                out = paged_decode_attention(
                    q[:, 0], k_flat, v_flat, page_table, new_lengths,
                    page_size, qpos=positions[:, 0], scale=scale,
                    interpret=not on_tpu)
                return out[:, None], PagedKV(
                    k_flat, v_flat, page_table, new_lengths, page_size)

    # gather each sequence's contiguous KV view from its pages
    gather_idx = (page_table[:, :, None] * page_size
                  + jnp.arange(page_size)[None, None, :]
                  ).reshape(b, L)                             # (B, L)
    ck = k_flat[gather_idx]                                   # (B,L,Hkv,D)
    cv = v_flat[gather_idx]
    out = _attend_cached(q, ck, cv, positions, new_lengths, scale)
    return out, PagedKV(k_flat, v_flat, page_table, new_lengths,
                        page_size)


def multi_head_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                         *, causal: bool = True,
                         segment_ids: Optional[jax.Array] = None,
                         impl: str = "auto",
                         scale: Optional[float] = None) -> jax.Array:
    """q: (B, Sq, Hq, D); k/v: (B, Sk, Hkv, D) with Hq % Hkv == 0 (GQA).

    Returns (B, Sq, Hq, D).
    """
    explicit_pallas = impl == "pallas"
    impl = _resolve_impl(impl, q, k, causal, segment_ids)
    # Explicitly-requested pallas runs unconditionally (a lowering bug
    # must surface to the caller, not hide behind a silent fallback);
    # only the "auto" route degrades to XLA when the probe fails.
    if impl == "pallas" and (explicit_pallas
                             or pallas_flash_lowers(q, k, v, causal, scale)):
        from .pallas.flash_attention import flash_attention  # noqa: PLC0415
        return flash_attention(q, k, v, causal=causal, scale=scale)
    if impl == "dpa":
        # jax.nn.dot_product_attention: XLA's own fused attention,
        # which on TPU can lower to the compiler's flash kernel —
        # A/B against "xla" (hand einsum) + "pallas" via flash-ab.
        # Same no-silent-fallback rule as explicit pallas: unsupported
        # arguments must error, not contaminate A/B numbers.
        if segment_ids is not None or q.shape[1] != k.shape[1]:
            raise ValueError(
                "impl='dpa' supports only self-attention without "
                "segment_ids; use impl='xla' for packed/cached shapes")
        return jax.nn.dot_product_attention(
            q, k, v, is_causal=causal, scale=scale)

    b, sq, hq, d = q.shape
    _, sk, hkv, _ = k.shape
    if scale is None:
        scale = d ** -0.5
    if hq != hkv:
        # grouped-query: repeat kv heads
        rep = hq // hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)

    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    mask = None
    if causal:
        mask = causal_attention_mask(sq)[None, None, :, :]
        if sk != sq:  # decode with KV cache: offset the causal structure
            mask = jnp.tril(jnp.ones((sq, sk), dtype=jnp.bool_),
                            k=sk - sq)[None, None, :, :]
    if segment_ids is not None:
        seg_mask = (segment_ids[:, None, :, None]
                    == segment_ids[:, None, None, :])
        mask = seg_mask if mask is None else jnp.logical_and(mask, seg_mask)
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)
