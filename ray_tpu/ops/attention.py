"""Attention on TPU.

Default path: `jax.nn.dot_product_attention`, which XLA lowers to an MXU-
friendly fused kernel (and to TPU flash attention where supported). A Pallas
flash-attention kernel (ray_tpu/ops/pallas/flash_attention.py) can be
selected with impl="pallas" for long sequences.

Replaces the reference's torch scaled_dot_product_attention / flash-attn
dependency in its model code (e.g. rllib models and train examples).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def causal_attention_mask(seq_len: int, dtype=jnp.bool_) -> jax.Array:
    return jnp.tril(jnp.ones((seq_len, seq_len), dtype=dtype))


def _resolve_impl(impl: str, q: jax.Array, k: jax.Array, causal: bool,
                  segment_ids) -> str:
    """"auto" = the Pallas flash kernel on TPU whenever the shape suits it
    (self-attention, long enough to tile); XLA otherwise — notably cached
    decode (Sq != Sk under causal), segment masking, and CPU, where
    interpret-mode Pallas would crawl."""
    if impl != "auto":
        return impl
    if jax.default_backend() != "tpu":
        return "xla"
    if segment_ids is not None:
        return "xla"
    if causal and q.shape[1] != k.shape[1]:
        return "xla"
    if q.shape[1] < 128:
        return "xla"
    return "pallas"


def multi_head_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                         *, causal: bool = True,
                         segment_ids: Optional[jax.Array] = None,
                         impl: str = "auto",
                         scale: Optional[float] = None) -> jax.Array:
    """q: (B, Sq, Hq, D); k/v: (B, Sk, Hkv, D) with Hq % Hkv == 0 (GQA).

    Returns (B, Sq, Hq, D).
    """
    impl = _resolve_impl(impl, q, k, causal, segment_ids)
    if impl == "pallas":
        from .pallas.flash_attention import flash_attention  # noqa: PLC0415
        return flash_attention(q, k, v, causal=causal, scale=scale)

    b, sq, hq, d = q.shape
    _, sk, hkv, _ = k.shape
    if scale is None:
        scale = d ** -0.5
    if hq != hkv:
        # grouped-query: repeat kv heads
        rep = hq // hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)

    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    mask = None
    if causal:
        mask = causal_attention_mask(sq)[None, None, :, :]
        if sk != sq:  # decode with KV cache: offset the causal structure
            mask = jnp.tril(jnp.ones((sq, sk), dtype=jnp.bool_),
                            k=sk - sq)[None, None, :, :]
    if segment_ids is not None:
        seg_mask = (segment_ids[:, None, :, None]
                    == segment_ids[:, None, None, :])
        mask = seg_mask if mask is None else jnp.logical_and(mask, seg_mask)
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)
