"""Ring attention: sequence/context parallelism over the `sp` mesh axis.

Replaces the reference's context-parallel path (torch sequence parallelism /
ring-flash-attn integrations under python/ray/train) with a TPU-native
design: q/k/v are sharded over sequence on the `sp` axis; each device holds
one sequence chunk and the k/v chunks rotate around the ring with
`lax.ppermute` (nearest-neighbor ICI hops), while a running online-softmax
(m, l, acc) accumulates the attention output. After `sp` steps every q chunk
has attended over the full sequence without any device ever materializing
the (S, S) score matrix — HBM stays O(S/sp * S/sp) per step and the
ppermute overlaps with the per-chunk matmuls.

Causality is handled by global position masking, so chunk boundaries never
leak future tokens. GQA (n_kv_heads < n_heads) is supported by repeating kv
heads before the ring starts.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..util.jax_compat import shard_map as _shard_map

_NEG_BIG = -0.7 * float(jnp.finfo(jnp.float32).max)  # finite: avoids inf-inf


def _online_chunk(q, k, v, m, l, acc, q_offset, k_offset, scale, causal):
    """One block of online-softmax attention, grouped-query layout.

    q: (B, Sq, Hkv, R, D) local query chunk at global offset q_offset —
       R = Hq // Hkv query heads per kv head, so kv stays un-repeated
    k/v: (B, Sk, Hkv, D) visiting kv chunk at global offset k_offset
    m/l: (B, Hkv, R, Sq) running max / denominator;
    acc: (B, Sq, Hkv, R, D) running numerator. All fp32.
    """
    logits = jnp.einsum("bqhrd,bkhd->bhrqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        qpos = q_offset + jnp.arange(sq)[:, None]
        kpos = k_offset + jnp.arange(sk)[None, :]
        logits = jnp.where((qpos >= kpos)[None, None, None],
                           logits, _NEG_BIG)
    new_m = jnp.maximum(m, logits.max(axis=-1))
    correction = jnp.exp(m - new_m)
    p = jnp.exp(logits - new_m[..., None])          # (B,Hkv,R,Sq,Sk)
    new_l = l * correction + p.sum(axis=-1)
    pv = jnp.einsum("bhrqk,bkhd->bqhrd", p, v.astype(jnp.float32))
    new_acc = (acc * correction.transpose(0, 3, 1, 2)[..., None] + pv)
    return new_m, new_l, new_acc


def _ring_attention_local(q, k, v, *, axis_name: str, n_chunks: int,
                          causal: bool, scale: float):
    """Per-device body under shard_map. q: local (B, S/n, Hq, D);
    k/v: local (B, S/n, Hkv, D). kv rides the ring at Hkv width — GQA's
    bandwidth saving applies to the ppermute traffic too."""
    idx = jax.lax.axis_index(axis_name)
    b, sq, hq, d = q.shape
    hkv = k.shape[2]
    rep = hq // hkv
    q32 = q.astype(jnp.float32).reshape(b, sq, hkv, rep, d)
    m = jnp.full((b, hkv, rep, sq), _NEG_BIG, jnp.float32)
    l = jnp.zeros((b, hkv, rep, sq), jnp.float32)
    acc = jnp.zeros((b, sq, hkv, rep, d), jnp.float32)
    perm = [(i, (i + 1) % n_chunks) for i in range(n_chunks)]

    def body(s, carry):
        m, l, acc, k, v = carry
        # After s forward rotations device `idx` holds the chunk that
        # started on device (idx - s) % n.
        k_idx = (idx - s) % n_chunks
        m, l, acc = _online_chunk(
            q32, k.astype(jnp.float32), v.astype(jnp.float32), m, l, acc,
            q_offset=idx * sq, k_offset=k_idx * sq,
            scale=scale, causal=causal)
        k = jax.lax.ppermute(k, axis_name, perm)
        v = jax.lax.ppermute(v, axis_name, perm)
        return m, l, acc, k, v

    m, l, acc, _, _ = jax.lax.fori_loop(0, n_chunks, body,
                                        (m, l, acc, k, v))
    out = acc / l.transpose(0, 3, 1, 2)[..., None]
    return out.reshape(b, sq, hq, d).astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   mesh: Mesh, axis_name: str = "sp",
                   causal: bool = True,
                   scale: Optional[float] = None) -> jax.Array:
    """Context-parallel attention over `axis_name` of `mesh`.

    q: (B, S, Hq, D); k/v: (B, S, Hkv, D), Hq % Hkv == 0. The S dim is
    sharded over `axis_name` (S % axis_size == 0). Returns (B, S, Hq, D)
    with the same sequence sharding.
    """
    b, s, hq, d = q.shape
    hkv = k.shape[2]
    if hq % hkv:
        raise ValueError(f"n_heads {hq} % n_kv_heads {hkv} != 0")
    if scale is None:
        scale = d ** -0.5
    n = mesh.shape.get(axis_name, 1)
    if n == 1:
        # Degenerate ring == dense attention; reuse the canonical impl.
        from .attention import multi_head_attention  # noqa: PLC0415
        return multi_head_attention(q, k, v, causal=causal, scale=scale)
    if s % n:
        raise ValueError(f"seq len {s} not divisible by {axis_name}={n}")

    spec = P(None, axis_name, None, None)
    fn = _shard_map(
        functools.partial(_ring_attention_local, axis_name=axis_name,
                          n_chunks=n, causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)
