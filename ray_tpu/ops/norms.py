"""Normalization ops.

Accumulate statistics in fp32 regardless of input dtype (bf16-safe on the
VPU), then cast back — matching apex FusedRMSNorm semantics the reference's
torch models rely on.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jax.Array, weight: jax.Array, eps: float = 1e-6) -> jax.Array:
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    normed = xf * jax.lax.rsqrt(var + eps)
    return (normed * weight.astype(jnp.float32)).astype(dtype)


def layer_norm(x: jax.Array, weight: jax.Array, bias: jax.Array,
               eps: float = 1e-5) -> jax.Array:
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    normed = (xf - mean) * jax.lax.rsqrt(var + eps)
    out = normed * weight.astype(jnp.float32) + bias.astype(jnp.float32)
    return out.astype(dtype)
