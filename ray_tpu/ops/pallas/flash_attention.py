"""Flash attention for TPU in Pallas.

Online-softmax tiled attention: Q/K/V blocks stream HBM -> VMEM, logits
never materialize in HBM, accumulators live in VMEM scratch across the
innermost (k-block) grid dimension — the standard TPU flash schedule.

Forward is the Pallas kernel; backward currently recomputes through the
XLA attention path via jax.custom_vjp (correct gradients, HBM-heavier —
a Pallas backward is a later optimization). The kernel auto-runs in
interpret mode on CPU so tests exercise the same code path.

Replaces the reference's flash-attn/CUDA dependency (torch
scaled_dot_product_attention in its model stacks).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
                *, scale: float, causal: bool, block_q: int, block_k: int,
                seq_len: int):
    iq = pl.program_id(1)
    jk = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(jk == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_start = iq * block_q
    k_start = jk * block_k

    run = True
    if causal:
        # Skip blocks entirely in the future of this q block.
        run = k_start <= q_start + block_q - 1

    @pl.when(run)
    def _compute():
        q = q_ref[0]                      # (block_q, d)
        k = k_ref[0]                      # (block_k, d)
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (bq, bk)
        # causal + padding masks
        q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32,
                                                   (block_q, block_k), 0)
        k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32,
                                                   (block_q, block_k), 1)
        mask = k_pos < seq_len
        if causal:
            mask = jnp.logical_and(mask, k_pos <= q_pos)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[:, :1]                               # (bq, 1)
        m_cur = jnp.max(s, axis=1, keepdims=True)           # (bq, 1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                              # (bq, bk)
        correction = jnp.exp(m_prev - m_new)                # (bq, 1)
        l_new = correction * l_ref[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * correction + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(jk == nk - 1)
    def _finalize():
        l = l_ref[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)


def _flash_fwd(q, k, v, scale: float, causal: bool,
               block_q: int, block_k: int, interpret: bool):
    """q,k,v: (BH, S, D) with identical head counts (GQA pre-expanded)."""
    bh, s, d = q.shape
    sk = k.shape[1]
    bq = min(block_q, s)
    bk = min(block_k, sk)
    nq = pl.cdiv(s, bq)
    nk = pl.cdiv(sk, bk)
    # pad sequence dims to block multiples
    s_pad, sk_pad = nq * bq, nk * bk
    if s_pad != s:
        q = jnp.pad(q, ((0, 0), (0, s_pad - s), (0, 0)))
    if sk_pad != sk:
        k = jnp.pad(k, ((0, 0), (0, sk_pad - sk), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, sk_pad - sk), (0, 0)))

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=bq, block_k=bk,
        seq_len=sk)
    out = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s_pad, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v)
    return out[:, :s, :]


def _xla_reference(q, k, v, scale, causal):
    s = jnp.einsum("bqd,bkd->bqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((sq, sk), dtype=jnp.bool_), k=sk - sq)
        s = jnp.where(mask[None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bqk,bkd->bqd", p, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, scale, causal, block_q, block_k, interpret):
    return _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret)


def _flash_vjp_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    out = _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret)
    return out, (q, k, v)


def _flash_vjp_bwd(scale, causal, block_q, block_k, interpret, res, g):
    q, k, v = res
    # Correct-by-construction backward via the XLA path (recompute).
    _, vjp = jax.vjp(lambda q, k, v: _xla_reference(q, k, v, scale, causal),
                     q, k, v)
    return vjp(g.astype(jnp.float32))


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, *, causal: bool = True,
                    scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: Optional[bool] = None):
    """q: (B, Sq, Hq, D); k/v: (B, Sk, Hkv, D). Returns (B, Sq, Hq, D).

    GQA is handled by expanding kv heads before the kernel (the extra HBM
    reads are amortized by the block streaming).
    """
    b, sq, hq, d = q.shape
    _, sk, hkv, _ = k.shape
    if scale is None:
        scale = d ** -0.5
    if hq != hkv:
        rep = hq // hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"

    def flat(x):
        return x.transpose(0, 2, 1, 3).reshape(b * hq, x.shape[1], d)

    out = _flash(flat(q), flat(k), flat(v), float(scale), bool(causal),
                 int(block_q), int(block_k), bool(interpret))
    return out.reshape(b, hq, sq, d).transpose(0, 2, 1, 3)
