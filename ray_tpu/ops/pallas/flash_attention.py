"""Flash attention for TPU in Pallas — forward AND backward kernels.

Online-softmax tiled attention: Q/K/V blocks stream HBM -> VMEM, logits
never materialize in HBM, accumulators live in VMEM scratch across the
innermost grid dimension — the standard TPU flash schedule.

Forward emits the per-row logsumexp; backward is two Pallas kernels
(FlashAttention-2 style): a dQ kernel accumulating over key blocks and a
dK/dV kernel accumulating over query blocks, with
delta = rowsum(dO * O) precomputed in XLA. Logits are rebuilt in VMEM
from the saved logsumexp, so the backward is O(S) HBM like the forward.
The kernels auto-run in interpret mode on CPU so tests exercise the same
code path.

Replaces the reference's flash-attn/CUDA dependency (torch
scaled_dot_product_attention in its model stacks, e.g.
python/ray/train/torch/train_loop_utils.py models).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...util import knobs
from ...util.jax_compat import pallas_tpu_compiler_params \
    as _CompilerParams

NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, scale: float, causal: bool, block_q: int, block_k: int,
                seq_len: int):
    iq = pl.program_id(1)
    jk = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(jk == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_start = iq * block_q
    k_start = jk * block_k

    run = True
    if causal:
        # Skip blocks entirely in the future of this q block.
        run = k_start <= q_start + block_q - 1

    @pl.when(run)
    def _compute():
        q = q_ref[0]                      # (block_q, d)
        k = k_ref[0]                      # (block_k, d)
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (bq, bk)
        # causal + padding masks
        q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32,
                                                   (block_q, block_k), 0)
        k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32,
                                                   (block_q, block_k), 1)
        mask = k_pos < seq_len
        if causal:
            mask = jnp.logical_and(mask, k_pos <= q_pos)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[:, :1]                               # (bq, 1)
        m_cur = jnp.max(s, axis=1, keepdims=True)           # (bq, 1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                              # (bq, bk)
        correction = jnp.exp(m_prev - m_new)                # (bq, 1)
        l_new = correction * l_ref[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * correction + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(jk == nk - 1)
    def _finalize():
        l = l_ref[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)
        lse_ref[0, 0] = (m_ref[:, 0] + jnp.log(safe_l[:, 0]))


def _flash_fwd(q, k, v, scale: float, causal: bool,
               block_q: int, block_k: int, interpret: bool):
    """q,k,v: (BH, S, D) with identical head counts (GQA pre-expanded).
    Returns (out (BH, S, D), lse (BH, S) fp32)."""
    bh, s, d = q.shape
    sk = k.shape[1]
    bq = min(block_q, s)
    bk = min(block_k, sk)
    nq = pl.cdiv(s, bq)
    nk = pl.cdiv(sk, bk)
    # pad sequence dims to block multiples
    s_pad, sk_pad = nq * bq, nk * bk
    if s_pad != s:
        q = jnp.pad(q, ((0, 0), (0, s_pad - s), (0, 0)))
    if sk_pad != sk:
        k = jnp.pad(k, ((0, 0), (0, sk_pad - sk), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, sk_pad - sk), (0, 0)))

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=bq, block_k=bk,
        seq_len=sk)
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            # lse rides in a (bh, 1, s_pad) layout: the block's second-minor
            # dim (1) then equals the full array dim, which Mosaic's
            # (8, 128) tiling rule permits — a 2-D (bh, s_pad) array with a
            # (1, bq) block does NOT lower on real TPU (sublane dim 1 is
            # neither a multiple of 8 nor the array dim).
            pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s_pad, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, s_pad), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v)
    return out[:, :s, :], lse[:, 0, :s]


def _bwd_p_ds(q, k, v, do, lse, delta, q_start, k_start, *, scale,
              causal, sq, sk, block_q, block_k):
    """Shared VMEM math for both backward kernels: rebuild the normalized
    probabilities p from the saved logsumexp and form
    ds = p * (dO V^T - delta) * scale. Returns (p, ds) in fp32."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale          # (bq, bk)
    q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32,
                                               (block_q, block_k), 0)
    k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32,
                                               (block_q, block_k), 1)
    mask = jnp.logical_and(k_pos < sk, q_pos < sq)
    if causal:
        mask = jnp.logical_and(mask, k_pos <= q_pos)
    # p = exp(s - lse): already normalized. Padded/fully-masked rows have
    # lse == 0 from re-padding; their dO rows are 0 so contributions die,
    # but mask them anyway so no inf/nan can form.
    p = jnp.where(mask, jnp.exp(s - lse[:, None]), 0.0)      # (bq, bk)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)                  # (bq, bk)
    ds = p * (dp - delta[:, None]) * scale
    return p, ds


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_acc, *, scale, causal, block_q, block_k,
                   sq, sk):
    iq = pl.program_id(1)
    jk = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(jk == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    q_start = iq * block_q
    k_start = jk * block_k
    run = True
    if causal:
        run = k_start <= q_start + block_q - 1

    @pl.when(run)
    def _compute():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        _, ds = _bwd_p_ds(q, k, v, do, lse_ref[0, 0], delta_ref[0, 0],
                          q_start, k_start, scale=scale, causal=causal,
                          sq=sq, sk=sk, block_q=block_q, block_k=block_k)
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(jk == nk - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                    block_q, block_k, sq, sk):
    ik = pl.program_id(1)
    jq = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(jq == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    k_start = ik * block_k
    q_start = jq * block_q
    run = True
    if causal:
        # Skip q blocks entirely before this k block (they can't see it).
        run = q_start + block_q - 1 >= k_start

    @pl.when(run)
    def _compute():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        p, ds = _bwd_p_ds(q, k, v, do, lse_ref[0, 0], delta_ref[0, 0],
                          q_start, k_start, scale=scale, causal=causal,
                          sq=sq, sk=sk, block_q=block_q, block_k=block_k)
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # (bk, d)
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # (bk, d)

    @pl.when(jq == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, out, lse, do, scale, causal,
               block_q, block_k, interpret):
    """Pallas backward. q/out/do: (BH, S, D); k/v: (BH, Sk, D)."""
    bh, s, d = q.shape
    sk = k.shape[1]
    bq = min(block_q, s)
    bk = min(block_k, sk)
    nq = pl.cdiv(s, bq)
    nk = pl.cdiv(sk, bk)
    s_pad, sk_pad = nq * bq, nk * bk

    # delta_i = sum_j dO_ij * O_ij  (fp32, one cheap XLA pass)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)                                  # (BH, S)
    if s_pad != s:
        pad = ((0, 0), (0, s_pad - s), (0, 0))
        q, do = jnp.pad(q, pad), jnp.pad(do, pad)
        lse = jnp.pad(lse, ((0, 0), (0, s_pad - s)))
        delta = jnp.pad(delta, ((0, 0), (0, s_pad - s)))
    if sk_pad != sk:
        pad = ((0, 0), (0, sk_pad - sk), (0, 0))
        k, v = jnp.pad(k, pad), jnp.pad(v, pad)
    # Per-row tensors travel as (BH, 1, S): see the fwd lse out_spec for why
    # a 2-D (BH, S) layout cannot tile on real TPU.
    lse = lse.reshape(bh, 1, s_pad)
    delta = delta.reshape(bh, 1, s_pad)

    common = dict(scale=scale, causal=causal, block_q=bq, block_k=bk,
                  sq=s, sk=sk)
    qspec = pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0))
    kspec = pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0))
    rowspec = pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, i))
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **common),
        grid=(bh, nq, nk),
        in_specs=[qspec, kspec, kspec, qspec, rowspec, rowspec],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s_pad, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    # dk/dv: swap loop order — k blocks in the grid, q blocks innermost.
    qspec2 = pl.BlockSpec((1, bq, d), lambda b, i, j: (b, j, 0))
    kspec2 = pl.BlockSpec((1, bk, d), lambda b, i, j: (b, i, 0))
    rowspec2 = pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, j))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, **common),
        grid=(bh, nk, nq),
        in_specs=[qspec2, kspec2, kspec2, qspec2, rowspec2, rowspec2],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk_pad, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk_pad, d), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq[:, :s, :], dk[:, :sk, :], dv[:, :sk, :]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, scale, causal, block_q, block_k, interpret):
    out, _ = _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret)
    return out


def _flash_vjp_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    out, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k,
                          interpret)
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(scale, causal, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    return _flash_bwd(q, k, v, out, lse, g.astype(q.dtype), scale, causal,
                      block_q, block_k, interpret)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, *, causal: bool = True,
                    scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None):
    """q: (B, Sq, Hq, D); k/v: (B, Sk, Hkv, D). Returns (B, Sq, Hq, D).

    GQA is handled by expanding kv heads before the kernel (the extra HBM
    reads are amortized by the block streaming).

    Block sizes default to 128x128; RAY_TPU_FLASH_BLOCK_Q/K override for
    on-chip tuning sweeps (bench.py --phase flash-ab).
    """
    if block_q is None:
        block_q = knobs.get_int("RAY_TPU_FLASH_BLOCK_Q")
    if block_k is None:
        block_k = knobs.get_int("RAY_TPU_FLASH_BLOCK_K")
    b, sq, hq, d = q.shape
    _, sk, hkv, _ = k.shape
    if scale is None:
        scale = d ** -0.5
    if hq != hkv:
        rep = hq // hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"

    def flat(x):
        return x.transpose(0, 2, 1, 3).reshape(b * hq, x.shape[1], d)

    out = _flash(flat(q), flat(k), flat(v), float(scale), bool(causal),
                 int(block_q), int(block_k), bool(interpret))
    return out.reshape(b, hq, sq, d).transpose(0, 2, 1, 3)
