"""Pallas TPU kernels — the hand-written hot ops (SURVEY.md §2.2 P9)."""
from .flash_attention import flash_attention
from .rmsnorm import fused_rms_norm

__all__ = ["flash_attention", "fused_rms_norm"]
