"""Fused RMSNorm for TPU in Pallas.

One HBM pass: each row tile streams into VMEM once, the fp32 mean-square
reduction, rsqrt, and weight multiply all fuse in-kernel, and the result
streams back in the input dtype — apex-FusedRMSNorm semantics (the
reference stacks use apex/torch fused norms; SURVEY.md §2.2 P9).

Forward is the Pallas kernel; backward goes through the XLA math of
ops.norms.rms_norm via jax.custom_vjp (same pattern as
pallas/flash_attention.py: correct grads now, Pallas backward as a later
optimization). Auto-interprets on CPU so tests run the same code path.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..norms import rms_norm as _xla_rms_norm


def _rmsnorm_kernel(x_ref, w_ref, o_ref, *, eps: float):
    x = x_ref[...].astype(jnp.float32)              # (block_rows, d)
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    y = x * jax.lax.rsqrt(ms + eps)
    o_ref[...] = (y * w_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


def _fwd(x2d, weight, eps: float, block_rows: int, interpret: bool):
    rows, d = x2d.shape
    padded = pl.cdiv(rows, block_rows) * block_rows
    if padded != rows:
        x2d = jnp.pad(x2d, ((0, padded - rows), (0, 0)))
    out = pl.pallas_call(
        functools.partial(_rmsnorm_kernel, eps=eps),
        grid=(padded // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            # rank-2 weight tile: Mosaic wants (sublane, lane)-tileable
            # operands; a rank-1 ref lowers poorly on real TPU
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((padded, d), x2d.dtype),
        interpret=interpret,
    )(x2d, weight.reshape(1, d))
    return out[:rows]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _rmsnorm(x2d, weight, eps, block_rows, interpret):
    return _fwd(x2d, weight, eps, block_rows, interpret)


def _rmsnorm_vjp_fwd(x2d, weight, eps, block_rows, interpret):
    return _fwd(x2d, weight, eps, block_rows, interpret), (x2d, weight)


def _rmsnorm_vjp_bwd(eps, block_rows, interpret, res, g):
    x2d, weight = res
    _, vjp = jax.vjp(lambda x, w: _xla_rms_norm(x, w, eps), x2d, weight)
    return vjp(g)


_rmsnorm.defvjp(_rmsnorm_vjp_fwd, _rmsnorm_vjp_bwd)


def fused_rms_norm(x: jax.Array, weight: jax.Array, eps: float = 1e-6,
                   *, block_rows: Optional[int] = None,
                   interpret: Optional[bool] = None) -> jax.Array:
    """Drop-in for ops.norms.rms_norm with a fused Pallas forward.

    x: (..., d); weight: (d,). Any leading shape — rows are flattened
    into the kernel grid.
    """
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    d = x.shape[-1]
    lead = x.shape[:-1]
    x2d = x.reshape(-1, d)
    if block_rows is None:
        # keep the fp32 tile well under VMEM (rows*d*4B <= ~2MB) and
        # never pad a small input up to a much bigger tile
        block_rows = max(8, min(256, (2 << 20) // max(d * 4, 1),
                                x2d.shape[0]))
    # Mosaic fp32 tiles are (8, 128): a block_rows that isn't a multiple
    # of 8 fails to lower on real TPU (grid already pads rows, so
    # rounding up is free).
    block_rows = -(-int(block_rows) // 8) * 8
    out = _rmsnorm(x2d, weight, eps, int(block_rows), bool(interpret))
    return out.reshape(*lead, d)
