"""Pallas TPU kernel: single-token decode attention over a paged KV pool.

The serve engine's paged cache (ops/attention.py:PagedKV) stores KV as
flat token rows in a shared pool with per-sequence page tables. The
XLA fallback path gathers each sequence's pages into a contiguous
(S, L, Hkv, D) view per layer per decode step — correct, but it
materializes L*page_size rows of temp HBM traffic per layer even when
sequences are short. This kernel reads the pages DIRECTLY:

  * the page table and lengths ride in SMEM via scalar prefetch
    (pltpu.PrefetchScalarGridSpec), so each (sequence, page) grid step's
    BlockSpec index_map picks the physical page — the indirection costs
    an SMEM read, not an HBM gather;
  * grid (S, P) accumulates flash-style (online softmax) across the
    page dimension; pages past the sequence length are skipped whole
    (pl.when), so work scales with the ACTUAL tokens, not the max;
  * GQA is handled in-kernel (q reshaped to (Hkv, rep, D)) — the pool
    is never head-expanded.

Decode is inference-only: no backward pass is defined (the training
path never runs paged attention).

Same vLLM-PagedAttention capability as the reference's GPU serving
path, re-designed for Mosaic's tiling rules (blocks keep the pool's
(page_size, Hkv, D) layout; the second-minor block dim equals the full
array dim, which the (8, 128) tiling rule permits).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...util.jax_compat import pallas_tpu_compiler_params \
    as _CompilerParams

NEG_INF = -1e30

# signature -> bool compile-probe cache (mirrors flash_attention's
# pallas_flash_lowers: Mosaic failures degrade to the gather path)
_LOWER_CACHE: dict = {}


def _decode_kernel(pt_ref, len_ref, qpos_ref, q_ref, k_ref, v_ref,
                   o_ref, acc_ref, m_ref, l_ref, *,
                   scale: float, page_size: int, n_kv: int, rep: int):
    s = pl.program_id(0)
    p = pl.program_id(1)
    np_ = pl.num_programs(1)

    @pl.when(p == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # causal bound: keys at positions <= the query's own position AND
    # < the sequence length — identical masking to _attend_cached, so
    # a replay query at an EARLIER position (positions < lengths-1,
    # e.g. speculative-decode verification) can't see future keys
    seq_len = jnp.minimum(len_ref[s], qpos_ref[s] + 1)
    run = p * page_size < seq_len

    @pl.when(run)
    def _compute():
        q = q_ref[0]                       # (Hq, D)
        k = k_ref[0]                       # (ps, Hkv, D)
        v = v_ref[0]
        hq, d = q.shape
        qg = q.reshape(n_kv, rep, d)
        # per-kv-head scores: (rep, ps) each; stacked -> (Hq, ps)
        parts = []
        for h in range(n_kv):
            sh = jax.lax.dot_general(
                qg[h], k[:, h, :], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            parts.append(sh)               # (rep, ps)
        scores = jnp.concatenate(parts, axis=0)        # (Hq, ps)
        pos = p * page_size + jax.lax.broadcasted_iota(
            jnp.int32, scores.shape, 1)
        scores = jnp.where(pos < seq_len, scores, NEG_INF)

        m_prev = m_ref[:, :1]                           # (Hq, 1)
        m_cur = jnp.max(scores, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        pexp = jnp.exp(scores - m_new)                  # (Hq, ps)
        corr = jnp.exp(m_prev - m_new)
        l_ref[:] = jnp.broadcast_to(
            corr * l_ref[:, :1]
            + jnp.sum(pexp, axis=1, keepdims=True), l_ref.shape)
        pv_parts = []
        pg = pexp.reshape(n_kv, rep, page_size)
        for h in range(n_kv):
            pv = jax.lax.dot_general(
                pg[h].astype(v.dtype), v[:, h, :],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)     # (rep, D)
            pv_parts.append(pv)
        acc_ref[:] = (acc_ref[:] * corr
                      + jnp.concatenate(pv_parts, axis=0))
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    @pl.when(p == np_ - 1)
    def _finalize():
        l = l_ref[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)


def paged_decode_attention(q, k_flat, v_flat, page_table, lengths,
                           page_size: int,
                           qpos=None,
                           scale: "float | None" = None,
                           interpret: bool = False):
    """q: (S, Hq, D) one decode token per sequence (cache already holds
    its KV); k_flat/v_flat: (N_flat, Hkv, D) page pools; page_table:
    (S, P) int32; lengths: (S,) int32 — keys valid at positions
    < lengths. qpos: (S,) int32 query positions (causal bound: keys at
    positions <= qpos attend; default lengths-1, the decode-at-end
    case). Returns (S, Hq, D)."""
    s_n, hq, d = q.shape
    n_flat, hkv, _ = k_flat.shape
    assert n_flat % page_size == 0, (n_flat, page_size)
    rep = hq // hkv
    if scale is None:
        scale = d ** -0.5
    n_pages = n_flat // page_size
    kp = k_flat.reshape(n_pages, page_size, hkv, d)
    vp = v_flat.reshape(n_pages, page_size, hkv, d)
    P = page_table.shape[1]
    if qpos is None:
        qpos = lengths - 1

    kernel = functools.partial(
        _decode_kernel, scale=scale, page_size=page_size,
        n_kv=hkv, rep=rep)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,             # page_table, lengths, qpos
        grid=(s_n, P),
        in_specs=[
            pl.BlockSpec((1, hq, d),
                         lambda s, p, pt, ln, qp: (s, 0, 0)),
            pl.BlockSpec((1, page_size, hkv, d),
                         lambda s, p, pt, ln, qp: (pt[s, p], 0, 0, 0)),
            pl.BlockSpec((1, page_size, hkv, d),
                         lambda s, p, pt, ln, qp: (pt[s, p], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, hq, d),
                               lambda s, p, pt, ln, qp: (s, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((hq, d), jnp.float32),
            pltpu.VMEM((hq, 128), jnp.float32),
            pltpu.VMEM((hq, 128), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s_n, hq, d), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(page_table, lengths, jnp.asarray(qpos, jnp.int32), q, kp, vp)


def paged_decode_lowers(q, k_flat, page_table, page_size: int) -> bool:
    """Compile-probe the kernel once per shape signature; a Mosaic
    failure degrades the engine to the XLA gather path with a warning
    instead of killing the decode step (same contract as
    flash_attention.pallas_flash_lowers)."""
    key = (q.shape, str(q.dtype), k_flat.shape, str(k_flat.dtype),
           page_table.shape, page_size)
    hit = _LOWER_CACHE.get(key)
    if hit is not None:
        return hit
    if jax.default_backend() != "tpu":
        _LOWER_CACHE[key] = True
        return True
    import logging
    try:
        abstract = [
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct(k_flat.shape, k_flat.dtype),
            jax.ShapeDtypeStruct(k_flat.shape, k_flat.dtype),
            jax.ShapeDtypeStruct(page_table.shape, jnp.int32),
            jax.ShapeDtypeStruct((q.shape[0],), jnp.int32),
        ]
        jax.jit(functools.partial(
            paged_decode_attention, page_size=page_size)).lower(
            *abstract).compile()
        ok = True
    except Exception as exc:  # Mosaic/XLA lowering errors are varied
        logging.getLogger("ray_tpu.ops.pallas.paged").warning(
            "paged decode kernel failed to lower for q=%s pool=%s "
            "(%s: %s); using the XLA gather path.",
            q.shape, k_flat.shape, type(exc).__name__, exc)
        ok = False
    _LOWER_CACHE[key] = ok
    return ok
