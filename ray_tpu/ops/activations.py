"""Gated activations (fused by XLA into the surrounding matmuls)."""
from __future__ import annotations

import jax
import jax.numpy as jnp


def swiglu(gate: jax.Array, up: jax.Array) -> jax.Array:
    return jax.nn.silu(gate) * up


def geglu(gate: jax.Array, up: jax.Array) -> jax.Array:
    return jax.nn.gelu(gate) * up
