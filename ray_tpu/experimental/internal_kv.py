"""Cluster-wide internal key-value store.

Reference parity: python/ray/experimental/internal_kv.py (the GCS KV
used by runtime_env, serve, jobs...). Keys/values are bytes; optional
namespace isolates users. Works from the driver (directly against the
GCS table) and from workers (a sys.kv report_sync round-trip).
"""
from __future__ import annotations

from typing import List, Optional, Union

from ..core import runtime as runtime_mod


def _key(ns: Optional[Union[str, bytes]],
         key: Union[str, bytes]) -> str:
    if isinstance(key, bytes):
        key = key.decode()
    if ns:
        if isinstance(ns, bytes):
            ns = ns.decode()
        return f"{ns}\x00{key}"
    return f"\x00{key}"


def _as_bytes(v: Union[str, bytes]) -> bytes:
    return v.encode() if isinstance(v, str) else bytes(v)


def _call(op: str, *args):
    rt = runtime_mod.get_runtime()
    if rt.is_driver:
        return rt._kv_op(op, *args)
    return rt.report_sync("sys.kv", (op, *args), timeout=10.0)


def _internal_kv_initialized() -> bool:
    return runtime_mod.runtime_initialized()


def _internal_kv_put(key, value, overwrite: bool = True,
                     namespace=None) -> bool:
    """Returns True iff the key already existed."""
    return _call("put", _key(namespace, key), _as_bytes(value), overwrite)


def _internal_kv_get(key, namespace=None) -> Optional[bytes]:
    return _call("get", _key(namespace, key))


def _internal_kv_exists(key, namespace=None) -> bool:
    return _call("exists", _key(namespace, key))


def _internal_kv_del(key, del_by_prefix: bool = False,
                     namespace=None) -> int:
    return _call("del", _key(namespace, key), del_by_prefix)


def _internal_kv_list(prefix, namespace=None) -> List[bytes]:
    return _call("list", _key(namespace, prefix))


# public aliases (the reference keeps the underscore names; both work)
kv_put = _internal_kv_put
kv_get = _internal_kv_get
kv_del = _internal_kv_del
kv_list = _internal_kv_list
kv_exists = _internal_kv_exists
