"""Experimental APIs (reference: python/ray/experimental)."""
from . import internal_kv  # noqa: F401
from . import tqdm_ray     # noqa: F401

__all__ = ["internal_kv", "tqdm_ray"]
