"""Distributed-safe progress bars (reference parity:
python/ray/experimental/tqdm_ray.py).

Workers' stdout is captured and line-streamed to the driver, so real
tqdm's in-place carriage returns turn into log spam. This shim batches
progress into rate-limited single lines that survive the worker->driver
log relay; API-compatible with the tqdm calls the libraries use
(update/set_description/close, iterable wrapping).
"""
from __future__ import annotations

import sys
import time
from typing import Iterable, Optional

_MIN_INTERVAL_S = 0.5


class tqdm:  # noqa: N801  (tqdm-compatible name)
    def __init__(self, iterable: Optional[Iterable] = None,
                 desc: str = "", total: Optional[int] = None,
                 unit: str = "it", **_ignored):
        self._iterable = iterable
        self.desc = desc
        self.total = total if total is not None else (
            len(iterable) if hasattr(iterable, "__len__") else None)
        self.unit = unit
        self.n = 0
        self._start = time.time()
        self._last_print = 0.0
        self._closed = False

    def __iter__(self):
        for x in self._iterable:
            yield x
            self.update(1)
        self.close()

    def update(self, n: int = 1) -> None:
        self.n += n
        now = time.time()
        if now - self._last_print >= _MIN_INTERVAL_S:
            self._last_print = now
            self._emit()

    def set_description(self, desc: str, refresh: bool = True) -> None:
        self.desc = desc
        if refresh:
            self._emit()

    def _emit(self) -> None:
        elapsed = max(time.time() - self._start, 1e-9)
        rate = self.n / elapsed
        frac = f"{self.n}/{self.total}" if self.total else str(self.n)
        pct = (f" {100.0 * self.n / self.total:.0f}%"
               if self.total else "")
        print(f"[{self.desc or 'progress'}] {frac}{pct} "
              f"({rate:.1f} {self.unit}/s)", file=sys.stderr, flush=True)

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._emit()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def safe_print(*args, **kwargs) -> None:
    """print() replacement that cooperates with the bars (parity shim —
    our bars are plain lines, so this is just print)."""
    print(*args, **kwargs)


__all__ = ["tqdm", "safe_print"]
