"""Cluster wait graph: "why is nothing happening right now".

The wait plane (util/waits.py) ships every park site's in-progress
waits to the driver; this module folds those records with the GCS
task/object/actor tables into a directed *waits-on* graph and walks it
for the three shapes of stuck:

  * **Deadlock** — a cycle. The canonical case: actor A's running
    method blocks on a call into actor B whose running method blocks
    on a call back into A. Edges close through the tables (task →
    object → producing task, actor-call → target actor → its running
    tasks), so the cycle is detected and NAMED even though no single
    process can see it.
  * **Stale wait** — a record older than `RAY_TPU_HANG_WARN_S` that is
    not part of a cycle. The chain walk follows waits-on edges to a
    terminal node — "task t parked on object o, produced by task p,
    which is EXECUTING on worker w" — so the report carries a live
    root cause, not just "something is slow".
  * **Straggler** — a collective round where some ranks have been
    parked (contributed, polling) far longer than the round should
    take while other ranks are absent: the missing ranks are still
    computing, frozen, or dead, and they are named. A SIGSTOP'd rank
    ships nothing, so detection works from the *siblings'* records.

`HangMonitor.probe()` runs the walk; the driver calls it from a
watchdog thread every `RAY_TPU_HANG_PROBE_S` and it emits
`sched.deadlock.detected` / `sched.hang.suspected` /
`sched.hang.resolved` plus `ray_tpu_hangs_detected_total{kind}`.
Every emission is once-per-incident (fingerprinted), and a suspected
hang auto-writes a forensics post-mortem for its subject so the
evidence survives the eventual mitigation.

Graph nodes are string keys: `task:<id>`, `actor:<id>`, `object:<id>`,
`collective:<rid>`, `channel:<id>`, `lease:<lid>@<node>`,
`grant:<job>`, `worker:<wid>`, `driver`.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Set, Tuple

from ..util import knobs

__all__ = ["WaitGraph", "HangMonitor", "build_graph", "gather_records"]

# A chain walk stops after this many hops — wait chains are short in
# practice; anything longer is a cycle the SCC pass already found.
MAX_CHAIN_HOPS = 16

# The data-service producer pool's actor-name prefix (data/service.py
# _WORKER_NAME_FMT): stale data-grant waits chain to these actors.
_DATA_WORKER_PREFIX = "_rtpu_data_worker_"


def gather_records(rt) -> List[Dict[str, Any]]:
    """Every known wait record: remote snapshots from ClusterWaitStore
    plus the driver's own local table (stamped like a shipped source
    would be)."""
    from ..util import waits as waits_mod
    recs = rt.cluster_waits.snapshot()
    for r in waits_mod.snapshot():
        r.setdefault("worker_id", "driver")
        r.setdefault("node_id", rt.node_id)
        recs.append(r)
    return recs


class WaitGraph:
    """The folded waits-on digraph plus per-record chain context."""

    def __init__(self) -> None:
        self.records: List[Dict[str, Any]] = []
        self.nodes: Dict[str, Dict[str, Any]] = {}
        self.edges: List[Tuple[str, str, str]] = []   # (src, dst, why)
        self.adj: Dict[str, List[str]] = {}
        # record index -> its waiter node key (chain walk entry point)
        self.waiter_of: Dict[int, str] = {}

    # ---- construction ------------------------------------------------------
    def _node(self, key: str, **attrs: Any) -> str:
        n = self.nodes.get(key)
        if n is None:
            n = self.nodes[key] = {"key": key}
        for k, v in attrs.items():
            if v is not None:
                n.setdefault(k, v)
        return key

    def _edge(self, src: str, dst: str, why: str) -> None:
        if src == dst:
            return
        lst = self.adj.setdefault(src, [])
        if dst not in lst:
            lst.append(dst)
            self.edges.append((src, dst, why))

    def label(self, key: str) -> str:
        """Human line for a node: `task:abc (foo, RUNNING on w3)`."""
        n = self.nodes.get(key, {})
        bits = [str(v) for v in (n.get("name"), n.get("state")) if v]
        if n.get("worker_id"):
            bits.append(f"on {n['worker_id']}")
        return f"{key} ({', '.join(bits)})" if bits else key

    # ---- analysis ----------------------------------------------------------
    def cycles(self) -> List[List[str]]:
        """Strongly-connected components with >1 node (iterative
        Tarjan — the graph is small but recursion depth is not ours to
        gamble with)."""
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: Set[str] = set()
        stack: List[str] = []
        out: List[List[str]] = []
        counter = [0]

        for root in list(self.nodes):
            if root in index:
                continue
            work: List[Tuple[str, int]] = [(root, 0)]
            while work:
                v, pi = work[-1]
                if pi == 0:
                    index[v] = low[v] = counter[0]
                    counter[0] += 1
                    stack.append(v)
                    on_stack.add(v)
                nbrs = self.adj.get(v, [])
                advanced = False
                while pi < len(nbrs):
                    w = nbrs[pi]
                    pi += 1
                    work[-1] = (v, pi)
                    if w not in index:
                        work.append((w, 0))
                        advanced = True
                        break
                    if w in on_stack:
                        low[v] = min(low[v], index[w])
                if advanced:
                    continue
                work.pop()
                if low[v] == index[v]:
                    scc = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        scc.append(w)
                        if w == v:
                            break
                    if len(scc) > 1:
                        out.append(sorted(scc))
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[v])
        return out

    def chain(self, rec_idx: int) -> List[str]:
        """Greedy waits-on walk from a record's waiter node: the
        first-listed edge at each hop, stopping on a terminal node, a
        revisit (cycle), or MAX_CHAIN_HOPS."""
        key = self.waiter_of.get(rec_idx)
        if key is None:
            return []
        seen = [key]
        cur = key
        for _ in range(MAX_CHAIN_HOPS):
            nxt = self.adj.get(cur, [])
            if not nxt:
                break
            cur = nxt[0]
            if cur in seen:
                seen.append(cur)   # show the back-edge, then stop
                break
            seen.append(cur)
        return seen

    def root_cause(self, rec_idx: int) -> str:
        ch = self.chain(rec_idx)
        if not ch:
            return "no wait chain"
        if len(ch) >= 2 and ch[-1] in ch[:-1]:
            return "cycle: " + " -> ".join(self.label(k) for k in ch)
        term = self.nodes.get(ch[-1], {})
        tail = self.label(ch[-1])
        if term.get("state") == "RUNNING":
            cause = f"{tail} is executing"
        elif ch[-1].startswith("collective:"):
            cause = f"{tail} round incomplete"
        else:
            cause = f"{tail} has no further waits-on edge"
        prefix = " -> ".join(self.label(k) for k in ch[:-1])
        return f"{prefix} -> {cause}" if prefix else cause

    def to_dict(self) -> Dict[str, Any]:
        return {
            "nodes": [dict(n) for n in self.nodes.values()],
            "edges": [{"src": s, "dst": d, "why": w}
                      for s, d, w in self.edges],
            "records": len(self.records),
        }


def build_graph(records: List[Dict[str, Any]], gcs,
                now: Optional[float] = None) -> WaitGraph:
    """Fold wait records + GCS tables into the waits-on digraph.

    Edge direction is "X cannot make progress until Y does":
      waiter task/driver -> resource -> responsible task/actor -> ...
    An actor node points at its RUNNING tasks (it cannot serve the
    waiter's call until they finish), which is the resource-allocation
    -graph approximation that closes call cycles.
    """
    now = time.time() if now is None else now
    g = WaitGraph()
    # point-in-time copies: the dispatcher thread mutates these dicts
    tasks = dict(gcs.tasks)
    objects = dict(gcs.objects)
    actors = dict(gcs.actors)

    def task_node(tid: str) -> str:
        te = tasks.get(tid)
        return g._node(f"task:{tid}",
                       name=te.name if te else None,
                       state=te.state if te else None,
                       worker_id=te.worker_id if te else None,
                       actor_id=te.actor_id if te else None)

    def actor_node(aid: str) -> str:
        ae = actors.get(aid)
        return g._node(f"actor:{aid}",
                       name=ae.class_name if ae else None,
                       state=ae.state if ae else None,
                       worker_id=ae.worker_id if ae else None)

    # An actor's worker runs only that actor's methods, so a parked
    # record from that worker IS the actor's current task even when
    # the task itself is invisible to the driver (direct calls).
    actor_on_worker: Dict[str, str] = {
        ae.worker_id: aid for aid, ae in actors.items()
        if ae.worker_id and ae.state != "DEAD"}

    # ---- pass 1: waiter -> resource edges ---------------------------------
    grant_jobs: Set[str] = set()
    for i, r in enumerate(records):
        g.records.append(r)
        kind, rid = r.get("kind", "other"), r.get("rid", "")
        ctx = r.get("ctx") or {}
        tid = r.get("task_id") or ctx.get("task")
        if tid:
            waiter = task_node(tid)
            te = tasks.get(tid)
            # the actor cannot serve other callers while this (running,
            # parked) task occupies it; for direct-call tasks the GCS
            # has no entry, so fall back to the record's worker
            aid = (te.actor_id if te is not None and te.actor_id
                   else actor_on_worker.get(r.get("worker_id", "")))
            if aid:
                g._edge(actor_node(aid), waiter, "running-task")
        elif r.get("worker_id") == "driver" or ctx.get("waiter") == "driver":
            waiter = g._node("driver")
        else:
            waiter = g._node(f"worker:{r.get('worker_id', '?')}")
        g.waiter_of[i] = waiter
        g.nodes[waiter].setdefault("parked_since", r.get("ts"))

        if kind == "object":
            res = g._node(f"object:{rid}")
            g._edge(waiter, res, "get")
        elif kind == "actor-call":
            target = ctx.get("target_actor")
            if not target:
                oe = objects.get(rid)
                if oe is not None and oe.owner_task:
                    te = tasks.get(oe.owner_task)
                    target = te.actor_id if te else None
            if target:
                res = actor_node(target)
            else:
                res = g._node(f"object:{rid}")
            g._edge(waiter, res, "call")
        elif kind == "collective-round":
            res = g._node(f"collective:{rid}",
                          group=ctx.get("group"), seq=ctx.get("seq"),
                          world=ctx.get("world"))
            g._edge(waiter, res, "round")
        elif kind == "dag-channel":
            res = g._node(f"channel:{rid}", op=ctx.get("op"))
            g._edge(waiter, res, ctx.get("op") or "dag")
        elif kind == "lease-slot":
            res = g._node(f"lease:{rid}@{r.get('node_id', '?')}",
                          queued=ctx.get("queued"))
            g._edge(waiter, res, "queue")
        elif kind == "data-grant":
            job = ctx.get("job") or rid
            res = g._node(f"grant:{job}")
            g._edge(waiter, res, "next_shard")
            grant_jobs.add(job)
        else:
            res = g._node(f"other:{rid}")
            g._edge(waiter, res, kind)

    # ---- pass 2: resource -> responsible-party edges ----------------------
    # a pending object is produced by its owner task; a queued (not
    # yet running) actor call waits on its target actor. Together with
    # the actor -> running-parked-task edges these close driver-path
    # call cycles the same way ctx.target_actor closes direct-call
    # ones: tA -> obj -> tB2(queued) -> actor:B -> tB -> obj' -> ...
    for key in list(g.nodes):
        if key.startswith("object:"):
            oid = key[len("object:"):]
            oe = objects.get(oid)
            if oe is not None and oe.state == "pending" and oe.owner_task:
                g._edge(key, task_node(oe.owner_task), "produced-by")
    for key in list(g.nodes):
        if key.startswith("task:"):
            te = tasks.get(key[len("task:"):])
            if te is not None and te.actor_id \
                    and te.state in ("PENDING", "SCHEDULED"):
                g._edge(key, actor_node(te.actor_id), "queued-on")
    # a starved data-service job waits on the producer pool
    if grant_jobs:
        for aid, ae in actors.items():
            if (ae.name or "").startswith(_DATA_WORKER_PREFIX) \
                    and ae.state != "DEAD":
                for job in grant_jobs:
                    g._edge(f"grant:{job}", actor_node(aid), "producer")
    # every actor anyone waits on cannot make progress until its
    # RUNNING tasks finish (parked ones continue the chain / close the
    # cycle; computing ones terminate it with a live "is executing"
    # root cause)
    running_by_actor: Dict[str, List[str]] = {}
    for tid, te in tasks.items():
        if te.state == "RUNNING" and te.actor_id:
            running_by_actor.setdefault(te.actor_id, []).append(tid)
    for akey in [k for k in g.nodes if k.startswith("actor:")]:
        for tid in running_by_actor.get(akey[len("actor:"):], []):
            g._edge(akey, task_node(tid), "running-task")
    return g


def detect_stragglers(records: List[Dict[str, Any]], now: float,
                      warn_s: float) -> List[Dict[str, Any]]:
    """Collective rounds where parked ranks have aged past `warn_s`
    while other ranks are absent (still computing / frozen / dead) or
    parked on an earlier round: name the laggards.

    Grouping key is (group, epoch, generation): ranks of the same
    group incarnation. Within it, ranks parked on the HIGHEST seq are
    up to date; everyone else — missing or parked behind — is a
    straggler candidate."""
    groups: Dict[Tuple, List[Dict[str, Any]]] = {}
    for r in records:
        if r.get("kind") != "collective-round":
            continue
        ctx = r.get("ctx") or {}
        key = (ctx.get("group"), ctx.get("epoch"), ctx.get("generation"))
        groups.setdefault(key, []).append(r)
    out: List[Dict[str, Any]] = []
    for (group, epoch, gen), recs in groups.items():
        oldest = min(r.get("ts", now) for r in recs)
        if now - oldest < warn_s:
            continue
        world = max(int((r.get("ctx") or {}).get("world") or 0)
                    for r in recs)
        seqs = {int((r.get("ctx") or {}).get("seq") or 0) for r in recs}
        head = max(seqs) if seqs else 0
        parked = {}
        for r in recs:
            rk = (r.get("ctx") or {}).get("rank")
            if rk is not None:
                parked[int(rk)] = r
        at_head = {rk for rk, r in parked.items()
                   if int((r.get("ctx") or {}).get("seq") or 0) == head}
        missing = [rk for rk in range(world) if rk not in parked]
        behind = sorted(set(parked) - at_head)
        if not missing and not behind:
            continue   # everyone parked on the same round: not a
            # straggler shape (could be a stale/deadlocked round)
        rounds = sorted({(r.get("ctx") or {}).get("round")
                         for r in recs if (r.get("ctx") or {}).get("round")})
        out.append({"group": group, "epoch": epoch, "generation": gen,
                    "world": world, "seq": head,
                    "round": rounds[0] if rounds else None,
                    "parked_ranks": sorted(at_head),
                    "behind_ranks": behind,
                    "missing_ranks": missing,
                    "stuck_s": round(now - oldest, 1)})
    return out


class HangMonitor:
    """Stateful watchdog: fingerprints incidents so each deadlock /
    suspected hang / straggler emits exactly once, and emits
    `sched.hang.resolved` when a previously-suspected wait drains."""

    def __init__(self, rt) -> None:
        self.rt = rt
        self._lock = threading.Lock()
        self._cycles_seen: Set[frozenset] = set()
        # incident key -> {"first": ts, "info": {...}} for resolution
        self._suspected: Dict[Any, Dict[str, Any]] = {}
        self._snapshots = 0
        self.max_snapshots = 8    # forensics bundles per driver life
        self.last_probe: Dict[str, Any] = {}

    # ---- helpers -----------------------------------------------------------
    def _emit(self, etype: str, msg: str, **fields: Any) -> None:
        try:
            from ..util import events as events_mod
            events_mod.emit_safe(etype, msg, **fields)
        except Exception:  # noqa: BLE001
            pass

    def _count(self, kind: str) -> None:
        try:
            from ..util import metrics_catalog as mcat
            mcat.get("ray_tpu_hangs_detected_total").inc(
                tags={"kind": kind})
        except Exception:  # noqa: BLE001
            pass

    def _forensics(self, subject_id: Optional[str]) -> None:
        """Best-effort post-mortem for a suspected hang's subject so
        the wait chain's evidence survives mitigation. Bounded: hangs
        can be recurrent, disks are not. Snapshots land in the temp
        dir, not the driver's cwd — an auto-writer must not litter."""
        if not subject_id or self._snapshots >= self.max_snapshots:
            return
        self._snapshots += 1
        try:
            import os  # noqa: PLC0415
            import tempfile  # noqa: PLC0415

            from . import forensics
            forensics.write_post_mortem(subject_id, os.path.join(
                tempfile.gettempdir(),
                f"rtpu-hang-{subject_id}.json"))
        except Exception:  # noqa: BLE001
            pass

    @staticmethod
    def _rec_key(r: Dict[str, Any]) -> Tuple:
        return (r.get("worker_id"), r.get("tok"),
                round(float(r.get("ts", 0.0)), 2))

    @staticmethod
    def _rec_subject(r: Dict[str, Any]) -> Optional[str]:
        return r.get("task_id") or (r.get("ctx") or {}).get("task")

    # ---- the probe ---------------------------------------------------------
    def probe(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One watchdog pass. Returns a summary (tests call this
        directly instead of waiting out the thread cadence)."""
        now = time.time() if now is None else now
        warn_s = knobs.get_float("RAY_TPU_HANG_WARN_S")
        records = gather_records(self.rt)
        g = build_graph(records, self.rt.gcs, now=now)
        summary: Dict[str, Any] = {"records": len(records),
                                   "deadlocks": [], "suspected": [],
                                   "stragglers": [], "resolved": []}

        # -- deadlocks: cycles in the waits-on graph ------------------------
        in_cycle: Set[str] = set()
        for scc in g.cycles():
            in_cycle.update(scc)
            fp = frozenset(scc)
            cyc = {"nodes": scc,
                   "edges": [{"src": s, "dst": d, "why": w}
                             for s, d, w in g.edges
                             if s in fp and d in fp],
                   "labels": [g.label(k) for k in scc]}
            summary["deadlocks"].append(cyc)
            with self._lock:
                new = fp not in self._cycles_seen
                if new:
                    self._cycles_seen.add(fp)
            if new:
                parts = ", ".join(cyc["labels"])
                self._emit(
                    "sched.deadlock.detected",
                    f"waits-on cycle among {len(scc)} nodes: {parts}",
                    kind="deadlock", nodes=scc, edges=cyc["edges"],
                    task_id=next((k.split(":", 1)[1] for k in scc
                                  if k.startswith("task:")), None),
                    actor_id=next((k.split(":", 1)[1] for k in scc
                                   if k.startswith("actor:")), None))
                self._count("deadlock")
                self._forensics(next(
                    (k.split(":", 1)[1] for k in scc
                     if k.startswith(("task:", "actor:"))), None))

        # -- stale waits: aged records outside any cycle --------------------
        live: Set[Any] = set()
        for i, r in enumerate(records):
            age = now - float(r.get("ts", now))
            if age < warn_s:
                continue
            key = self._rec_key(r)
            live.add(key)
            waiter = g.waiter_of.get(i, "?")
            if waiter in in_cycle:
                continue      # already reported as a deadlock
            cause = g.root_cause(i)
            info = {"kind": r.get("kind"), "rid": r.get("rid"),
                    "waiter": waiter, "worker_id": r.get("worker_id"),
                    "age_s": round(age, 1), "root_cause": cause}
            summary["suspected"].append(info)
            with self._lock:
                new = key not in self._suspected
                if new:
                    self._suspected[key] = {"first": now, "info": info,
                                            "ts": r.get("ts")}
            if new:
                self._emit(
                    "sched.hang.suspected",
                    f"{waiter} stuck {age:.0f}s on "
                    f"{r.get('kind')}:{r.get('rid')} — {cause}",
                    kind="stale", wait_kind=r.get("kind"),
                    rid=r.get("rid"), age_s=round(age, 1),
                    root_cause=cause,
                    task_id=self._rec_subject(r),
                    worker_id=r.get("worker_id"),
                    node_id=r.get("node_id"))
                self._count("stale")
                self._forensics(self._rec_subject(r))

        # -- resolved: previously-suspected waits that drained --------------
        with self._lock:
            gone = [k for k in self._suspected if k not in live]
            for k in gone:
                ent = self._suspected.pop(k)
                stuck = now - float(ent.get("ts") or ent["first"])
                info = ent["info"]
                summary["resolved"].append(info)
                self._emit(
                    "sched.hang.resolved",
                    f"{info['waiter']} unstuck after {stuck:.0f}s "
                    f"({info['kind']}:{info['rid']})",
                    kind=info.get("kind"), stuck_s=round(stuck, 1),
                    worker_id=info.get("worker_id"))

        # -- collective stragglers ------------------------------------------
        for s in detect_stragglers(records, now, warn_s):
            summary["stragglers"].append(s)
            skey = ("straggler", s["group"], s["epoch"],
                    s["generation"], s["seq"])
            with self._lock:
                new = skey not in self._suspected
                if new:
                    self._suspected[skey] = {
                        "first": now, "ts": now - s["stuck_s"],
                        "info": {"kind": "straggler",
                                 "rid": f"{s['group']}:{s['seq']}",
                                 "waiter": f"collective:{s['group']}",
                                 "worker_id": None}}
            if new:
                lag = s["missing_ranks"] + s["behind_ranks"]
                self._emit(
                    "sched.hang.suspected",
                    f"collective group {s['group']!r} round "
                    f"{s['round']} seq {s['seq']}: ranks "
                    f"{s['parked_ranks']} parked {s['stuck_s']}s "
                    f"waiting on ranks {lag} "
                    f"(missing={s['missing_ranks']}, "
                    f"behind={s['behind_ranks']})",
                    kind="straggler", group=s["group"],
                    seq=s["seq"], round=s["round"],
                    missing_ranks=s["missing_ranks"],
                    behind_ranks=s["behind_ranks"],
                    stuck_s=s["stuck_s"])
                self._count("straggler")
        # straggler incidents resolve through the same `gone` path on
        # the next probe once the group's rounds start completing

        self.last_probe = summary
        return summary
