"""Always-on sampling profiler: per-worker stack sampling with
task attribution, aggregated into folded stacks.

Reference counterpart: `ray stack` / py-spy attach in the reference
runtime — replaced by an IN-PROCESS stdlib sampler (`sys._current_frames`
walked by a daemon thread at `RAY_TPU_PROFILE_HZ`) so profiles carry
task/actor-method attribution for free: the PR-3 per-task log markers
(`core/logging.mark_current_task`) also stamp a thread→task map here,
and every sample lands in a `(task_id, folded_stack)` bucket.

Aggregates stay bounded (`RAY_TPU_PROFILE_MAX_STACKS` distinct stacks,
overflow collapses into one "(overflow)" bucket) and ship to the driver
as deltas over the existing telemetry channel (`sys.profile` reports on
the worker heartbeat — never the control plane), where a
`ClusterProfileStore` merges them per worker for `ray_tpu profile` /
`/api/profile` export as collapsed-stack (flamegraph.pl / speedscope
paste) or speedscope JSON.

The sampler is off by default (hz=0) and can be started, stopped, or
snapshotted per worker at runtime through the `profile_ctl` control
verb without restarting anything.
"""
from __future__ import annotations

import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..util import knobs

__all__ = ["SamplingProfiler", "ClusterProfileStore", "mark_thread",
           "fold_frame", "dump_stacks"]

# thread ident -> task_id currently attributed to that thread (same
# last-marker-wins contract as the log markers). Plain dict ops are
# atomic under the GIL; the sampler reads a point-in-time copy.
_marks: Dict[int, str] = {}


def mark_thread(task_id: Optional[str]) -> None:
    """Attribute the calling thread's future samples to `task_id`
    (None = idle). Hooked from core/logging.mark_current_task so the
    existing task-boundary markers drive profiler attribution too."""
    ident = threading.get_ident()
    if task_id:
        _marks[ident] = task_id
    else:
        _marks.pop(ident, None)


def _short_path(path: str) -> str:
    """Last two path components — enough to tell ray_tpu/core/worker.py
    from a user module without shipping absolute paths in every frame."""
    head, tail = os.path.split(path)
    base = os.path.basename(head)
    return f"{base}/{tail}" if base else tail


def fold_frame(frame, depth: int) -> str:
    """One sampled frame folded root-first into the collapsed-stack
    convention: `file:func;file:func;...` (leaf last)."""
    parts: List[str] = []
    while frame is not None and len(parts) < depth:
        code = frame.f_code
        parts.append(f"{_short_path(code.co_filename)}:{code.co_name}")
        frame = frame.f_back
    parts.reverse()
    return ";".join(parts)


def dump_stacks(depth: Optional[int] = None) -> dict:
    """One-shot stack dump of every live thread in this process (the
    `ray_tpu stack` payload — the in-process answer to py-spy attach).
    Unlike the sampler this is on demand and exact: each thread's
    current stack, folded root-first, with its name and the task id
    currently attributed to it."""
    if depth is None:
        depth = knobs.get_int("RAY_TPU_PROFILE_DEPTH")
    names = {t.ident: t.name for t in threading.enumerate()}
    marks = dict(_marks)
    threads: List[Dict[str, Any]] = []
    for ident, frame in sys._current_frames().items():
        threads.append({"ident": ident,
                        "name": names.get(ident, f"thread-{ident}"),
                        "task_id": marks.get(ident, ""),
                        "stack": fold_frame(frame, depth)})
    threads.sort(key=lambda t: t["name"])
    return {"threads": threads, "ts": time.time()}


class SamplingProfiler:
    """The in-worker sampler: a daemon thread walks every live thread's
    stack at `hz` and aggregates (task_id, folded_stack) counts between
    `collect_delta()` calls. All entry points are thread-safe and never
    raise into callers — profiling must not fail user work."""

    def __init__(self, hz: float = 0.0,
                 max_stacks: Optional[int] = None,
                 depth: Optional[int] = None):
        self._lock = threading.Lock()
        self._counts: Dict[Tuple[str, str], int] = {}
        self._max_stacks = (max_stacks if max_stacks is not None
                            else knobs.get_int("RAY_TPU_PROFILE_MAX_STACKS"))
        self._depth = (depth if depth is not None
                       else knobs.get_int("RAY_TPU_PROFILE_DEPTH"))
        self._hz = 0.0
        self._samples_total = 0
        self._dropped = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._gen = 0           # bumps on every set_hz: retires old threads
        if hz > 0:
            self.set_hz(hz)

    @property
    def hz(self) -> float:
        return self._hz

    def set_hz(self, hz: float) -> None:
        """Start (hz>0), retune, or stop (hz<=0) the sampler thread."""
        hz = max(0.0, float(hz))
        with self._lock:
            self._hz = hz
            self._gen += 1
            gen = self._gen
        if hz <= 0:
            self._stop.set()
            return
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, args=(gen, self._stop), daemon=True,
            name="rtpu-profiler")
        self._thread.start()

    def stop(self) -> None:
        self.set_hz(0.0)

    def _loop(self, gen: int, stop: threading.Event) -> None:
        while not stop.is_set():
            with self._lock:
                if self._gen != gen:
                    return          # superseded by a newer set_hz
                hz = self._hz
            if hz <= 0:
                return
            if stop.wait(1.0 / hz):
                return
            try:
                self._sample_once()
            except Exception:
                pass                # a bad frame walk skips one tick

    def _sample_once(self) -> None:
        me = threading.get_ident()
        frames = sys._current_frames()
        marks = dict(_marks)
        folded: List[Tuple[str, str]] = []
        for ident, frame in frames.items():
            if ident == me:
                continue            # never sample the sampler
            folded.append((marks.get(ident, ""),
                           fold_frame(frame, self._depth)))
        del frames
        with self._lock:
            for key in folded:
                if key not in self._counts \
                        and len(self._counts) >= self._max_stacks:
                    self._counts[("", "(overflow)")] = \
                        self._counts.get(("", "(overflow)"), 0) + 1
                    self._dropped += 1
                    continue
                self._counts[key] = self._counts.get(key, 0) + 1
            self._samples_total += len(folded)
        try:
            from ..util import metrics_catalog as mcat  # noqa: PLC0415
            mcat.get("ray_tpu_profile_samples_total").inc(len(folded))
        except Exception:
            pass

    # ---- export -----------------------------------------------------------
    def collect_delta(self) -> Optional[dict]:
        """Swap out and return the aggregate accumulated since the last
        call as a wire-pure payload (msgpack-safe: strings/ints/floats
        only), or None when nothing was sampled."""
        with self._lock:
            if not self._counts:
                return None
            counts, self._counts = self._counts, {}
            dropped, self._dropped = self._dropped, 0
            hz = self._hz
        return {"hz": hz,
                "samples": [[task, stack, n]
                            for (task, stack), n in counts.items()],
                "dropped": dropped}

    def snapshot(self) -> dict:
        """Non-destructive view of the pending (un-flushed) aggregate
        plus lifetime totals — the profile_ctl `snapshot` reply."""
        with self._lock:
            return {"hz": self._hz,
                    "samples": [[task, stack, n]
                                for (task, stack), n
                                in self._counts.items()],
                    "dropped": self._dropped,
                    "samples_total": self._samples_total}

    def status(self) -> dict:
        with self._lock:
            return {"hz": self._hz,
                    "samples_total": self._samples_total,
                    "pending_stacks": len(self._counts),
                    "dropped": self._dropped}


class ClusterProfileStore:
    """Driver-side merge of every worker's `sys.profile` deltas, keyed
    `(worker_id, task_id, folded_stack)`; exports collapsed-stack text
    and speedscope JSON (mirrors ClusterMetricsStore for metrics)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: Dict[Tuple[str, str, str], int] = {}
        self.samples_total = 0
        self.dropped_total = 0
        self.hz: Dict[str, float] = {}      # worker_id -> last known hz

    def ingest(self, worker_id: str, payload: dict) -> None:
        if not isinstance(payload, dict):
            return
        samples = payload.get("samples") or []
        with self._lock:
            self.hz[worker_id] = float(payload.get("hz", 0.0) or 0.0)
            self.dropped_total += int(payload.get("dropped", 0) or 0)
            for entry in samples:
                try:
                    task, stack, n = entry
                except Exception:
                    continue
                key = (worker_id, str(task or ""), str(stack))
                self._counts[key] = self._counts.get(key, 0) + int(n)
                self.samples_total += int(n)

    def clear(self) -> None:
        with self._lock:
            self._counts.clear()
            self.samples_total = 0
            self.dropped_total = 0

    def _filtered(self, worker: Optional[str],
                  task: Optional[str]) -> Dict[Tuple[str, str, str], int]:
        with self._lock:
            return {k: v for k, v in self._counts.items()
                    if (worker is None or k[0] == worker)
                    and (task is None or k[1] == task)}

    def collapsed(self, worker: Optional[str] = None,
                  task: Optional[str] = None,
                  tag_tasks: bool = True) -> str:
        """flamegraph.pl / speedscope-paste format: one `stack count`
        line per aggregate bucket; task attribution becomes a synthetic
        root frame `task:<id>` so per-task towers separate visually."""
        merged: Dict[str, int] = {}
        for (wid, tid, stack), n in self._filtered(worker, task).items():
            line = stack
            if tag_tasks and tid:
                line = f"task:{tid};{line}" if line else f"task:{tid}"
            merged[line] = merged.get(line, 0) + n
        return "\n".join(f"{stack} {n}"
                         for stack, n in sorted(merged.items(),
                                                key=lambda kv: -kv[1]))

    def speedscope(self, worker: Optional[str] = None,
                   task: Optional[str] = None,
                   name: str = "ray_tpu profile") -> dict:
        """One sampled-type speedscope profile (weights = sample
        counts); open at https://www.speedscope.app or in Perfetto."""
        frame_index: Dict[str, int] = {}
        frames: List[Dict[str, Any]] = []
        samples: List[List[int]] = []
        weights: List[int] = []

        def fidx(fname: str) -> int:
            i = frame_index.get(fname)
            if i is None:
                i = frame_index[fname] = len(frames)
                frames.append({"name": fname})
            return i

        for (wid, tid, stack), n in sorted(
                self._filtered(worker, task).items()):
            parts = []
            if tid:
                parts.append(f"task:{tid}")
            parts.extend(p for p in stack.split(";") if p)
            samples.append([fidx(p) for p in parts])
            weights.append(n)
        total = sum(weights)
        return {
            "$schema": "https://www.speedscope.app/file-format-schema.json",
            "name": name,
            "shared": {"frames": frames},
            "profiles": [{
                "type": "sampled", "name": name, "unit": "none",
                "startValue": 0, "endValue": total,
                "samples": samples, "weights": weights,
            }],
        }

    def summary(self) -> dict:
        with self._lock:
            workers = sorted({k[0] for k in self._counts})
            return {"samples_total": self.samples_total,
                    "dropped_total": self.dropped_total,
                    "stacks": len(self._counts),
                    "workers": workers,
                    "hz": dict(self.hz)}
