"""Chrome-trace timeline export of task/actor spans.

Reference counterpart: ray.timeline() (python/ray/_private/profiling.py,
state API timeline export) — emits the chrome://tracing "trace events"
JSON array format. Rows are workers; spans are task executions; instant
events mark actor state changes.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from ..core.runtime import get_runtime

_US = 1_000_000.0


def timeline_events() -> List[Dict[str, Any]]:
    rt = get_runtime()
    events: List[Dict[str, Any]] = []
    pid = 1   # single "process": the cluster; tid = worker lane

    lanes: Dict[str, int] = {}

    def lane(wid: Optional[str]) -> int:
        key = wid or "driver"
        if key not in lanes:
            lanes[key] = len(lanes) + 1
            events.append({
                "name": "thread_name", "ph": "M", "pid": pid,
                "tid": lanes[key], "args": {"name": f"worker:{key}"}})
        return lanes[key]

    for te in list(rt.gcs.tasks.values()):
        if not te.started_at:
            continue
        end = te.finished_at or te.started_at
        cat = "actor_task" if te.actor_id else "task"
        events.append({
            "name": te.name, "cat": cat, "ph": "X",
            "ts": te.started_at * _US,
            "dur": max(1.0, (end - te.started_at) * _US),
            "pid": pid, "tid": lane(te.worker_id),
            "args": {"task_id": te.task_id, "state": te.state,
                     "actor_id": te.actor_id,
                     "queued_s": round(te.started_at - te.submitted_at, 6)
                     if te.submitted_at else None},
        })
    for ae in list(rt.gcs.actors.values()):
        if ae.worker_id is None:
            continue
        events.append({
            "name": f"actor:{ae.class_name}[{ae.state}]", "cat": "actor",
            "ph": "i", "s": "t",
            "ts": 0 if not rt.gcs.tasks else min(
                (t.submitted_at for t in list(rt.gcs.tasks.values())
                 if t.submitted_at), default=0) * _US,
            "pid": pid, "tid": lane(ae.worker_id),
            "args": {"actor_id": ae.actor_id}})
    return events


def timeline(filename: Optional[str] = None) -> Any:
    """Export the trace; returns the event list, optionally writing JSON
    loadable in chrome://tracing / Perfetto."""
    events = timeline_events()
    if filename is not None:
        with open(filename, "w") as f:
            json.dump(events, f)
        return filename
    return events
