"""Chrome-trace timeline export of task/actor spans.

Reference counterpart: ray.timeline() (python/ray/_private/profiling.py,
state API timeline export) — emits the chrome://tracing "trace events"
JSON array format. Rows are workers; spans are task executions; instant
events mark actor state changes.

Cross-process spans: each task's driver-side SUBMIT span (queued →
dispatched, drawn on the driver lane) carries the span_id stamped on its
TaskSpec (util/tracing.py); worker processes ship their execution spans
back over the telemetry channel and they render here parented to the
submit span (args.parent_span_id + chrome flow arrows), so one export
shows the full submit → dispatch → execute tree across processes.

Zero-driver fast paths ride the same channel (the flight recorder,
docs/OBSERVABILITY.md): direct-call submit spans (cat "dcall_submit"),
lease grants ("lease_grant"), and compiled-DAG per-stage spans
("dag_stage", parented across worker processes by DERIVED ids —
util/tracing.derived_span_id — with ack-window stall time as an
`ack_stall_s` arg) all merge into this one export.
"""
from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional

from ..core.runtime import get_runtime

_US = 1_000_000.0


def timeline_events() -> List[Dict[str, Any]]:
    rt = get_runtime()
    try:
        # compiled-DAG controllers defer driver-side submit/result
        # spans in bounded rings; surface them before reading the store
        rt.drain_fastpath_spans()
    except Exception:
        pass
    events: List[Dict[str, Any]] = []
    pid = 1   # single "process": the cluster; tid = worker lane

    lanes: Dict[str, int] = {}

    def lane(wid: Optional[str]) -> int:
        key = wid or "driver"
        if key not in lanes:
            lanes[key] = len(lanes) + 1
            events.append({
                "name": "thread_name", "ph": "M", "pid": pid,
                "tid": lanes[key], "args": {"name": f"worker:{key}"}})
        return lanes[key]

    for te in list(rt.gcs.tasks.values()):
        span_id = getattr(te, "span_id", "")
        if te.submitted_at:
            # driver-side submit span: queued -> dispatched (or queued ->
            # now for still-pending tasks — those are exactly the bars a
            # queueing investigation needs to see); worker execution
            # spans parent to its span_id
            sub_end = te.started_at or te.finished_at or time.time()
            events.append({
                "name": f"submit:{te.name}", "cat": "submit", "ph": "X",
                "ts": te.submitted_at * _US,
                "dur": max(1.0, (sub_end - te.submitted_at) * _US),
                "pid": pid, "tid": lane("driver"),
                "args": {"task_id": te.task_id, "state": te.state,
                         "span_id": span_id,
                         "parent_span_id": getattr(te, "parent_span_id",
                                                   ""),
                         "trace_id": getattr(te, "trace_id", "")},
            })
            if span_id:
                events.append({
                    "name": "task", "cat": "submit_flow", "ph": "s",
                    "id": span_id, "ts": te.submitted_at * _US,
                    "pid": pid, "tid": lane("driver")})
        if not te.started_at:
            continue
        end = te.finished_at or te.started_at
        cat = "actor_task" if te.actor_id else "task"
        events.append({
            "name": te.name, "cat": cat, "ph": "X",
            "ts": te.started_at * _US,
            "dur": max(1.0, (end - te.started_at) * _US),
            "pid": pid, "tid": lane(te.worker_id),
            "args": {"task_id": te.task_id, "state": te.state,
                     "actor_id": te.actor_id,
                     "span_id": span_id,
                     "queued_s": round(te.started_at - te.submitted_at, 6)
                     if te.submitted_at else None},
        })
    # worker-side execution spans shipped over the telemetry channel
    # (core/worker.py): true in-process timing, parented to the driver's
    # submit span and linked with a chrome flow arrow
    for sp in list(getattr(rt, "trace_spans", ())):
        try:
            start, end = sp["start"], sp["end"]
        except (KeyError, TypeError):
            continue
        args = {"task_id": sp.get("task_id"),
                "span_id": sp.get("span_id"),
                "parent_span_id": sp.get("parent_span_id"),
                "trace_id": sp.get("trace_id"),
                "status": sp.get("status"),
                "node_id": sp.get("node_id"),
                "worker_pid": sp.get("pid")}
        # fast-path span attributes (compiled-DAG stages, lease grants,
        # direct calls) pass straight through to the trace viewer
        for extra in ("dag_id", "sid", "seqno", "ack_stall_s",
                      "lease_id", "slots"):
            if sp.get(extra) is not None:
                args[extra] = sp[extra]
        events.append({
            "name": sp.get("name", "task"),
            "cat": sp.get("cat", "task_exec"),
            "ph": "X", "ts": start * _US,
            "dur": max(1.0, (end - start) * _US),
            "pid": pid, "tid": lane(sp.get("worker_id")),
            "args": args,
        })
        if sp.get("parent_span_id"):
            events.append({
                "name": "task", "cat": "submit_flow", "ph": "f",
                "bp": "e", "id": sp["parent_span_id"],
                "ts": start * _US, "pid": pid,
                "tid": lane(sp.get("worker_id"))})
    for ae in list(rt.gcs.actors.values()):
        if ae.worker_id is None:
            continue
        events.append({
            "name": f"actor:{ae.class_name}[{ae.state}]", "cat": "actor",
            "ph": "i", "s": "t",
            "ts": 0 if not rt.gcs.tasks else min(
                (t.submitted_at for t in list(rt.gcs.tasks.values())
                 if t.submitted_at), default=0) * _US,
            "pid": pid, "tid": lane(ae.worker_id),
            "args": {"actor_id": ae.actor_id}})
    return events


def span_subtree(trace_id: str = "",
                 subject_id: str = "") -> List[Dict[str, Any]]:
    """The timeline events belonging to one trace (driver submit spans
    + worker execution spans sharing `trace_id`), plus any event whose
    args reference `subject_id` as its task/actor — the span slice a
    post-mortem bundle carries (observability/forensics.py)."""
    out = []
    for e in timeline_events():
        args = e.get("args") or {}
        if trace_id and args.get("trace_id") == trace_id:
            out.append(e)
        elif subject_id and (args.get("task_id") == subject_id
                             or args.get("actor_id") == subject_id):
            out.append(e)
    return out


def timeline(filename: Optional[str] = None) -> Any:
    """Export the trace; returns the event list, optionally writing JSON
    loadable in chrome://tracing / Perfetto."""
    events = timeline_events()
    if filename is not None:
        with open(filename, "w") as f:
            json.dump(events, f)
        return filename
    return events
