"""Memory accounting: store quota + worker RSS watchdog.

Reference counterpart: python/ray/_private/memory_monitor.py and the
raylet OOM killer (src/ray/raylet worker_killing_policy). The store
already enforces its byte quota via LRU eviction (C++ arena); this adds
(a) usage reporting and (b) an optional RSS watchdog that kills the
fattest killable worker before the host OOMs.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional

from ..util import knobs


def _rss_bytes(pid: int) -> Optional[int]:
    try:
        with open(f"/proc/{pid}/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, IndexError, ValueError):
        return None


def _host_memory() -> Dict[str, int]:
    info = {}
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                k, v = line.split(":", 1)
                info[k] = int(v.strip().split()[0]) * 1024
    except OSError:
        pass
    return {"total": info.get("MemTotal", 0),
            "available": info.get("MemAvailable", 0)}


def memory_summary() -> Dict[str, Any]:
    """Snapshot: host memory, store usage, per-worker RSS."""
    from ..core.runtime import get_runtime
    rt = get_runtime()
    workers: List[Dict[str, Any]] = []
    for w in list(rt.workers.values()):
        if w.pid is None or w.state == "dead":
            continue
        workers.append({"worker_id": w.worker_id, "pid": w.pid,
                        "state": w.state, "rss_bytes": _rss_bytes(w.pid)})
    host = _host_memory()
    return {
        "host_total_bytes": host["total"],
        "host_available_bytes": host["available"],
        "store_used_bytes": rt.store.used_bytes(),
        "store_capacity_bytes": getattr(rt.store, "capacity", None),
        "workers": workers,
        "driver_rss_bytes": _rss_bytes(os.getpid()),
    }


class MemoryMonitor:
    """Background watchdog: when host available memory drops below
    `min_available_frac`, terminate the highest-RSS busy worker (its task
    retries per max_retries — same contract as the reference OOM killer).
    """

    def __init__(self, *, min_available_frac: float = 0.05,
                 poll_interval_s: float = 1.0, kill: bool = True):
        self.min_available_frac = min_available_frac
        self.poll_interval_s = poll_interval_s
        self.kill = kill
        self.events: List[Dict[str, Any]] = []
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="rtpu-memmon")
        self._thread.start()

    def _gauge(self, frac: float) -> None:
        """Publish host memory headroom so pressure is visible on the
        dashboard BEFORE the watchdog kills anything (cataloged gauge:
        1 - available/total, i.e. rises toward 1.0 under pressure)."""
        try:
            from ..util import metrics_catalog as mcat
            mcat.get("ray_tpu_node_memory_pressure").set(
                round(1.0 - frac, 6))
        except Exception:
            pass

    def _loop(self) -> None:
        from ..core.runtime import get_runtime
        from ..util import events as events_mod
        pressured = False
        while not self._stop.wait(self.poll_interval_s):
            host = _host_memory()
            if not host["total"]:
                continue
            frac = host["available"] / host["total"]
            self._gauge(frac)
            if frac >= self.min_available_frac:
                pressured = False
                continue
            if not pressured:
                # one event per pressure episode, emitted whether or
                # not a kill follows (there may be nothing to kill)
                pressured = True
                try:
                    events_mod.emit(
                        "node.memory_pressure",
                        f"host available memory {frac:.1%} below "
                        f"threshold {self.min_available_frac:.1%}",
                        node_id=knobs.get_raw("RAY_TPU_NODE_ID"),
                        available_frac=round(frac, 4),
                        threshold=self.min_available_frac)
                except Exception:
                    pass
            try:
                rt = get_runtime()
            except Exception:
                continue
            victims = [(w, _rss_bytes(w.pid) or 0)
                       for w in list(rt.workers.values())
                       if w.state == "busy" and w.pid]
            if not victims:
                continue
            victim, rss = max(victims, key=lambda t: t[1])
            self.events.append({"time": time.time(),
                                "worker_id": victim.worker_id,
                                "rss_bytes": rss,
                                "available_frac": frac,
                                "killed": self.kill})
            if self.kill:
                rt.inbox.put(("worker_dead", victim.worker_id))
                try:
                    victim.proc.terminate()
                except Exception:
                    pass

    def stop(self) -> None:
        self._stop.set()
