"""Dashboard: HTTP JSON endpoints over the state API.

Reference counterpart: python/ray/dashboard (head modules serving
/api/...). No JS frontend (documented gap in SURVEY.md §2.8 O2); every
panel the reference renders is available as JSON:

  GET /api/cluster     — cluster summary
  GET /api/persistence — control-plane WAL/snapshot health
  GET /api/dispatch    — batched-dispatch plane counters (submit
                         batches, worker leases, direct actor calls)
  GET /api/nodes       — node table
  GET /api/actors      — actor table
  GET /api/tasks       — task table
  GET /api/objects     — object summary + rows
  GET /api/workers     — worker processes
  GET /api/placement_groups
  GET /api/timeline    — chrome-trace events
  GET /api/profile     — sampling-profiler aggregate
                         (?format=summary|collapsed|speedscope,
                          ?worker=<wid>, ?task=<task id>)
  GET /api/waits       — cluster wait chains with root causes
                         (?id=<subject>, ?min_age=<seconds>)
  GET /api/waitgraph   — folded waits-on graph + watchdog findings
  GET /metrics         — Prometheus text exposition

Job submission over HTTP (reference: python/ray/dashboard/modules/job/
job_head.py + job_manager.py — submit/status/logs via the dashboard):

  POST /api/jobs                 {"entrypoint": ..., "runtime_env": ...}
  GET  /api/jobs                 — job table
  GET  /api/jobs/<sid>           — one job's info
  GET  /api/jobs/<sid>/logs      — captured logs (?follow=1 streams
                                   chunked text until the job exits)
  POST /api/jobs/<sid>/stop      — SIGTERM the job's process group
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional
from urllib.parse import parse_qs, urlparse

from ..util import metrics as metrics_mod
from ..util import state as state_mod
from . import timeline as timeline_mod


_jobs_client = None
_jobs_lock = threading.Lock()


def _jobs():
    """One shared JobSubmissionClient behind the HTTP surface (jobs
    submitted over HTTP and via this process's Python client share a
    table the way the reference's JobManager does)."""
    global _jobs_client
    with _jobs_lock:
        if _jobs_client is None:
            from ..core.jobs import JobSubmissionClient
            _jobs_client = JobSubmissionClient()
        return _jobs_client


class _Handler(BaseHTTPRequestHandler):
    # chunked Transfer-Encoding (log follow) is only legal on HTTP/1.1;
    # everything else sends Content-Length so keep-alive stays correct
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):       # silence per-request stderr noise
        pass

    def _send(self, code: int, body: bytes,
              ctype: str = "application/json") -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _json(self, obj: Any, code: int = 200) -> None:
        self._send(code, json.dumps(obj, default=str).encode())

    def do_GET(self):
        try:
            parsed = urlparse(self.path)
            q = parse_qs(parsed.query)
        except ValueError:
            self._json({"error": "malformed query string"}, 400)
            return
        try:
            limit = int(q.get("limit", ["100"])[0])
            since_seq = int(q.get("since", ["0"])[0])
        except (ValueError, TypeError):
            # a malformed query param is the CLIENT's error, not a 500
            self._json({"error": "limit/since must be integers"}, 400)
            return
        route = parsed.path.rstrip("/")
        try:
            if route == "/api/cluster":
                self._json(state_mod.cluster_summary())
            elif route == "/api/persistence":
                self._json(state_mod.persistence_summary())
            elif route == "/api/dispatch":
                self._json(state_mod.dispatch_summary())
            elif route == "/api/nodes":
                self._json(state_mod.list_nodes(limit=limit))
            elif route == "/api/actors":
                self._json(state_mod.list_actors(limit=limit))
            elif route == "/api/tasks":
                self._json(state_mod.list_tasks(limit=limit))
            elif route == "/api/objects":
                self._json({"summary": state_mod.summarize_objects(),
                            "objects": state_mod.list_objects(limit=limit)})
            elif route == "/api/workers":
                self._json(state_mod.list_workers(limit=limit))
            elif route == "/api/placement_groups":
                self._json(state_mod.list_placement_groups(limit=limit))
            elif route == "/api/summary/tasks":
                self._json(state_mod.summarize_tasks())
            elif route == "/api/summary/actors":
                self._json(state_mod.summarize_actors())
            elif route == "/api/summary/objects":
                self._json(state_mod.summarize_objects())
            elif route == "/api/events":
                ids = [v for key in ("id", "task_id", "actor_id",
                                     "object_id", "node_id",
                                     "worker_id")
                       for v in q.get(key, [])]
                types = q.get("type") or None
                sevs = q.get("severity") or None
                rows = state_mod.list_events(
                    limit=limit, ids=ids or None, types=types,
                    severities=sevs, since_seq=since_seq)
                self._json({"events": list(rows),
                            "total": rows.total,
                            "truncated": rows.truncated})
            elif route == "/api/summary/events":
                self._json(state_mod.summarize_events())
            elif route == "/api/post_mortem":
                sid = (q.get("id") or [""])[0]
                if not sid:
                    self._json({"error": "missing ?id=<task|actor id>"},
                               400)
                else:
                    from . import forensics
                    self._json(forensics.build_post_mortem(sid))
            elif route == "/api/waits":
                sid = (q.get("id") or [None])[0]
                try:
                    min_age = float((q.get("min_age") or ["0"])[0])
                except (ValueError, TypeError):
                    self._json({"error": "min_age must be a number"},
                               400)
                    return
                self._json({"waits": state_mod.wait_chains(
                    subject_id=sid, min_age_s=min_age)})
            elif route == "/api/waitgraph":
                self._json(state_mod.waitgraph())
            elif route == "/api/timeline":
                self._json(timeline_mod.timeline_events())
            elif route == "/api/profile":
                from ..core.runtime import get_runtime
                store = get_runtime().profile_store
                fmt = (q.get("format") or ["summary"])[0]
                worker = (q.get("worker") or [None])[0]
                task = (q.get("task") or [None])[0]
                if fmt == "collapsed":
                    self._send(200,
                               store.collapsed(worker, task).encode(),
                               "text/plain; charset=utf-8")
                elif fmt == "speedscope":
                    self._json(store.speedscope(worker, task))
                else:
                    self._json(store.summary())
            elif route == "/api/serve":
                self._json(_serve_status())
            elif route == "/api/serve/router":
                self._json(state_mod.serve_router_table())
            elif route == "/api/serve/autoscaler":
                self._json(state_mod.serve_autoscaler_status())
            elif route == "/api/jobs":
                self._json(_jobs().list_jobs())
            elif route.startswith("/api/jobs/"):
                parts = route.split("/")  # ['', 'api', 'jobs', sid, ...]
                sid = parts[3]
                if len(parts) == 4:
                    self._json(_jobs().get_job_info(sid))
                elif parts[4] == "logs" and q.get("follow", ["0"])[0] \
                        in ("1", "true"):
                    self._stream_logs(sid)
                elif parts[4] == "logs":
                    self._json({"submission_id": sid,
                                "logs": _jobs().get_job_logs(sid)})
                else:
                    self._json({"error": f"no route {route}"}, 404)
            elif route == "/metrics":
                # merged cluster exposition: local registry + every
                # worker/node snapshot shipped to the driver (series
                # tagged node_id/worker_id)
                self._send(200, metrics_mod.cluster_exposition().encode(),
                           "text/plain; version=0.0.4")
            elif route in ("", "/"):
                self._send(200, _INDEX_HTML.encode(),
                           "text/html; charset=utf-8")
            elif route == "/api":
                self._json({"routes": ["/api/cluster", "/api/nodes",
                                       "/api/actors", "/api/tasks",
                                       "/api/objects", "/api/workers",
                                       "/api/placement_groups",
                                       "/api/dispatch",
                                       "/api/serve",
                                       "/api/serve/router",
                                       "/api/serve/autoscaler",
                                       "/api/summary/tasks",
                                       "/api/summary/actors",
                                       "/api/summary/objects",
                                       "/api/summary/events",
                                       "/api/events",
                                       "/api/post_mortem",
                                       "/api/jobs",
                                       "/api/waits", "/api/waitgraph",
                                       "/api/timeline", "/api/profile",
                                       "/metrics"]})
            else:
                self._json({"error": f"no route {route}"}, 404)
        except (BrokenPipeError, ConnectionResetError):
            # client hung up mid-response: writing an error body would
            # raise again and leak a 500 into the server log — just
            # drop the connection
            self.close_connection = True
        except ValueError as e:      # unknown job id etc.
            self._json({"error": str(e)}, 404)
        except Exception as e:  # surface errors as JSON, keep serving
            try:
                self._json({"error": repr(e)}, 500)
            except OSError:
                self.close_connection = True

    def do_POST(self):
        route = urlparse(self.path).path.rstrip("/")
        try:
            n = int(self.headers.get("Content-Length") or 0)
            body = json.loads(self.rfile.read(n) or b"{}") if n else {}
            if route == "/api/jobs":
                sid = _jobs().submit_job(
                    entrypoint=body["entrypoint"],
                    runtime_env=body.get("runtime_env"),
                    submission_id=body.get("submission_id"),
                    metadata=body.get("metadata"))
                self._json({"submission_id": sid})
            elif route.startswith("/api/jobs/") and \
                    route.endswith("/stop"):
                sid = route.split("/")[3]
                self._json({"submission_id": sid,
                            "stopped": _jobs().stop_job(sid)})
            elif route == "/api/profile":
                # drive one worker's sampling profiler:
                # {"worker": wid, "action": start|stop|snapshot|status,
                #  "hz": 100}  (core/worker.py profile_ctl verb)
                from ..core.runtime import get_runtime
                self._json(get_runtime().profile_ctl(
                    body["worker"], body.get("action", "status"),
                    body.get("hz")))
            else:
                self._json({"error": f"no route {route}"}, 404)
        except (BrokenPipeError, ConnectionResetError):
            self.close_connection = True
        except KeyError as e:
            self._json({"error": f"missing field {e}"}, 400)
        except json.JSONDecodeError as e:
            # malformed request body is the client's error, not a 404
            self._json({"error": f"malformed JSON body: {e}"}, 400)
        except ValueError as e:
            self._json({"error": str(e)}, 404)
        except Exception as e:  # noqa: BLE001
            try:
                self._json({"error": repr(e)}, 500)
            except OSError:
                self.close_connection = True

    def _stream_logs(self, sid: str) -> None:
        """Chunked text/plain tail of a job's logs until it exits
        (reference: JobSubmissionClient.tail_job_logs)."""
        _jobs().get_job_info(sid)   # raise ValueError BEFORE headers
        gen = _jobs().tail_job_logs(sid)
        self.send_response(200)
        self.send_header("Content-Type", "text/plain; charset=utf-8")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def chunk(data: bytes) -> None:
            self.wfile.write(f"{len(data):x}\r\n".encode())
            self.wfile.write(data + b"\r\n")
            self.wfile.flush()

        try:
            for piece in gen:
                if piece:
                    chunk(piece.encode(errors="replace"))
            self.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError):
            pass                      # client hung up mid-tail
        except Exception:  # noqa: BLE001
            # mid-stream failure AFTER headers went out (e.g. the log
            # file vanished): a second HTTP response would corrupt the
            # chunked framing — terminate the stream and drop the
            # connection instead
            try:
                self.wfile.write(b"0\r\n\r\n")
            except OSError:
                pass
            self.close_connection = True


def _serve_status() -> Any:
    """Serve application/deployment table if a controller is running."""
    import ray_tpu
    from ..serve.controller import CONTROLLER_NAME
    try:
        ctrl = ray_tpu.get_actor(CONTROLLER_NAME, timeout=0.2)
    except ValueError:
        return {"running": False, "applications": {}}
    try:
        apps = ray_tpu.get(ctrl.list_applications.remote(), timeout=5.0)
        detail = {a: ray_tpu.get(ctrl.get_app_status.remote(a),
                                 timeout=5.0) for a in apps}
        return {"running": True, "applications": detail}
    except Exception as e:  # noqa: BLE001
        return {"running": True, "error": repr(e)}


# Single-file status page: fetches the JSON endpoints client-side and
# renders tables (no build step — the documented JS-frontend scope cut
# stays; this is the reference dashboard's overview page, not its SPA).
_INDEX_HTML = """<!doctype html>
<meta charset="utf-8"><title>ray_tpu dashboard</title>
<style>
 body{font:13px system-ui,sans-serif;margin:1.2em;background:#fafafa}
 h1{font-size:18px} h2{font-size:14px;margin:1.2em 0 .3em}
 table{border-collapse:collapse;background:#fff;min-width:40em}
 td,th{border:1px solid #ddd;padding:.25em .6em;text-align:left}
 th{background:#f0f0f0} code{background:#eee;padding:0 .3em}
 #err{color:#b00}
</style>
<h1>ray_tpu dashboard</h1>
<div id=err></div>
<h2>Cluster</h2><table id=cluster></table>
<h2>Nodes</h2><table id=nodes></table>
<h2>Actors</h2><table id=actors></table>
<h2>Task summary</h2><table id=tasks></table>
<h2>Serve</h2><table id=serve></table>
<h2>Jobs</h2><table id=jobs></table>
<h2>Recent warnings &amp; errors</h2><table id=events></table>
<script>
const cell = v => typeof v === 'object' && v !== null
  ? JSON.stringify(v) : String(v);
function rows(el, list){
  const t = document.getElementById(el);
  if (!Array.isArray(list)) list = Object.entries(list).map(
    ([k, v]) => ({key: k, value: v}));
  if (!list.length) { t.innerHTML = '<tr><td>-</td></tr>'; return; }
  const cols = Object.keys(list[0]);
  t.innerHTML = '<tr>' + cols.map(c => `<th>${c}</th>`).join('')
    + '</tr>' + list.map(r => '<tr>' + cols.map(
      c => `<td>${cell(r[c])}</td>`).join('') + '</tr>').join('');
}
async function refresh(){
  try {
    const get = p => fetch(p).then(r => r.json());
    rows('cluster', await get('/api/cluster'));
    rows('nodes', await get('/api/nodes'));
    rows('actors', await get('/api/actors?limit=50'));
    rows('tasks', await get('/api/summary/tasks'));
    const s = await get('/api/serve');
    rows('serve', s.running ? s.applications : {running: false});
    rows('jobs', await get('/api/jobs'));
    const ev = await get(
      '/api/events?severity=warning&severity=error&limit=20');
    rows('events', (ev.events || []).map(e => ({
      seq: e.seq, type: e.type, severity: e.severity,
      message: e.message || '',
      id: e.task_id || e.actor_id || e.object_id || e.node_id || ''})));
    document.getElementById('err').textContent = '';
  } catch (e) {
    document.getElementById('err').textContent = 'refresh failed: ' + e;
  }
}
refresh(); setInterval(refresh, 3000);
</script>"""


class Dashboard:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._server = ThreadingHTTPServer((host, port), _Handler)
        self.host, self.port = self._server.server_address
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="rtpu-dashboard")
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


_dashboard: Optional[Dashboard] = None


def start_dashboard(host: str = "127.0.0.1", port: int = 0) -> Dashboard:
    global _dashboard
    if _dashboard is None:
        _dashboard = Dashboard(host, port)
    return _dashboard


def stop_dashboard() -> None:
    global _dashboard
    if _dashboard is not None:
        _dashboard.stop()
        _dashboard = None
