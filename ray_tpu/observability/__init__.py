"""Observability: timeline tracing, dashboard, memory accounting.

Reference counterpart: python/ray/dashboard, ray.timeline,
_private/memory_monitor.py (SURVEY.md §2.8 O2/O4/O6).
"""
from .dashboard import Dashboard, start_dashboard, stop_dashboard
from .forensics import build_post_mortem, write_post_mortem
from .memory_monitor import MemoryMonitor, memory_summary
from .timeline import timeline, timeline_events
from . import profiler  # noqa: F401

__all__ = ["Dashboard", "start_dashboard", "stop_dashboard",
           "MemoryMonitor", "memory_summary", "timeline",
           "timeline_events", "profiler", "build_post_mortem",
           "write_post_mortem"]
