"""On-device profiling: jax profiler traces + timing helpers.

Reference parity: ray.timeline covers host-side task spans
(observability/timeline.py); this module adds the DEVICE side — XLA/TPU
op-level traces via jax.profiler — so a perf investigation gets both
views. Traces open in TensorBoard's profile plugin or Perfetto.
"""
from __future__ import annotations

import contextlib
import os
import time
from typing import Any, Dict, Optional

_active_dir: Optional[str] = None


def start_trace(log_dir: str) -> str:
    """Begin capturing a device trace into log_dir (one capture at a
    time; mirrors jax.profiler.start_trace)."""
    global _active_dir
    import jax
    os.makedirs(log_dir, exist_ok=True)
    jax.profiler.start_trace(log_dir)
    _active_dir = log_dir
    return log_dir


def stop_trace() -> Optional[str]:
    global _active_dir
    import jax
    jax.profiler.stop_trace()
    out, _active_dir = _active_dir, None
    return out


@contextlib.contextmanager
def trace(log_dir: str):
    """with profiler.trace("/tmp/prof"): step(...)"""
    start_trace(log_dir)
    try:
        yield log_dir
    finally:
        stop_trace()


@contextlib.contextmanager
def annotate(name: str):
    """Named region inside a capture (jax.profiler.TraceAnnotation)."""
    import jax
    with jax.profiler.TraceAnnotation(name):
        yield


def device_memory_profile(path: Optional[str] = None) -> bytes:
    """Snapshot device memory (pprof format; jax.profiler parity)."""
    import jax
    data = jax.profiler.device_memory_profile()
    if path:
        with open(path, "wb") as f:
            f.write(data)
    return data


def hbm_usage() -> Dict[str, int]:
    """bytes-in-use per local accelerator device (device.memory_stats,
    the cheap always-callable sibling of device_memory_profile). Only
    consults jax when user code already imported it — a worker that
    never touched jax must not pay the import — AND only when a
    backend is already live: jax.local_devices() on a cold process
    would initialize the backend, which breaks a later
    jax.distributed.initialize() (multihost SPMD workers would die on
    'must be called before any JAX computations'). Returns {} on
    backends that do not report memory stats (CPU)."""
    import sys
    if "jax" not in sys.modules:
        return {}
    import jax
    try:
        from jax._src import xla_bridge  # noqa: PLC0415
        if not getattr(xla_bridge, "_backends", None):
            return {}
    except Exception:
        return {}
    out: Dict[str, int] = {}
    try:
        for dev in jax.local_devices():
            stats_fn = getattr(dev, "memory_stats", None)
            stats = stats_fn() if callable(stats_fn) else None
            if not stats:
                continue
            used = stats.get("bytes_in_use")
            if used is not None:
                out[str(dev.id)] = int(used)
    except Exception:
        pass
    return out


def host_rss_bytes() -> int:
    """This process's resident set size (/proc/self/statm)."""
    with open("/proc/self/statm") as f:
        pages = int(f.read().split()[1])
    return pages * os.sysconf("SC_PAGE_SIZE")


def timed_steps(step_fn, state, batch, *, warmup: int = 2,
                iters: int = 10, sync=None) -> Dict[str, Any]:
    """Wall-time a jitted step the way bench.py does: warmup, then time
    `iters` calls fenced by a host fetch of `sync(result)` (defaults to
    the first leaf of the metrics pytree)."""
    import jax
    import numpy as np

    def fence(out):
        tgt = sync(out) if sync is not None else \
            jax.tree_util.tree_leaves(out)[0]
        return np.asarray(tgt)

    for _ in range(warmup):
        state, m = step_fn(state, batch)
    fence(m)
    t0 = time.time()
    for _ in range(iters):
        state, m = step_fn(state, batch)
    fence(m)
    dt = time.time() - t0
    return {"mean_step_s": dt / iters, "steps_per_s": iters / dt,
            "state": state}


__all__ = ["start_trace", "stop_trace", "trace", "annotate",
           "device_memory_profile", "hbm_usage", "host_rss_bytes",
           "timed_steps"]
