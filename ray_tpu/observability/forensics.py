"""Failure forensics: post-mortem bundles for tasks and actors.

When a task or actor dies, the answer to "what happened?" is scattered
across four planes: the lifecycle event log (util/events.py), the trace
timeline (observability/timeline.py), the per-task-tagged worker logs
(core/logging.py), and the metrics registries. `build_post_mortem`
assembles all four into one JSON artifact — the causally-linked event
chain, the span subtree, the tagged log tail, and a metrics snapshot —
the way the reference's `ray list tasks --detail` + log tailing would
be combined by hand. Served at `GET /api/post_mortem?id=...` and by the
`post-mortem` CLI subcommand.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Set

from ..core.runtime import get_runtime

# How many neighbouring-id events / log lines / metric chars a bundle
# carries — post-mortems are for reading, not for archiving the world.
MAX_CHAIN_EVENTS = 500
MAX_LOG_LINES = 200
MAX_METRICS_CHARS = 200_000


def _subject(rt, subject_id: str) -> Dict[str, Any]:
    """The GCS row(s) for the id: kind + task/actor table entries."""
    te = rt.gcs.tasks.get(subject_id)
    if te is not None:
        return {"kind": "task", "task": {
            "task_id": te.task_id, "name": te.name, "state": te.state,
            "worker_id": te.worker_id, "actor_id": te.actor_id,
            "submitted_at": te.submitted_at,
            "started_at": te.started_at, "finished_at": te.finished_at,
            "retries_left": te.retries_left,
            "trace_id": getattr(te, "trace_id", ""),
            "span_id": getattr(te, "span_id", "")}}
    ae = rt.gcs.actors.get(subject_id)
    if ae is not None:
        return {"kind": "actor", "actor": {
            "actor_id": ae.actor_id, "class_name": ae.class_name,
            "state": ae.state, "worker_id": ae.worker_id,
            "num_restarts": ae.num_restarts,
            "max_restarts": ae.max_restarts,
            "death_cause": ae.death_cause}}
    return {"kind": "unknown"}


def _event_chain(rt, subject_id: str) -> List[Dict[str, Any]]:
    """Causally-linked events: the subject's own events, widened one hop
    through every id they reference (worker, node, objects, sibling
    task/actor) so the chain shows WHY — a task.retry sits next to the
    worker.death and node.death that caused it."""
    from ..util.events import ID_KEYS
    store = rt.cluster_events
    own = store.for_id(subject_id)
    linked: Set[str] = {subject_id}
    nodes: Set[str] = set()
    for ev in own:
        for key in ID_KEYS:
            v = ev.get(key)
            if not v:
                continue
            # node ids link to EVERYTHING on the node; widening through
            # them verbatim would bury the chain in unrelated seals —
            # keep only the node's own lifecycle (node.*) events
            (nodes if key == "node_id" else linked).add(v)
    rows, _total = store.query(ids=sorted(linked | nodes),
                               limit=MAX_CHAIN_EVENTS)
    out = []
    for ev in rows:
        direct = any(ev.get(k) in linked for k in ID_KEYS)
        if direct or (ev.get("type", "").startswith("node.")
                      and ev.get("node_id") in nodes):
            out.append(ev)
    return out


def _span_subtree(rt, subject: Dict[str, Any],
                  subject_id: str) -> List[Dict[str, Any]]:
    """Every timeline event sharing the subject's trace (driver submit
    spans from the task table + worker execution spans shipped over the
    telemetry channel)."""
    # note: `from . import timeline` would resolve to the same-named
    # FUNCTION re-exported by the package __init__, not the module
    from .timeline import span_subtree
    trace_id = ""
    if subject["kind"] == "task":
        trace_id = subject["task"].get("trace_id") or ""
    return span_subtree(trace_id=trace_id, subject_id=subject_id)


def _log_tail(rt, subject: Dict[str, Any],
              subject_id: str) -> Dict[str, Any]:
    """Task-attributed log lines captured on the driver's host (remote
    workers log into their own agent's dir — marked unavailable rather
    than silently empty)."""
    from ..core import logging as logging_mod
    if subject["kind"] == "task":
        lines = logging_mod.task_log_tail(rt.log_dir, subject_id,
                                          max_lines=MAX_LOG_LINES)
        note = None
        te = subject.get("task", {})
        wid = te.get("worker_id")
        if not lines and wid is not None:
            w = rt.workers.get(wid)
            if w is not None and w.node_id not in (None, rt.node_id):
                note = (f"worker {wid} ran on remote node {w.node_id}; "
                        "its log file lives in that agent's log dir")
        return {"lines": lines, "note": note}
    if subject["kind"] == "actor":
        wid = subject["actor"].get("worker_id")
        if wid:
            # an actor's whole worker log is its log; tail it raw
            import os
            path = os.path.join(rt.log_dir, f"worker-{wid}.log")
            try:
                text = logging_mod.read_log_tail(path)
                pairs, _cur = logging_mod.attribute_lines(text)
                lines = [{"worker": f"worker-{wid}",
                          "task_id": tid, "line": line}
                         for tid, line in pairs if line.strip()]
                return {"lines": lines[-MAX_LOG_LINES:], "note": None}
            except OSError:
                return {"lines": [], "note": f"no local log at {path}"}
    return {"lines": [], "note": "no log attribution for this subject"}


def _reconstruction_chain(rt, subject_id: str) -> List[Dict[str, Any]]:
    """The lineage walk behind any reconstructions touching the
    subject: starting from the subject task, follow dep-object edges
    upstream through their producing tasks (bounded hops) and collect
    each hop's object.lost / object.reconstruct / task.retry events —
    so a post-mortem shows WHICH producers re-executed and why, not
    just that the final task retried."""
    recon_types = ("object.lost", "object.reconstruct", "task.retry")
    out: List[Dict[str, Any]] = []
    seen: set = set()
    frontier = [subject_id]
    for hop in range(8):
        nxt: List[str] = []
        for tid in frontier:
            if tid in seen:
                continue
            seen.add(tid)
            spec = rt._lineage_specs.get(tid) \
                or rt._respawnable_specs.get(tid)
            events = [ev for ev in rt.cluster_events.for_id(tid)
                      if ev.get("type") in recon_types]
            for dep in list(getattr(spec, "dep_object_ids", []) or []):
                events.extend(
                    ev for ev in rt.cluster_events.for_id(dep)
                    if ev.get("type") in recon_types)
                de = rt.gcs.objects.get(dep)
                if de is not None and de.owner_task:
                    nxt.append(de.owner_task)
            if events:
                te = rt.gcs.tasks.get(tid)
                out.append({
                    "task_id": tid,
                    "name": te.name if te is not None else None,
                    "hop": hop,
                    "reconstructions": getattr(spec, "reconstructions",
                                               0) if spec else 0,
                    "events": sorted(events,
                                     key=lambda ev: ev.get("ts", 0))})
        if not nxt:
            break
        frontier = nxt
    return out


def build_post_mortem(subject_id: str) -> Dict[str, Any]:
    """One JSON artifact: event chain + span subtree + tagged log tail
    + metrics snapshot for a task_id or actor_id."""
    rt = get_runtime()
    rt.drain_local_events()
    subject = _subject(rt, subject_id)
    chain = _event_chain(rt, subject_id)
    spans = _span_subtree(rt, subject, subject_id)
    logs = _log_tail(rt, subject, subject_id)
    from ..util import metrics as metrics_mod
    try:
        metrics_text = metrics_mod.cluster_exposition()
        if len(metrics_text) > MAX_METRICS_CHARS:
            metrics_text = metrics_text[:MAX_METRICS_CHARS] \
                + "\n# ...truncated...\n"
    except Exception as e:  # noqa: BLE001
        metrics_text = f"# metrics snapshot failed: {e!r}\n"
    # the wait plane's view: chains touching the subject first, else
    # every live chain — a post-mortem on a HUNG subject leads with
    # why it is (or was) not making progress
    try:
        from ..util import state as state_mod
        wait_chains = state_mod.wait_chains(subject_id=subject_id)
        if not wait_chains:
            wait_chains = state_mod.wait_chains()
    except Exception:  # noqa: BLE001
        wait_chains = []
    bundle = {
        "subject_id": subject_id,
        "generated_at": time.time(),
        "subject": subject,
        "events": chain,
        "spans": spans,
        "log_tail": logs,
        "wait_chains": wait_chains,
        "reconstruction": _reconstruction_chain(rt, subject_id),
        "metrics": metrics_text,
        "event_summary": rt.cluster_events.summarize(),
    }
    if getattr(rt, "incarnation", 0) or getattr(rt, "resumed", False):
        # a post-mortem read on a RESUMED driver leads with the restart
        # context: the driver.restart / node.reattach / gcs.snapshot
        # chain explains why the subject's history starts mid-life
        rows, _tot = rt.cluster_events.query(
            types=["driver.restart", "node.reattach", "gcs.snapshot"],
            limit=50)
        bundle["driver_recovery"] = {
            "incarnation": rt.incarnation,
            "persistence": rt.persistence_stats(),
            "events": rows,
        }
    return bundle


def write_post_mortem(subject_id: str,
                      path: Optional[str] = None) -> str:
    """Build and write the bundle; returns the path."""
    import json
    bundle = build_post_mortem(subject_id)
    path = path or f"post-mortem-{subject_id}.json"
    with open(path, "w") as f:
        json.dump(bundle, f, indent=1, default=str)
    return path
