"""Build-on-first-import for the native components.

Compiles <name>.cc into build/lib<name>.so with g++ (cached by source
mtime; atomic rename so concurrently-importing worker processes never see
a half-written library).
"""
from __future__ import annotations

import os
import subprocess
import tempfile

_HERE = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_HERE, "build")


def build_library(name: str) -> str:
    """Return the path to lib<name>.so, compiling if stale or missing."""
    src = os.path.join(_HERE, f"{name}.cc")
    out = os.path.join(_BUILD_DIR, f"lib{name}.so")
    if (os.path.exists(out)
            and os.path.getmtime(out) >= os.path.getmtime(src)):
        return out
    os.makedirs(_BUILD_DIR, exist_ok=True)
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_BUILD_DIR)
    os.close(fd)
    try:
        subprocess.run(
            ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", tmp,
             src, "-lpthread", "-lrt"],
            check=True, capture_output=True, text=True, timeout=120)
        os.replace(tmp, out)   # atomic: racers overwrite with identical .so
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return out
