// Shared-memory object arena — the plasma-store equivalent for ray_tpu.
//
// Reference parity: src/ray/object_manager/plasma/{store.cc,eviction_policy.cc}
// (create/seal/get/release/delete, refcounts, LRU eviction). Re-designed for
// a single-host multi-process runtime: one POSIX shm segment holds a header,
// a fixed open-addressing object table, and a data region managed by a
// first-fit free list with offset-based links (all state is position-
// independent so every process can mmap at a different address). A
// process-shared *robust* pthread mutex serializes mutations — a worker
// dying mid-operation leaves the lock recoverable (EOWNERDEAD).
//
// Exposed as a flat C ABI for ctypes (no pybind11 in the image).
//
// Zero-copy contract with Python: create() returns an offset into the
// mapping; the caller packs serialized bytes directly into base+offset and
// then seal()s. get() pins (refcount++) and returns the offset; numpy
// arrays built over that memory alias shared pages until release().

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x52545055414e4101ull;  // "RTPUANA\x01"
constexpr uint32_t kNumSlots = 1 << 16;
constexpr uint64_t kAlign = 64;
constexpr uint32_t kIdLen = 47;  // + NUL -> 48-byte field

// Object states.
enum : uint32_t { kFree = 0, kCreated = 1, kSealed = 2, kDeletePending = 3 };

constexpr uint32_t kNilIdx = 0xffffffffu;

struct Entry {
  char id[kIdLen + 1];
  uint64_t offset;      // into data region
  uint64_t size;        // object payload size (what readers see)
  uint64_t alloc_size;  // actual bytes taken from the allocator
  int64_t refcount;
  uint32_t state;
  uint32_t probe;    // nonzero if slot ever used (tombstone-aware probing)
  // Intrusive LRU list over *evictable* entries (sealed, refcount==0):
  // head = most recent. Pinning removes; sealing/unpinning pushes front.
  uint32_t in_lru;
  uint32_t lru_prev;
  uint32_t lru_next;
};

struct FreeBlock {   // lives at the start of each free data block
  uint64_t size;
  uint64_t next;     // data-region offset of next free block; ~0ull = none
};
constexpr uint64_t kNil = ~0ull;

struct Header {
  uint64_t magic;
  uint64_t total_bytes;    // whole mapping
  uint64_t data_off;       // start of data region (from base)
  uint64_t data_size;
  uint64_t used;           // allocated bytes in data region
  uint64_t free_head;      // data-region offset of first free block
  uint32_t lru_head;       // slot index of most-recently-used evictable
  uint32_t lru_tail;       // slot index of least-recently-used evictable
  uint32_t n_slots;
  uint32_t n_objects;
  pthread_mutex_t mutex;
};

struct Arena {
  uint8_t* base;
  uint64_t total;
  int is_owner;
  char name[128];
};

inline Header* header(Arena* a) { return reinterpret_cast<Header*>(a->base); }
inline Entry* table(Arena* a) {
  return reinterpret_cast<Entry*>(a->base + sizeof(Header));
}
inline uint8_t* data(Arena* a) { return a->base + header(a)->data_off; }

uint64_t align_up(uint64_t v, uint64_t a) { return (v + a - 1) & ~(a - 1); }

uint64_t fnv1a(const char* s) {
  uint64_t h = 1469598103934665603ull;
  for (; *s; ++s) {
    h ^= static_cast<uint8_t>(*s);
    h *= 1099511628211ull;
  }
  return h;
}

class Locker {  // RAII over the robust process-shared mutex
 public:
  explicit Locker(Header* h) : h_(h) {
    int rc = pthread_mutex_lock(&h_->mutex);
    if (rc == EOWNERDEAD) pthread_mutex_consistent(&h_->mutex);
  }
  ~Locker() { pthread_mutex_unlock(&h_->mutex); }

 private:
  Header* h_;
};

Entry* find(Arena* a, const char* id) {
  Header* h = header(a);
  Entry* t = table(a);
  uint64_t slot = fnv1a(id) % h->n_slots;
  for (uint32_t i = 0; i < h->n_slots; ++i) {
    Entry* e = &t[(slot + i) % h->n_slots];
    if (e->state == kFree && !e->probe) return nullptr;  // never-used slot
    if (e->state != kFree && strncmp(e->id, id, kIdLen) == 0) return e;
  }
  return nullptr;
}

Entry* find_empty(Arena* a, const char* id) {
  Header* h = header(a);
  Entry* t = table(a);
  uint64_t slot = fnv1a(id) % h->n_slots;
  for (uint32_t i = 0; i < h->n_slots; ++i) {
    Entry* e = &t[(slot + i) % h->n_slots];
    if (e->state == kFree) return e;
  }
  return nullptr;
}

// -- free-list allocator (offsets into the data region) ----------------------

// First-fit. Fills *actual with the bytes really taken (aligned request,
// plus any absorbed sliver) — the caller must pass the same value back to
// fl_free so accounting and coalescing stay exact.
uint64_t fl_alloc(Arena* a, uint64_t size, uint64_t* actual) {
  Header* h = header(a);
  size = align_up(size ? size : 1, kAlign);
  uint64_t prev = kNil, cur = h->free_head;
  while (cur != kNil) {
    FreeBlock* b = reinterpret_cast<FreeBlock*>(data(a) + cur);
    if (b->size >= size) {
      uint64_t remaining = b->size - size;
      uint64_t next = b->next;
      if (remaining >= sizeof(FreeBlock) + kAlign) {
        uint64_t tail = cur + size;
        FreeBlock* nb = reinterpret_cast<FreeBlock*>(data(a) + tail);
        nb->size = remaining;
        nb->next = next;
        next = tail;
      } else {
        size = b->size;  // absorb the sliver
      }
      if (prev == kNil) h->free_head = next;
      else reinterpret_cast<FreeBlock*>(data(a) + prev)->next = next;
      h->used += size;
      *actual = size;
      return cur;
    }
    prev = cur;
    cur = b->next;
  }
  return kNil;
}

void fl_free(Arena* a, uint64_t off, uint64_t size) {
  Header* h = header(a);
  size = align_up(size ? size : 1, kAlign);
  h->used -= size;
  // insert sorted by offset, coalescing with neighbors
  uint64_t prev = kNil, cur = h->free_head;
  while (cur != kNil && cur < off) {
    prev = cur;
    cur = reinterpret_cast<FreeBlock*>(data(a) + cur)->next;
  }
  FreeBlock* nb = reinterpret_cast<FreeBlock*>(data(a) + off);
  nb->size = size;
  nb->next = cur;
  if (cur != kNil && off + size == cur) {  // merge with next
    FreeBlock* cb = reinterpret_cast<FreeBlock*>(data(a) + cur);
    nb->size += cb->size;
    nb->next = cb->next;
  }
  if (prev != kNil) {
    FreeBlock* pb = reinterpret_cast<FreeBlock*>(data(a) + prev);
    if (prev + pb->size == off) {  // merge with prev
      pb->size += nb->size;
      pb->next = nb->next;
      return;
    }
    pb->next = off;
  } else {
    h->free_head = off;
  }
}

// -- LRU list over evictable entries (O(1) victim selection, the role of
// -- the plasma reference's eviction_policy.cc) ------------------------------

inline uint32_t slot_of(Arena* a, Entry* e) {
  return static_cast<uint32_t>(e - table(a));
}

void lru_remove(Arena* a, Entry* e) {
  if (!e->in_lru) return;
  Header* h = header(a);
  Entry* t = table(a);
  if (e->lru_prev != kNilIdx) t[e->lru_prev].lru_next = e->lru_next;
  else h->lru_head = e->lru_next;
  if (e->lru_next != kNilIdx) t[e->lru_next].lru_prev = e->lru_prev;
  else h->lru_tail = e->lru_prev;
  e->in_lru = 0;
  e->lru_prev = e->lru_next = kNilIdx;
}

void lru_push_front(Arena* a, Entry* e) {
  if (e->in_lru) return;
  Header* h = header(a);
  Entry* t = table(a);
  e->lru_prev = kNilIdx;
  e->lru_next = h->lru_head;
  if (h->lru_head != kNilIdx) t[h->lru_head].lru_prev = slot_of(a, e);
  h->lru_head = slot_of(a, e);
  if (h->lru_tail == kNilIdx) h->lru_tail = h->lru_head;
  e->in_lru = 1;
}

void free_entry(Arena* a, Entry* e) {
  Header* h = header(a);
  lru_remove(a, e);
  fl_free(a, e->offset, e->alloc_size);
  e->state = kFree;  // probe stays set: tombstone for open addressing
  e->id[0] = '\0';
  h->n_objects--;
}

// Allocate, evicting from the LRU tail (retrying after each eviction so
// coalescing gets a chance to defragment). Returns the allocated offset or
// kNil when eviction can't help; fills *actual for the eventual fl_free.
uint64_t alloc_with_eviction(Arena* a, uint64_t size, uint64_t* actual) {
  Header* h = header(a);
  uint64_t off = fl_alloc(a, size, actual);
  while (off == kNil) {
    if (h->lru_tail == kNilIdx) return kNil;
    free_entry(a, &table(a)[h->lru_tail]);
    off = fl_alloc(a, size, actual);
  }
  return off;
}

}  // namespace

extern "C" {

Arena* rtpu_arena_create(const char* name, uint64_t capacity, int is_owner) {
  uint64_t table_bytes = sizeof(Entry) * static_cast<uint64_t>(kNumSlots);
  uint64_t data_off = align_up(sizeof(Header) + table_bytes, 4096);
  uint64_t total = data_off + align_up(capacity, 4096);

  int fd;
  if (is_owner) {
    shm_unlink(name);  // stale segment from a crashed run
    fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
    if (fd < 0) return nullptr;
    if (ftruncate(fd, static_cast<off_t>(total)) != 0) {
      close(fd);
      shm_unlink(name);
      return nullptr;
    }
  } else {
    fd = shm_open(name, O_RDWR, 0600);
    if (fd < 0) return nullptr;
    struct stat st;
    if (fstat(fd, &st) != 0) {
      close(fd);
      return nullptr;
    }
    total = static_cast<uint64_t>(st.st_size);
  }

  void* mem = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;

  Arena* a = new Arena;
  a->base = static_cast<uint8_t*>(mem);
  a->total = total;
  a->is_owner = is_owner;
  snprintf(a->name, sizeof(a->name), "%s", name);

  if (is_owner) {
    Header* h = header(a);
    memset(h, 0, sizeof(Header));
    memset(table(a), 0, table_bytes);
    h->magic = kMagic;
    h->total_bytes = total;
    h->data_off = data_off;
    h->data_size = total - data_off;
    h->used = 0;
    h->lru_head = kNilIdx;
    h->lru_tail = kNilIdx;
    h->n_slots = kNumSlots;
    h->n_objects = 0;
    FreeBlock* fb = reinterpret_cast<FreeBlock*>(data(a));
    fb->size = h->data_size;
    fb->next = kNil;
    h->free_head = 0;

    pthread_mutexattr_t attr;
    pthread_mutexattr_init(&attr);
    pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
    pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
    pthread_mutex_init(&h->mutex, &attr);
    pthread_mutexattr_destroy(&attr);
  } else if (header(a)->magic != kMagic) {
    munmap(mem, total);
    delete a;
    return nullptr;
  }
  return a;
}

void rtpu_arena_close(Arena* a, int unlink_seg) {
  if (!a) return;
  munmap(a->base, a->total);
  if (unlink_seg) shm_unlink(a->name);
  delete a;
}

// Unlink the segment name without unmapping: live zero-copy readers keep
// their pages; the kernel reclaims memory when the last process unmaps
// (i.e. at exit).
void rtpu_arena_unlink(Arena* a) {
  if (a) shm_unlink(a->name);
}

uint8_t* rtpu_arena_base(Arena* a) { return a->base + header(a)->data_off; }

// Returns data-region offset of a writable (unsealed) object, or:
//   -1 out of memory (even after eviction), -2 id already exists,
//   -3 object table full.
int64_t rtpu_arena_create_object(Arena* a, const char* id, uint64_t size) {
  Header* h = header(a);
  Locker lock(h);
  if (find(a, id)) return -2;
  Entry* e = find_empty(a, id);
  if (!e) return -3;
  uint64_t actual = 0;
  uint64_t off = alloc_with_eviction(a, size, &actual);
  if (off == kNil) return -1;
  snprintf(e->id, sizeof(e->id), "%s", id);
  e->offset = off;
  e->size = size;
  e->alloc_size = actual;
  e->refcount = 1;  // creator's write pin
  e->state = kCreated;
  e->probe = 1;
  e->in_lru = 0;
  e->lru_prev = e->lru_next = kNilIdx;
  h->n_objects++;
  return static_cast<int64_t>(off);
}

int rtpu_arena_seal(Arena* a, const char* id) {
  Locker lock(header(a));
  Entry* e = find(a, id);
  if (!e || e->state != kCreated) return -1;
  e->state = kSealed;
  e->refcount = 0;  // creator's write pin drops; readers pin via get
  lru_push_front(a, e);
  return 0;
}

// Pins the object (refcount++). Returns offset, fills *size; -1 if absent
// or unsealed.
int64_t rtpu_arena_get(Arena* a, const char* id, uint64_t* size) {
  Header* h = header(a);
  Locker lock(h);
  Entry* e = find(a, id);
  if (!e || e->state != kSealed) return -1;
  e->refcount++;
  lru_remove(a, e);  // pinned objects are not evictable
  if (size) *size = e->size;
  return static_cast<int64_t>(e->offset);
}

int rtpu_arena_release(Arena* a, const char* id) {
  Locker lock(header(a));
  Entry* e = find(a, id);
  if (!e) return -1;
  if (e->refcount > 0) e->refcount--;
  if (e->refcount <= 0) {
    if (e->state == kDeletePending) free_entry(a, e);
    else if (e->state == kSealed) lru_push_front(a, e);
  }
  return 0;
}

// Frees now if unpinned, else defers to the last release.
int rtpu_arena_delete(Arena* a, const char* id) {
  Locker lock(header(a));
  Entry* e = find(a, id);
  if (!e) return -1;
  if (e->refcount <= 0) free_entry(a, e);
  else e->state = kDeletePending;
  return 0;
}

int rtpu_arena_contains(Arena* a, const char* id) {
  Locker lock(header(a));
  Entry* e = find(a, id);
  return e && e->state == kSealed;
}

uint64_t rtpu_arena_used(Arena* a) {
  Locker lock(header(a));
  return header(a)->used;
}

uint64_t rtpu_arena_capacity(Arena* a) { return header(a)->data_size; }

uint32_t rtpu_arena_count(Arena* a) {
  Locker lock(header(a));
  return header(a)->n_objects;
}

}  // extern "C"
