// ray_tpu cross-language C++ task/actor API (header-only).
//
// Reference parity: ray.cross_language / the Ray C++ worker API
// (reference: python/ray/cross_language.py, cpp/include/ray/api.h) lets a
// Python driver invoke functions and actors implemented in C++.  Ray runs
// them in a dedicated C++ worker binary speaking the raylet gRPC protocol;
// in ray_tpu's single-controller runtime the TPU-first redesign is
// IN-PROCESS: a user shared library is dlopen()ed inside the (Python)
// worker that the scheduler already placed, and invoked through the stable
// C ABI below.  No extra process hop, no second wire protocol; arguments
// make one encode into a compact wire buffer whose array payloads C++
// reads in place (borrowed, copy-on-misalignment).
//
// User model:
//
//   #include "cross_lang.hpp"
//   static xl::Value add(const std::vector<xl::Value>& a) {
//     return xl::Value(a.at(0).as_int() + a.at(1).as_int());
//   }
//   XL_FUNC(add)
//
//   struct Counter : xl::Actor {
//     long long n = 0;
//     explicit Counter(const std::vector<xl::Value>& a) {
//       if (!a.empty()) n = a[0].as_int();
//     }
//     xl::Value call(const std::string& m,
//                    const std::vector<xl::Value>& a) override {
//       if (m == "inc") { n += a.empty() ? 1 : a[0].as_int(); return xl::Value(n); }
//       if (m == "get") return xl::Value(n);
//       throw std::runtime_error("Counter: unknown method " + m);
//     }
//   };
//   XL_ACTOR(Counter)
//
//   XL_MODULE()   // exactly once per shared library: emits the C ABI
//
// Build:  g++ -O2 -std=c++17 -shared -fPIC -I <ray_tpu/_native> mylib.cc -o libmy.so
// Call from Python:  f = ray_tpu.cross_language.cpp_function("libmy.so", "add")
//                    ray_tpu.get(f.remote(2, 3))  # -> 5
//
// Wire format (shared with ray_tpu/cross_language.py, little-endian):
//   value := tag payload
//     'N'                         nil
//     'T' / 'F'                   bool
//     'i' int64                   integer
//     'd' float64                 float
//     's' u32 len + utf-8 bytes   str
//     'b' u32 len + raw bytes     bytes
//     'l' u32 count + value*      list/tuple
//     'm' u32 count + (value value)*   dict
//     'a' u8 dtype, u8 ndim, u64 shape[ndim], raw C-order data   ndarray
//   dtype codes: 1=f32 2=f64 3=i8 4=i32 5=i64 6=u8 7=u32 8=u64 9=bool
#pragma once

#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <functional>
#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <utility>
#include <vector>

namespace xl {

static_assert(sizeof(double) == 8, "xl wire format needs 64-bit doubles");

enum class Kind : uint8_t { Nil, Bool, Int, Float, Str, Bytes, List, Map, Array };

enum class DType : uint8_t {
  F32 = 1, F64 = 2, I8 = 3, I32 = 4, I64 = 5, U8 = 6, U32 = 7, U64 = 8, Bool = 9,
};

inline size_t dtype_itemsize(DType d) {
  switch (d) {
    case DType::F32: case DType::I32: case DType::U32: return 4;
    case DType::F64: case DType::I64: case DType::U64: return 8;
    default: return 1;
  }
}

struct Value;
using List = std::vector<Value>;
using MapItems = std::vector<std::pair<Value, Value>>;

// N-dimensional array. `data` may BORROW the request buffer (valid for the
// duration of the call) or OWN a copy (`owned` non-empty).  Returning a
// borrowed array from a function is fine: encode() copies it to the wire.
struct NdArray {
  DType dtype = DType::F64;
  std::vector<uint64_t> shape;
  const uint8_t* data = nullptr;
  std::vector<uint8_t> owned;

  size_t size() const {
    size_t n = 1;
    for (uint64_t d : shape) n *= static_cast<size_t>(d);
    return n;
  }
  size_t nbytes() const { return size() * dtype_itemsize(dtype); }
  const uint8_t* ptr() const { return owned.empty() ? data : owned.data(); }

  template <typename T> const T* as() const {
    return reinterpret_cast<const T*>(ptr());
  }
  template <typename T> static NdArray make(DType dt, std::vector<uint64_t> shp,
                                            const T* src = nullptr) {
    NdArray a;
    a.dtype = dt;
    a.shape = std::move(shp);
    a.owned.resize(a.nbytes());
    if (src) std::memcpy(a.owned.data(), src, a.nbytes());
    return a;
  }
  template <typename T> T* mutable_data() {
    return reinterpret_cast<T*>(owned.data());
  }
};

struct Value {
  Kind kind = Kind::Nil;
  bool b = false;
  int64_t i = 0;
  double d = 0.0;
  std::string s;       // Str and Bytes both live here
  List list;
  MapItems map;
  NdArray arr;

  Value() = default;
  explicit Value(bool v) : kind(Kind::Bool), b(v) {}
  explicit Value(int64_t v) : kind(Kind::Int), i(v) {}
  explicit Value(int v) : kind(Kind::Int), i(v) {}
  explicit Value(double v) : kind(Kind::Float), d(v) {}
  explicit Value(const char* v) : kind(Kind::Str), s(v) {}
  explicit Value(std::string v) : kind(Kind::Str), s(std::move(v)) {}
  explicit Value(List v) : kind(Kind::List), list(std::move(v)) {}
  explicit Value(MapItems v) : kind(Kind::Map), map(std::move(v)) {}
  explicit Value(NdArray v) : kind(Kind::Array), arr(std::move(v)) {}

  static Value bytes(std::string v) {
    Value out;
    out.kind = Kind::Bytes;
    out.s = std::move(v);
    return out;
  }

  bool is_nil() const { return kind == Kind::Nil; }
  bool as_bool() const { require(Kind::Bool, "bool"); return b; }
  int64_t as_int() const {
    if (kind == Kind::Float) return static_cast<int64_t>(d);
    require(Kind::Int, "int");
    return i;
  }
  double as_float() const {
    if (kind == Kind::Int) return static_cast<double>(i);
    require(Kind::Float, "float");
    return d;
  }
  const std::string& as_str() const { require(Kind::Str, "str"); return s; }
  const std::string& as_bytes() const { require(Kind::Bytes, "bytes"); return s; }
  const List& as_list() const { require(Kind::List, "list"); return list; }
  const MapItems& as_map() const { require(Kind::Map, "map"); return map; }
  const NdArray& as_array() const { require(Kind::Array, "ndarray"); return arr; }

  const Value* find(const std::string& key) const {
    require(Kind::Map, "map");
    for (const auto& kv : map)
      if (kv.first.kind == Kind::Str && kv.first.s == key) return &kv.second;
    return nullptr;
  }

 private:
  void require(Kind k, const char* what) const {
    if (kind != k)
      throw std::runtime_error(std::string("xl::Value: expected ") + what +
                               ", got kind " + std::to_string(int(kind)));
  }
};

// ---------------------------------------------------------------- encoding

inline void _put_u32(std::vector<uint8_t>& out, uint32_t v) {
  out.push_back(v & 0xff); out.push_back((v >> 8) & 0xff);
  out.push_back((v >> 16) & 0xff); out.push_back((v >> 24) & 0xff);
}
inline uint32_t _checked_len(size_t n, const char* what) {
  if (n > 0xffffffffull)
    throw std::runtime_error(std::string(what) +
                             " exceeds the u32 wire length limit");
  return static_cast<uint32_t>(n);
}
inline void _put_u64(std::vector<uint8_t>& out, uint64_t v) {
  for (int k = 0; k < 8; ++k) out.push_back((v >> (8 * k)) & 0xff);
}

inline void encode_into(const Value& v, std::vector<uint8_t>& out) {
  switch (v.kind) {
    case Kind::Nil: out.push_back('N'); break;
    case Kind::Bool: out.push_back(v.b ? 'T' : 'F'); break;
    case Kind::Int: {
      out.push_back('i');
      uint64_t u; std::memcpy(&u, &v.i, 8); _put_u64(out, u);
      break;
    }
    case Kind::Float: {
      out.push_back('d');
      uint64_t u; std::memcpy(&u, &v.d, 8); _put_u64(out, u);
      break;
    }
    case Kind::Str: case Kind::Bytes: {
      out.push_back(v.kind == Kind::Str ? 's' : 'b');
      _put_u32(out, _checked_len(v.s.size(), "str/bytes"));
      out.insert(out.end(), v.s.begin(), v.s.end());
      break;
    }
    case Kind::List: {
      out.push_back('l');
      _put_u32(out, _checked_len(v.list.size(), "list"));
      for (const Value& it : v.list) encode_into(it, out);
      break;
    }
    case Kind::Map: {
      out.push_back('m');
      _put_u32(out, _checked_len(v.map.size(), "map"));
      for (const auto& kv : v.map) {
        encode_into(kv.first, out);
        encode_into(kv.second, out);
      }
      break;
    }
    case Kind::Array: {
      out.push_back('a');
      out.push_back(static_cast<uint8_t>(v.arr.dtype));
      out.push_back(static_cast<uint8_t>(v.arr.shape.size()));
      for (uint64_t dim : v.arr.shape) _put_u64(out, dim);
      const uint8_t* p = v.arr.ptr();
      out.insert(out.end(), p, p + v.arr.nbytes());
      break;
    }
  }
}

inline std::vector<uint8_t> encode(const Value& v) {
  std::vector<uint8_t> out;
  encode_into(v, out);
  return out;
}

// ---------------------------------------------------------------- decoding

struct Cursor {
  const uint8_t* p;
  const uint8_t* end;
  uint8_t u8() {
    if (p >= end) throw std::runtime_error("xl decode: truncated");
    return *p++;
  }
  uint32_t u32() {
    if (end - p < 4) throw std::runtime_error("xl decode: truncated");
    uint32_t v = p[0] | (p[1] << 8) | (p[2] << 16) | (uint32_t(p[3]) << 24);
    p += 4;
    return v;
  }
  uint64_t u64() {
    if (end - p < 8) throw std::runtime_error("xl decode: truncated");
    uint64_t v = 0;
    for (int k = 0; k < 8; ++k) v |= uint64_t(p[k]) << (8 * k);
    p += 8;
    return v;
  }
  const uint8_t* raw(size_t n) {
    if (static_cast<size_t>(end - p) < n)
      throw std::runtime_error("xl decode: truncated");
    const uint8_t* q = p;
    p += n;
    return q;
  }
};

// Arrays borrow the input buffer: valid for the lifetime of the request.
inline Value decode_one(Cursor& c) {
  uint8_t tag = c.u8();
  switch (tag) {
    case 'N': return Value();
    case 'T': return Value(true);
    case 'F': return Value(false);
    case 'i': {
      uint64_t u = c.u64();
      int64_t i; std::memcpy(&i, &u, 8);
      return Value(i);
    }
    case 'd': {
      uint64_t u = c.u64();
      double d; std::memcpy(&d, &u, 8);
      return Value(d);
    }
    case 's': case 'b': {
      uint32_t n = c.u32();
      const uint8_t* q = c.raw(n);
      std::string s(reinterpret_cast<const char*>(q), n);
      return tag == 's' ? Value(std::move(s)) : Value::bytes(std::move(s));
    }
    case 'l': {
      uint32_t n = c.u32();
      List items;
      items.reserve(n);
      for (uint32_t k = 0; k < n; ++k) items.push_back(decode_one(c));
      return Value(std::move(items));
    }
    case 'm': {
      uint32_t n = c.u32();
      MapItems items;
      items.reserve(n);
      for (uint32_t k = 0; k < n; ++k) {
        Value key = decode_one(c);
        Value val = decode_one(c);
        items.emplace_back(std::move(key), std::move(val));
      }
      return Value(std::move(items));
    }
    case 'a': {
      NdArray a;
      a.dtype = static_cast<DType>(c.u8());
      uint8_t nd = c.u8();
      a.shape.resize(nd);
      for (uint8_t k = 0; k < nd; ++k) a.shape[k] = c.u64();
      const uint8_t* p = c.raw(a.nbytes());
      // Borrow only when the wire offset happens to be aligned for the
      // dtype; otherwise copy so NdArray::as<T>() typed loads are legal.
      if (reinterpret_cast<uintptr_t>(p) % dtype_itemsize(a.dtype) == 0) {
        a.data = p;
      } else {
        a.owned.assign(p, p + a.nbytes());
      }
      return Value(std::move(a));
    }
    default:
      throw std::runtime_error("xl decode: bad tag " + std::to_string(tag));
  }
}

inline Value decode(const uint8_t* buf, size_t len) {
  Cursor c{buf, buf + len};
  return decode_one(c);
}

// ---------------------------------------------------------------- registry

struct Actor {
  virtual ~Actor() = default;
  virtual Value call(const std::string& method,
                     const std::vector<Value>& args) = 0;
};

using Fn = std::function<Value(const std::vector<Value>&)>;
using ActorFactory =
    std::function<std::unique_ptr<Actor>(const std::vector<Value>&)>;

struct Registry {
  std::map<std::string, Fn> fns;
  std::map<std::string, ActorFactory> actors;
  static Registry& inst() {
    static Registry r;
    return r;
  }
};

}  // namespace xl

#define XL_FUNC(fn)                                                     \
  static const bool _xl_reg_fn_##fn =                                   \
      (xl::Registry::inst().fns[#fn] = (fn), true);

#define XL_FUNC_NAMED(name, fn)                                         \
  static const bool _xl_reg_fn_named_##fn =                             \
      (xl::Registry::inst().fns[name] = (fn), true);

#define XL_ACTOR(Cls)                                                   \
  static const bool _xl_reg_actor_##Cls =                               \
      (xl::Registry::inst().actors[#Cls] =                              \
           [](const std::vector<xl::Value>& a) {                        \
             return std::unique_ptr<xl::Actor>(new Cls(a));             \
           },                                                           \
       true);

// Emits the stable C ABI.  Use exactly once per shared library.
#define XL_MODULE()                                                     \
  extern "C" {                                                          \
  static int _xl_run(const char* what,                                  \
                     const std::function<xl::Value()>& body,            \
                     unsigned char** out, unsigned long long* out_len,  \
                     char** err) {                                      \
    try {                                                               \
      std::vector<uint8_t> enc = xl::encode(body());                   \
      *out = static_cast<unsigned char*>(std::malloc(enc.size()));     \
      if (!enc.empty()) std::memcpy(*out, enc.data(), enc.size());     \
      *out_len = enc.size();                                            \
      return 0;                                                         \
    } catch (const std::exception& e) {                                 \
      std::string msg = std::string(what) + ": " + e.what();            \
      *err = static_cast<char*>(std::malloc(msg.size() + 1));          \
      std::memcpy(*err, msg.c_str(), msg.size() + 1);                  \
      return 1;                                                         \
    }                                                                   \
  }                                                                     \
  static std::vector<xl::Value> _xl_args(const unsigned char* in,       \
                                         unsigned long long in_len) {   \
    xl::Value v = xl::decode(in, in_len);                               \
    return v.as_list();                                                 \
  }                                                                     \
  int xl_invoke(const char* name, const unsigned char* in,              \
                unsigned long long in_len, unsigned char** out,         \
                unsigned long long* out_len, char** err) {              \
    auto it = xl::Registry::inst().fns.find(name);                      \
    if (it == xl::Registry::inst().fns.end()) {                         \
      std::string msg = std::string("no cross-language function '") +   \
                        name + "' registered in this library";          \
      *err = static_cast<char*>(std::malloc(msg.size() + 1));          \
      std::memcpy(*err, msg.c_str(), msg.size() + 1);                  \
      return 2;                                                         \
    }                                                                   \
    return _xl_run(name, [&] { return it->second(_xl_args(in, in_len)); }, \
                   out, out_len, err);                                  \
  }                                                                     \
  void* xl_actor_new(const char* cls, const unsigned char* in,          \
                     unsigned long long in_len, char** err) {           \
    try {                                                               \
      auto it = xl::Registry::inst().actors.find(cls);                  \
      if (it == xl::Registry::inst().actors.end())                      \
        throw std::runtime_error(                                       \
            std::string("no cross-language actor class '") + cls +      \
            "' registered in this library");                            \
      return it->second(_xl_args(in, in_len)).release();                \
    } catch (const std::exception& e) {                                 \
      std::string msg = std::string(cls) + ": " + e.what();             \
      *err = static_cast<char*>(std::malloc(msg.size() + 1));          \
      std::memcpy(*err, msg.c_str(), msg.size() + 1);                  \
      return nullptr;                                                   \
    }                                                                   \
  }                                                                     \
  int xl_actor_invoke(void* handle, const char* method,                 \
                      const unsigned char* in, unsigned long long in_len, \
                      unsigned char** out, unsigned long long* out_len, \
                      char** err) {                                     \
    xl::Actor* a = static_cast<xl::Actor*>(handle);                     \
    return _xl_run(method,                                              \
                   [&] { return a->call(method, _xl_args(in, in_len)); }, \
                   out, out_len, err);                                  \
  }                                                                     \
  void xl_actor_del(void* handle) {                                     \
    delete static_cast<xl::Actor*>(handle);                             \
  }                                                                     \
  void xl_free(void* p) { std::free(p); }                               \
  const char* xl_manifest() {                                           \
    static std::string m = [] {                                         \
      std::string s;                                                    \
      for (const auto& kv : xl::Registry::inst().fns)                   \
        s += "fn " + kv.first + "\n";                                   \
      for (const auto& kv : xl::Registry::inst().actors)                \
        s += "actor " + kv.first + "\n";                                \
      return s;                                                         \
    }();                                                                \
    return m.c_str();                                                   \
  }                                                                     \
  }  /* extern "C" */
