"""Native (C++) runtime components, bound via ctypes.

The image has no pybind11, so each component ships a flat C ABI compiled
on first use (g++ -O2 -shared) and cached next to the source. See
store_binding.py for the object-store arena.
"""
