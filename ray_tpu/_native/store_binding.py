"""ctypes binding for the C++ shared-memory object arena.

NativeStore implements the same interface as core.object_store.ShmStore
(put_value/get_value/release/delete_segment/used_bytes/shutdown) but backs
large objects with the single C++ arena instead of one POSIX segment per
object: allocation, refcounts, and LRU eviction all happen in native code
under one process-shared lock (reference parity:
src/ray/object_manager/plasma/store.cc).

Arena discovery: the owner (driver) picks a segment name and exports it as
RAY_TPU_ARENA_NAME so spawned workers attach the same arena. Writes are
zero-copy (serialize directly into the mapping); reads pin the object and
hand numpy views over shared pages until release().
"""
from __future__ import annotations

import ctypes
import itertools
import os
import threading
from typing import Any

from .build import build_library
from ..util import knobs
from ..core import serialization
from ..core.object_store import INLINE_MAX, ObjectLocation
from ..exceptions import ObjectLostError, ObjectStoreFullError

# nonce for reseal-under-pin fallback names (see put_value)
_RESEAL_SEQ = itertools.count()

_ENV_NAME = "RAY_TPU_ARENA_NAME"


def _load_lib() -> ctypes.CDLL:
    lib = ctypes.CDLL(build_library("object_store"))
    lib.rtpu_arena_create.restype = ctypes.c_void_p
    lib.rtpu_arena_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                      ctypes.c_int]
    lib.rtpu_arena_close.restype = None
    lib.rtpu_arena_close.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.rtpu_arena_unlink.restype = None
    lib.rtpu_arena_unlink.argtypes = [ctypes.c_void_p]
    lib.rtpu_arena_base.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.rtpu_arena_base.argtypes = [ctypes.c_void_p]
    lib.rtpu_arena_create_object.restype = ctypes.c_int64
    lib.rtpu_arena_create_object.argtypes = [ctypes.c_void_p,
                                             ctypes.c_char_p,
                                             ctypes.c_uint64]
    lib.rtpu_arena_seal.restype = ctypes.c_int
    lib.rtpu_arena_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rtpu_arena_get.restype = ctypes.c_int64
    lib.rtpu_arena_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.POINTER(ctypes.c_uint64)]
    lib.rtpu_arena_release.restype = ctypes.c_int
    lib.rtpu_arena_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rtpu_arena_delete.restype = ctypes.c_int
    lib.rtpu_arena_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rtpu_arena_contains.restype = ctypes.c_int
    lib.rtpu_arena_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rtpu_arena_used.restype = ctypes.c_uint64
    lib.rtpu_arena_used.argtypes = [ctypes.c_void_p]
    lib.rtpu_arena_capacity.restype = ctypes.c_uint64
    lib.rtpu_arena_capacity.argtypes = [ctypes.c_void_p]
    lib.rtpu_arena_count.restype = ctypes.c_uint32
    lib.rtpu_arena_count.argtypes = [ctypes.c_void_p]
    return lib


_lib_lock = threading.Lock()
_lib: ctypes.CDLL | None = None


def get_lib() -> ctypes.CDLL:
    global _lib
    with _lib_lock:
        if _lib is None:
            _lib = _load_lib()
        return _lib


def _sweep_stale_arenas() -> None:
    """Unlink arenas whose owner pid is dead (a SIGKILLed/SIGTERMed
    driver never runs its atexit unlink, and a multi-GB /dev/shm segment
    would otherwise leak until reboot). Arena names embed the creator's
    pid: /rtpu_arena_<pid>_<hex>."""
    try:
        entries = os.listdir("/dev/shm")
    except OSError:
        return
    for fname in entries:
        if not fname.startswith("rtpu_arena_"):
            continue
        parts = fname.split("_")
        try:
            pid = int(parts[2])
        except (IndexError, ValueError):
            continue
        try:
            os.kill(pid, 0)
            continue  # owner alive: not ours to touch
        except ProcessLookupError:
            pass
        except OSError:
            continue
        try:
            os.unlink(os.path.join("/dev/shm", fname))
        except OSError:
            pass


class NativeStore:
    """Per-process view of the node's C++ shared-memory arena."""

    def __init__(self, capacity_bytes: int = 8 << 30,
                 is_owner: bool = False):
        self._lib = get_lib()
        self.capacity = capacity_bytes
        self.is_owner = is_owner
        if is_owner:
            _sweep_stale_arenas()
            name = f"/rtpu_arena_{os.getpid()}_{os.urandom(4).hex()}"
            os.environ[_ENV_NAME] = name
        else:
            name = knobs.get_str(_ENV_NAME, default="")
            if not name:
                raise RuntimeError(
                    "no arena to attach: RAY_TPU_ARENA_NAME unset "
                    "(driver store is not the native backend)")
        self._name = name
        self._handle = self._lib.rtpu_arena_create(
            name.encode(), capacity_bytes, 1 if is_owner else 0)
        if not self._handle:
            raise RuntimeError(f"failed to map arena {name}")
        base = self._lib.rtpu_arena_base(self._handle)
        cap = self._lib.rtpu_arena_capacity(self._handle)
        self._base_addr = ctypes.addressof(base.contents)
        # One memoryview over the whole data region; object views slice it.
        self._data = memoryview(
            (ctypes.c_uint8 * cap).from_address(self._base_addr)).cast("B")
        self._lock = threading.Lock()

    def _pinned_view(self, name: str, off: int, size: int) -> memoryview:
        """Zero-copy view of a gotten (refcount-pinned) object whose pin
        releases when the LAST derived view dies. The exporter is a
        per-call ctypes array over the mapped pages with a
        weakref.finalize dropping the refcount: numpy views built by
        serialization.unpack keep the exporter alive through the buffer
        chain. (A PEP 688 __buffer__ wrapper class would be neater, but
        plain classes only export buffers from Python 3.12 — this must
        run on 3.10.)"""
        import weakref  # noqa: PLC0415
        carr = (ctypes.c_uint8 * size).from_address(self._base_addr + off)
        weakref.finalize(carr, self._release_one, name)
        return memoryview(carr).cast("B")

    # -- write path ---------------------------------------------------------
    def put_value(self, oid: str, value: Any) -> ObjectLocation:
        meta, bufs = serialization.serialize(value)
        size = serialization.packed_size(meta, bufs)
        if size <= INLINE_MAX:
            return ObjectLocation(kind="inline", size=size,
                                  data=serialization.pack_parts(meta, bufs))
        name = oid
        off = self._lib.rtpu_arena_create_object(
            self._handle, name.encode(), size)
        if off == -2:
            # lineage re-execution resealing an oid whose stale segment
            # survives in this arena (same-node re-run after a loss, or
            # a rejoined host): drop the old copy (refcount-safe — a
            # pinned reader defers the free) and seal fresh
            self._lib.rtpu_arena_delete(self._handle, name.encode())
            off = self._lib.rtpu_arena_create_object(
                self._handle, name.encode(), size)
        if off == -2:
            # the stale entry is pin-held (delete pending): seal under a
            # nonce-suffixed name instead, like put_packed — the nonce
            # keeps REPEATED reseals of one oid from colliding with
            # their own earlier suffixed entries (those are unpinned
            # once read, so the arena LRU reclaims them)
            name = f"{oid}r{os.getpid():x}x{next(_RESEAL_SEQ)}"
            off = self._lib.rtpu_arena_create_object(
                self._handle, name.encode(), size)
        if off == -2:
            raise ValueError(f"object {oid} already exists in the arena")
        if off < 0:
            raise ObjectStoreFullError(
                f"object {oid} ({size} B) does not fit in the arena "
                f"({self.used_bytes()}/{self.capacity} B used, "
                f"nothing evictable)")
        try:
            serialization.pack_into(self._data[off:off + size], meta, bufs)
        except BaseException:
            self._lib.rtpu_arena_seal(self._handle, name.encode())
            self._lib.rtpu_arena_delete(self._handle, name.encode())
            raise
        self._lib.rtpu_arena_seal(self._handle, name.encode())
        from ..core.object_store import current_node_id  # noqa: PLC0415
        return ObjectLocation(kind="native", size=size, name=name,
                              node_id=current_node_id())

    # -- read path ----------------------------------------------------------
    def get_value(self, loc: ObjectLocation) -> Any:
        from ..core.object_store import record_read  # noqa: PLC0415
        if loc.kind == "inline":
            record_read("inline")
            return serialization.unpack(loc.data)
        if loc.kind == "spill":
            from ..core.object_store import _read_spill_loc  # noqa: PLC0415
            record_read("spill")
            return serialization.unpack(_read_spill_loc(loc))
        if loc.kind == "native":
            size = ctypes.c_uint64()
            off = self._lib.rtpu_arena_get(
                self._handle, loc.name.encode(), ctypes.byref(size))
            if off < 0:
                if loc.spill_path:
                    from ..core.object_store import \
                        _read_spill_loc  # noqa: PLC0415
                    record_read("spill")
                    return serialization.unpack(_read_spill_loc(loc))
                raise ObjectLostError(
                    f"object {loc.name} is gone from the arena (evicted?)")
            record_read("hit")
            # The pin (refcount) lives exactly as long as the deserialized
            # value: zero-copy numpy views keep the exporter alive through
            # the memoryview chain; when the last view dies, the finalizer
            # unpins and the object becomes evictable again. Values with
            # no out-of-band buffers drop the pin on return.
            return serialization.unpack(
                self._pinned_view(loc.name, off, size.value))
        if loc.kind == "shm":
            # A peer fell back to the pure-Python store; read its segment.
            return self._shm_fallback().get_value(loc)
        raise ObjectLostError(f"unknown location kind {loc.kind!r}")

    def get_bytes(self, loc: ObjectLocation) -> bytes:
        """Raw packed payload for cross-node transfer (copies out of the
        arena; the pin lives only for the copy)."""
        from ..core.object_store import record_read  # noqa: PLC0415
        if loc.kind == "inline":
            record_read("inline")
            return loc.data
        if loc.kind == "spill":
            from ..core.object_store import _read_spill_loc  # noqa: PLC0415
            record_read("spill")
            return _read_spill_loc(loc)
        if loc.kind == "native":
            size = ctypes.c_uint64()
            off = self._lib.rtpu_arena_get(
                self._handle, loc.name.encode(), ctypes.byref(size))
            if off < 0:
                if loc.spill_path:
                    from ..core.object_store import \
                        _read_spill_loc  # noqa: PLC0415
                    record_read("spill")
                    return _read_spill_loc(loc)
                raise ObjectLostError(
                    f"object {loc.name} is gone from the arena (evicted?)")
            record_read("hit")
            try:
                return bytes(self._data[off:off + size.value])
            finally:
                self._release_one(loc.name)
        if loc.kind == "shm":
            return self._shm_fallback().get_bytes(loc)
        raise ObjectLostError(f"unknown location kind {loc.kind!r}")

    def get_buffer(self, loc: ObjectLocation):
        """Packed payload as a buffer for the transfer plane: a pinned
        zero-copy arena view when resident (the holder streams straight
        out of shared memory), bytes otherwise (inline / spill)."""
        if loc.kind == "native":
            size = ctypes.c_uint64()
            off = self._lib.rtpu_arena_get(
                self._handle, loc.name.encode(), ctypes.byref(size))
            if off >= 0:
                from ..core.object_store import record_read  # noqa: PLC0415
                record_read("hit")
                return self._pinned_view(loc.name, off, size.value)
        return self.get_bytes(loc)

    def put_packed(self, oid: str, data: bytes) -> ObjectLocation:
        """Seal an already-packed payload (cross-node fetch re-hosting)."""
        size = len(data)
        if size <= INLINE_MAX:
            return ObjectLocation(kind="inline", size=size, data=data)
        # pid-suffixed (see ShmStore.put_packed): concurrent re-hosts
        # from different processes sharing this arena must not race one
        # unsealed entry
        key = f"{oid}c{os.getpid():x}"
        off = self._lib.rtpu_arena_create_object(
            self._handle, key.encode(), size)
        if off == -2:
            from ..core.object_store import current_node_id  # noqa: PLC0415
            return ObjectLocation(kind="native", size=size, name=key,
                                  node_id=current_node_id())
        if off < 0:
            raise ObjectStoreFullError(
                f"re-hosted object {oid} ({size} B) does not fit in the "
                f"arena")
        self._data[off:off + size] = data
        self._lib.rtpu_arena_seal(self._handle, key.encode())
        from ..core.object_store import current_node_id  # noqa: PLC0415
        return ObjectLocation(kind="native", size=size, name=key,
                              node_id=current_node_id())

    def _shm_fallback(self):
        if not hasattr(self, "_fallback"):
            from ..core.object_store import ShmStore  # noqa: PLC0415
            self._fallback = ShmStore(capacity_bytes=self.capacity,
                                      is_owner=self.is_owner)
        return self._fallback

    # -- lifecycle ----------------------------------------------------------
    def _release_one(self, name: str) -> None:
        if self._handle:
            self._lib.rtpu_arena_release(self._handle, name.encode())

    def release(self, name: str) -> None:
        """Pins are lifetime-managed (_Pin); explicit release is a no-op."""

    def delete_segment(self, name: str, size: int) -> None:
        if name.startswith("rtpu_"):
            # Segment written by a ShmStore-fallback peer.
            self._shm_fallback().delete_segment(name, size)
        else:
            self._lib.rtpu_arena_delete(self._handle, name.encode())

    def contains(self, name: str) -> bool:
        return bool(self._lib.rtpu_arena_contains(self._handle,
                                                  name.encode()))

    def used_bytes(self) -> int:
        return int(self._lib.rtpu_arena_used(self._handle))

    def num_objects(self) -> int:
        return int(self._lib.rtpu_arena_count(self._handle))

    def shutdown(self) -> None:
        if self._handle:
            # Readers may still hold zero-copy numpy views into the
            # mapping, so never munmap mid-process: unlink the name (owner)
            # and let the kernel reclaim pages at process exit.
            if self.is_owner:
                self._lib.rtpu_arena_unlink(self._handle)
            self._handle = None
        if self.is_owner:
            os.environ.pop(_ENV_NAME, None)
