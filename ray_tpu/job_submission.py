"""Job submission API (reference: python/ray/job_submission)."""
from .core.jobs import JobStatus, JobSubmissionClient

__all__ = ["JobStatus", "JobSubmissionClient"]
