"""A resumable driver job for the driver-fault-tolerance bench/tests.

Phase 1 (no --resume): init with a state dir, create a checkpointed
named progress actor, run `total` tasks feeding it, then (when killed
mid-loop by the parent) leave everything to the WAL. Phase 2
(--resume): init(resume=True), recover the progress actor from its
__ray_save__ checkpoint, run ONLY the missing indices, and assert every
index completed exactly once — the "zero lost work" contract.

Usage: driver_ft_job.py <state_dir> <progress_file> <total> [--resume]
"""
import sys

STATE_DIR, PROGRESS, TOTAL = sys.argv[1], sys.argv[2], int(sys.argv[3])
RESUME = "--resume" in sys.argv[4:]

import ray_tpu  # noqa: E402


@ray_tpu.remote
def work(i):
    return i


@ray_tpu.remote(name="dft-progress", checkpoint_interval_s=0)
class Progress:
    def __init__(self):
        self.done = {}

    def record(self, i):
        self.done[i] = self.done.get(i, 0) + 1
        return len(self.done)

    def snapshot(self):
        return dict(self.done)

    def __ray_save__(self):
        return {"done": self.done}

    def __ray_restore__(self, state):
        self.done = state["done"]


def main():
    rt = ray_tpu.init(num_cpus=2, state_dir=STATE_DIR,
                      resume=RESUME)
    if RESUME:
        acc = ray_tpu.get_actor("dft-progress", timeout=60)
        done = ray_tpu.get(acc.snapshot.remote(), timeout=60)
    else:
        acc = Progress.remote()
        done = {}
    todo = [i for i in range(TOTAL) if i not in done]
    for i in todo:
        v = ray_tpu.get(work.remote(i), timeout=60)
        ray_tpu.get(acc.record.remote(v), timeout=60)
        with open(PROGRESS, "a") as f:
            f.write(f"{i} ")
    final = ray_tpu.get(acc.snapshot.remote(), timeout=60)
    missing = [i for i in range(TOTAL) if i not in final]
    assert not missing, f"lost tasks: {missing}"
    print(f"JOB-COMPLETE total={len(final)} resumed={rt.resumed} "
          f"incarnation={rt.incarnation}", flush=True)
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
