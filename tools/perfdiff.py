"""perfdiff: compare two sets of BENCH_*.json and flag regressions.

The repo commits benchmark snapshots (BENCH_CORE.json, BENCH_DAG.json,
BENCH_OBS.json, ...) next to the code that produced them. This tool
turns those snapshots into a regression gate:

    python -m tools.perfdiff OLD_DIR NEW_DIR
    python -m tools.perfdiff --git-baseline [REV]      # baseline from git

Both BENCH shapes in the tree are understood: the wrapped form
(``{"ts", "phase", "command", "result": {...}}``) and the flat form
(BENCH_EVENTS.json). Every numeric leaf becomes a dotted metric path
(``result.noop_tasks_per_s`` flattens to ``noop_tasks_per_s`` — the
wrapper keys ts/phase/command are metadata, not metrics).

Direction is inferred from the metric name:

  higher-is-better   *per_s*, *throughput*, *speedup*, *steps_per*
  lower-is-better    *latency*, *overhead*, *stall*, *_seconds*, *_ms*,
                     *frames_per*, *msgs_per*
  percentage-point   *_pct (gated on absolute point delta, not ratio —
                     an overhead going 0.5% -> 2.6% is the regression,
                     not the 420% relative blowup)
  informational      everything else (shown, never gated)

Exit codes: 0 = within tolerance, 1 = regression, 2 = usage/IO error.
Used by tests/test_perfdiff.py to gate the committed BENCH files
against HEAD on every tier-1 run.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import subprocess
import sys
from typing import Dict, Iterable, List, Optional, Tuple

# wrapper metadata in the wrapped BENCH shape — never metrics
_META_KEYS = {"ts", "phase", "command", "note", "platform"}

_HIGHER = ("per_s", "throughput", "speedup", "steps_per", "calls_per")
_LOWER = ("latency", "overhead_s", "stall", "_seconds", "_ms",
          "frames_per", "msgs_per", "queued_s", "_bytes")


def classify(name: str) -> str:
    """'higher' | 'lower' | 'pct' | 'info' for a dotted metric path."""
    leaf = name.rsplit(".", 1)[-1]
    if leaf.endswith("_pct"):
        return "pct"
    if any(t in leaf for t in _HIGHER):
        return "higher"
    if any(t in leaf for t in _LOWER):
        return "lower"
    return "info"


def flatten(obj, prefix: str = "") -> Dict[str, float]:
    """Numeric leaves of a BENCH document as dotted paths. The wrapped
    shape's ``result`` layer is elided so the same benchmark compares
    across both shapes."""
    out: Dict[str, float] = {}
    if not isinstance(obj, dict):
        return out
    for key, val in obj.items():
        if not prefix and key in _META_KEYS:
            continue
        path = key if key == "result" and not prefix else (
            f"{prefix}.{key}" if prefix else key)
        if key == "result" and not prefix:
            out.update(flatten(val))
        elif isinstance(val, dict):
            out.update(flatten(val, path))
        elif isinstance(val, bool):
            continue
        elif isinstance(val, (int, float)):
            out[path] = float(val)
    return out


def compare(base: Dict[str, float], cur: Dict[str, float],
            tolerance_pct: float,
            per_metric: Optional[Dict[str, float]] = None
            ) -> List[Tuple[str, str, float, float, float, str]]:
    """[(metric, direction, base, cur, delta, verdict)] over the common
    metric set; verdict in {'ok', 'REGRESSED', 'info'}."""
    rows = []
    per_metric = per_metric or {}
    for name in sorted(set(base) & set(cur)):
        b, c = base[name], cur[name]
        kind = classify(name)
        tol = per_metric.get(name, tolerance_pct)
        if kind == "pct":
            # percentage-point metric: gate the absolute point delta
            delta = c - b
            verdict = "REGRESSED" if delta > tol else "ok"
        elif kind == "info" or abs(b) < 1e-12:
            delta = c - b
            verdict = "info"
        else:
            delta = (c - b) / abs(b) * 100.0
            if kind == "higher":
                verdict = "REGRESSED" if delta < -tol else "ok"
            else:
                verdict = "REGRESSED" if delta > tol else "ok"
        rows.append((name, kind, b, c, delta, verdict))
    return rows


def _load(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def _git_show(rev: str, relpath: str, repo: str) -> Optional[dict]:
    """File contents at `rev`, or None if it does not exist there (a
    brand-new benchmark has no baseline to regress against)."""
    proc = subprocess.run(
        ["git", "show", f"{rev}:{relpath}"], cwd=repo,
        capture_output=True, text=True)
    if proc.returncode != 0:
        return None
    try:
        return json.loads(proc.stdout)
    except json.JSONDecodeError:
        return None


def _pairs_from_dirs(old_dir: str, new_dir: str
                     ) -> Iterable[Tuple[str, dict, dict]]:
    if os.path.isfile(old_dir) and os.path.isfile(new_dir):
        yield os.path.basename(new_dir), _load(old_dir), _load(new_dir)
        return
    for new_path in sorted(glob.glob(os.path.join(new_dir,
                                                  "BENCH_*.json"))):
        fname = os.path.basename(new_path)
        old_path = os.path.join(old_dir, fname)
        if not os.path.isfile(old_path):
            print(f"perfdiff: {fname}: no baseline in {old_dir}, "
                  "skipped")
            continue
        yield fname, _load(old_path), _load(new_path)


def _pairs_from_git(rev: str, repo: str, files: List[str]
                    ) -> Iterable[Tuple[str, dict, dict]]:
    if not files:
        files = sorted(glob.glob(os.path.join(repo, "BENCH_*.json")))
    for path in files:
        rel = os.path.relpath(path, repo)
        base = _git_show(rev, rel, repo)
        if base is None:
            # not in the baseline rev: new benchmark, nothing to gate
            print(f"perfdiff: {rel}: not in {rev}, skipped")
            continue
        yield rel, base, _load(path)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="perfdiff",
        description="compare BENCH_*.json sets and flag regressions")
    p.add_argument("old", nargs="?",
                   help="baseline dir (or single file)")
    p.add_argument("new", nargs="?",
                   help="current dir (or single file)")
    p.add_argument("--git-baseline", nargs="?", const="HEAD",
                   default=None, metavar="REV",
                   help="take the baseline from this git rev "
                        "(default HEAD); positional args become the "
                        "files to check (default: repo BENCH_*.json)")
    p.add_argument("--tolerance", type=float, default=10.0,
                   help="allowed regression percent "
                        "(points for *_pct metrics); default 10")
    p.add_argument("--metric-tolerance", action="append", default=[],
                   metavar="NAME=PCT",
                   help="per-metric tolerance override (repeatable)")
    p.add_argument("--repo", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))),
        help=argparse.SUPPRESS)
    args = p.parse_args(argv)

    per_metric: Dict[str, float] = {}
    for spec in args.metric_tolerance:
        name, _, pct = spec.partition("=")
        try:
            per_metric[name] = float(pct)
        except ValueError:
            print(f"perfdiff: bad --metric-tolerance {spec!r}",
                  file=sys.stderr)
            return 2

    try:
        if args.git_baseline is not None:
            files = [f for f in (args.old, args.new) if f]
            pairs = list(_pairs_from_git(args.git_baseline, args.repo,
                                         files))
        elif args.old and args.new:
            pairs = list(_pairs_from_dirs(args.old, args.new))
        else:
            p.print_usage(sys.stderr)
            return 2
    except (OSError, json.JSONDecodeError) as e:
        print(f"perfdiff: {e}", file=sys.stderr)
        return 2

    regressed = 0
    compared = 0
    for fname, base_doc, cur_doc in pairs:
        rows = compare(flatten(base_doc), flatten(cur_doc),
                       args.tolerance, per_metric)
        if not rows:
            continue
        print(f"\n== {fname} ==")
        width = max(len(r[0]) for r in rows)
        for name, kind, b, c, delta, verdict in rows:
            unit = "pp" if kind == "pct" else (
                "%" if kind in ("higher", "lower") else "")
            mark = " <-- REGRESSION" if verdict == "REGRESSED" else ""
            print(f"  {name.ljust(width)}  {b:>12.4g} -> {c:>12.4g}  "
                  f"{delta:+8.2f}{unit or ' '} [{kind}]{mark}")
            if verdict == "REGRESSED":
                regressed += 1
            if verdict != "info":
                compared += 1
    if not pairs:
        print("perfdiff: nothing to compare", file=sys.stderr)
        return 2
    print(f"\nperfdiff: {compared} gated metrics, "
          f"{regressed} regression(s)")
    return 1 if regressed else 0


if __name__ == "__main__":
    sys.exit(main())
