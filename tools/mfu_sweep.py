#!/usr/bin/env python
"""MFU sweep for the flagship train step (SURVEY §6: ≥40% target).

Runs `bench.py --phase train-llama` under a grid of the knobs that move
MFU on one chip — gradient-accumulation depth, remat policy, batch size —
with SHORT measure windows, then re-runs the best configuration at full
length. Every TPU-completed child already snapshots its result into
BENCH_TPU.json (bench.py:_snapshot_write); this tool additionally writes
the ranked table to MFU_SWEEP.json so the best configuration is a
committed, reproducible artifact.

Run (holds the TPU tunnel for its duration):
    python tools/mfu_sweep.py
Driven automatically by tools/tpu_watcher.py after the baseline
train-llama capture.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "MFU_SWEEP.json")

# (accum, remat_policy, batch) — ordered so the expected-best configs run
# first (a budget kill still leaves the informative rows).
GRID = [
    (4, "dots", 8),     # the r4 default recipe
    (1, "dots", 8),     # no accum scan: fewer, larger steps
    (2, "dots", 8),
    (4, "dots", 16),    # bigger batch if HBM allows
    (4, "full", 8),     # cheaper memory, more recompute
    (4, "none", 4),     # no remat at reduced batch
]
SHORT_ENV = {"RAY_TPU_BENCH_STEPS": "8", "RAY_TPU_BENCH_WARMUP": "2"}
PER_RUN_TIMEOUT = float(os.environ.get("MFU_SWEEP_RUN_TIMEOUT", 900))
TOTAL_BUDGET = float(os.environ.get("MFU_SWEEP_BUDGET", 4500))


def run_cfg(accum: int, remat: str, batch: int, env_extra: dict,
            timeout_s: float) -> dict:
    env = dict(os.environ)
    env.update(env_extra)
    env["RAY_TPU_BENCH_ACCUM"] = str(accum)
    env["RAY_TPU_BENCH_REMAT_POLICY"] = remat
    env["RAY_TPU_BENCH_BATCH"] = str(batch)
    t0 = time.time()
    try:
        proc = subprocess.run(
            [sys.executable, "bench.py", "--phase", "train-llama"],
            cwd=REPO, env=env, capture_output=True, timeout=timeout_s)
        lines = proc.stdout.decode(errors="replace").strip().splitlines()
        rec = json.loads(lines[-1]) if lines else {}
        if proc.returncode != 0 or not isinstance(rec, dict):
            rec = {"error": f"rc={proc.returncode}",
                   "tail": proc.stderr.decode(errors="replace")[-400:]}
    except subprocess.TimeoutExpired:
        rec = {"error": f"timeout {timeout_s:.0f}s"}
    except (ValueError, json.JSONDecodeError) as e:
        rec = {"error": f"unparseable output: {e!r}"}
    rec.update({"accum": accum, "remat": remat, "batch_cfg": batch,
                "wall_s": round(time.time() - t0, 1)})
    return rec


def main() -> None:
    t_start = time.time()
    rows = []
    for accum, remat, batch in GRID:
        if time.time() - t_start > TOTAL_BUDGET - PER_RUN_TIMEOUT:
            rows.append({"accum": accum, "remat": remat,
                         "batch_cfg": batch, "skipped": "budget"})
            continue
        print(f"[mfu-sweep] accum={accum} remat={remat} batch={batch}",
              flush=True)
        rec = run_cfg(accum, remat, batch, SHORT_ENV, PER_RUN_TIMEOUT)
        print(f"[mfu-sweep]   -> mfu={rec.get('mfu')} "
              f"tok/s={rec.get('tokens_per_s')} err={rec.get('error')}",
              flush=True)
        rows.append(rec)
        _write(rows, final=None)
    scored = [r for r in rows
              if isinstance(r.get("mfu"), (int, float))
              and r.get("platform") == "tpu"]
    final = None
    if scored:
        best = max(scored, key=lambda r: r["mfu"])
        print(f"[mfu-sweep] best short-run: {best['mfu']:.3f} "
              f"(accum={best['accum']} remat={best['remat']} "
              f"batch={best['batch_cfg']}); re-running full-length",
              flush=True)
        remaining = TOTAL_BUDGET - (time.time() - t_start)
        final = run_cfg(best["accum"], best["remat"], best["batch_cfg"],
                        {}, max(PER_RUN_TIMEOUT, min(remaining, 1800)))
        print(f"[mfu-sweep] full-length best: mfu={final.get('mfu')}",
              flush=True)
    _write(rows, final)


def _write(rows, final) -> None:
    with open(OUT, "w") as f:
        json.dump({"ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
                   "short_runs": rows, "best_full": final}, f, indent=1)


if __name__ == "__main__":
    main()
