"""Check registry. Adding a check: implement the Check protocol
(`code`, `name`, `summary`, `applies(rel)`, `run(unit, project)`) in a
module here, append an instance to ALL_CHECKS, document it in
docs/STATIC_ANALYSIS.md, and add violation + clean fixtures to
tests/test_raylint.py."""
from __future__ import annotations

from typing import List, Optional, Sequence

from .blocking import RT003UnboundedBlocking
from .knobs import RT005UndeclaredEnvKnob
from .locks import RT001BlockingUnderLock, RT002LockOrderInversion
from .telemetry import RT004UncatalogedTelemetry

ALL_CHECKS = [
    RT001BlockingUnderLock(),
    RT002LockOrderInversion(),
    RT003UnboundedBlocking(),
    RT004UncatalogedTelemetry(),
    RT005UndeclaredEnvKnob(),
]


def check_by_code(code: str):
    for c in ALL_CHECKS:
        if c.code == code.upper():
            return c
    raise KeyError(f"unknown check {code!r}; known: "
                   + ", ".join(c.code for c in ALL_CHECKS))


def select_checks(select: Optional[Sequence[str]] = None,
                  disable: Optional[Sequence[str]] = None) -> List:
    checks = list(ALL_CHECKS)
    if select:
        wanted = {c.upper() for c in select}
        unknown = wanted - {c.code for c in ALL_CHECKS}
        if unknown:
            raise KeyError(f"unknown check(s): {sorted(unknown)}")
        checks = [c for c in checks if c.code in wanted]
    if disable:
        off = {c.upper() for c in disable}
        unknown = off - {c.code for c in ALL_CHECKS}
        if unknown:
            raise KeyError(f"unknown check(s): {sorted(unknown)}")
        checks = [c for c in checks if c.code not in off]
    return checks
