"""RT003 unbounded-blocking-primitive.

The PR 11 gang-starvation class: a dispatcher/worker/supervisor loop
parked on a primitive with no timeout can never notice that the peer it
waits for is dead — the thread survives every death determination,
holds its resources, and the failure surfaces minutes later (or never)
as a wedged loop instead of a typed error. Inside `while` loops in
control-plane modules raylint flags:

  * `ev.wait()` with no timeout — a dead setter parks the loop forever;
  * `q.get()` with no timeout on a queue-ish receiver — a dead
    producer parks the loop forever (`put` is not flagged here: the
    control-plane inboxes are unbounded, so puts cannot park; a put
    under a LOCK is RT001's business);
  * `sock.recv(...)` / `read_frame(sock)` in a function that never
    arms `settimeout` — a half-open TCP peer (the classic silent
    preemption) blocks the read loop indefinitely.

`async def` bodies are exempt: awaited queue gets park a task, not a
thread, and asyncio primitives take no timeout kwarg (`wait_for` is
the bounding idiom there). Shutdown-path waits (a joining thread known
to exit) are the common legitimate exception — suppress those inline
with the reason.
"""
from __future__ import annotations

import ast
from typing import List, Optional

from ..engine import FileUnit, Finding, Project
from .common import call_attr, dotted, has_kwarg, receiver, terminal_name

_QUEUE_HINT = ("queue", "inbox", "outbox", "mailbox")
_SOCK_HINT = ("sock", "conn")
_RECV_FUNCS = {"read_exact", "read_frame", "read_obj"}


def _is_queueish(node: Optional[ast.AST]) -> bool:
    if node is None:
        return False
    t = terminal_name(node).lower()
    return (t == "q" or t.endswith("_q")
            or any(h in t for h in _QUEUE_HINT))


def _is_sockish(node: Optional[ast.AST]) -> bool:
    if node is None:
        return False
    t = terminal_name(node).lower()
    return any(h in t for h in _SOCK_HINT)


def _nonblocking(call: ast.Call) -> bool:
    if has_kwarg(call, "timeout"):
        return True
    for kw in call.keywords:
        if kw.arg == "block" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is False:
            return True
    for a in call.args:
        if isinstance(a, ast.Constant) and a.value is False:
            return True
    return False


class RT003UnboundedBlocking:
    code = "RT003"
    name = "unbounded-blocking-primitive"
    summary = ("Event.wait(), queue get/put, and socket reads inside "
               "control-plane `while` loops must carry a timeout")
    prefixes = ("ray_tpu/core/", "ray_tpu/serve/", "ray_tpu/train/",
                "ray_tpu/util/", "ray_tpu/data/",
                "ray_tpu/observability/")

    def applies(self, rel: str) -> bool:
        return rel.startswith(self.prefixes)

    def run(self, unit: FileUnit, project: Project) -> List[Finding]:
        out: List[Finding] = []

        # functions that arm a socket timeout anywhere are exempt from
        # the recv rule — their reads are already bounded
        def has_settimeout(fn: ast.AST) -> bool:
            return any(isinstance(n, ast.Call)
                       and call_attr(n) == "settimeout"
                       for n in ast.walk(fn))

        def scan_fn(fn, ctx: str):
            bounded_reads = has_settimeout(fn)
            seen = set()
            # own-body While loops only; nested defs scan on their own
            stack = list(fn.body)
            while stack:
                node = stack.pop()
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                if isinstance(node, ast.While):
                    for call in self._loop_calls(node):
                        if id(call) in seen:
                            continue
                        seen.add(id(call))
                        f = self._flag(call, bounded_reads)
                        if f:
                            out.append(Finding(
                                code=self.code, message=f,
                                path=unit.rel, line=call.lineno,
                                col=call.col_offset, context=ctx,
                                snippet=unit.line_text(call.lineno)))
                stack.extend(ast.iter_child_nodes(node))

        def walk(body, cls_name):
            for node in body:
                if isinstance(node, ast.ClassDef):
                    walk(node.body, node.name)
                elif isinstance(node, ast.AsyncFunctionDef):
                    walk(node.body, cls_name)   # exempt (see moduledoc)
                elif isinstance(node, ast.FunctionDef):
                    ctx = (f"{cls_name}.{node.name}" if cls_name
                           else node.name)
                    scan_fn(node, ctx)
                    walk(node.body, cls_name)

        walk(unit.tree.body, None)
        return out

    @staticmethod
    def _loop_calls(loop: ast.While):
        """Calls inside the loop body, not descending into nested
        function definitions (they run elsewhere)."""
        stack = list(loop.body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            if isinstance(node, ast.Call):
                yield node
            stack.extend(ast.iter_child_nodes(node))

    @staticmethod
    def _flag(call: ast.Call, bounded_reads: bool) -> Optional[str]:
        attr = call_attr(call)
        recv = receiver(call)
        if attr == "wait" and not call.args \
                and not has_kwarg(call, "timeout") and recv is not None:
            return (f"{dotted(call.func)}() with no timeout in a loop "
                    "— a dead setter parks this thread forever")
        if attr == "get" and _is_queueish(recv) \
                and not _nonblocking(call):
            return (f"timeout-less {dotted(call.func)}() in a loop — "
                    "a dead producer parks this thread forever")
        if not bounded_reads:
            if attr in ("recv", "recv_into") and _is_sockish(recv):
                return (f"{dotted(call.func)}() in a loop with no "
                        "settimeout anywhere in this function — a "
                        "half-open peer blocks the read forever")
            if isinstance(call.func, ast.Name) \
                    and call.func.id in _RECV_FUNCS:
                return (f"{call.func.id}() in a loop with no "
                        "settimeout anywhere in this function — a "
                        "half-open peer blocks the read forever")
        return None
